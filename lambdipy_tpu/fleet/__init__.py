"""Replica fleet: prefix-affinity router + health-driven replica pool.

The front door that multiplies the per-replica serve stack across N
supervised bundle servers — see pool.py (spawn/attach/probe/eject/
readmit/rolling drain), affinity.py (rendezvous hashing over leading
token blocks, matching the radix prefix cache), router.py (the HTTP
front-door with retry/hedge/spill/metrics-aggregation), breaker.py
(per-replica circuit breakers + the fleet-wide retry budget), spill.py
(the router-level overload parking lot built from the sched layer's
queue/policy pieces), and policy.py + controller.py (the elastic
control loop: pure decisions over the published signals, acted through
the pool/router's own safe primitives).
"""

from lambdipy_tpu.fleet.affinity import (
    DEFAULT_BLOCK,
    pick_replica,
    prefix_key,
    warm_prompt,
)
from lambdipy_tpu.fleet.affinity import ship_prompt
from lambdipy_tpu.fleet.breaker import CircuitBreaker, RetryBudget
from lambdipy_tpu.fleet.controller import FleetController
from lambdipy_tpu.fleet.policy import (
    Action,
    PolicyConfig,
    PolicyState,
    ReplicaView,
    Snapshot,
    decide,
)
from lambdipy_tpu.fleet.pool import (
    CLASSES,
    DECODE,
    DRAINING,
    EJECTED,
    MIXED,
    PREFILL,
    READY,
    STOPPED,
    FleetError,
    Replica,
    ReplicaPool,
    parse_attach_spec,
)
from lambdipy_tpu.fleet.router import FleetRouter
from lambdipy_tpu.fleet.spill import SpillQueue

__all__ = [
    "CLASSES",
    "DECODE",
    "DEFAULT_BLOCK",
    "DRAINING",
    "EJECTED",
    "MIXED",
    "PREFILL",
    "READY",
    "STOPPED",
    "Action",
    "CircuitBreaker",
    "FleetController",
    "FleetError",
    "FleetRouter",
    "PolicyConfig",
    "PolicyState",
    "Replica",
    "ReplicaPool",
    "ReplicaView",
    "RetryBudget",
    "Snapshot",
    "SpillQueue",
    "decide",
    "parse_attach_spec",
    "pick_replica",
    "prefix_key",
    "ship_prompt",
    "warm_prompt",
]
