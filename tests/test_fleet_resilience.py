"""Fleet-boundary resilience: circuit breakers, the fleet-wide retry
budget, the router spill queue, router-side network fault injection,
and first-class attached (unmanaged) replicas. All on scriptable stub
replicas — no device, no bundle boot — so the whole module stays in the
fast tier-1 budget; the live-fleet end-to-end matrix is
``bench.py --chaos-fleet`` (run_tier1.sh phase 8)."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from lambdipy_tpu.fleet import (
    EJECTED,
    READY,
    CircuitBreaker,
    FleetError,
    FleetRouter,
    ReplicaPool,
    RetryBudget,
    SpillQueue,
    affinity,
)
from lambdipy_tpu.fleet.breaker import CLOSED, HALF_OPEN, OPEN
from lambdipy_tpu.runtime.faults import FaultPlan
from lambdipy_tpu.sched.admission import Shed

from test_fleet import StubReplica, _get, _post


@pytest.fixture()
def stub_pair():
    s0, s1 = StubReplica("r0"), StubReplica("r1")
    pool = ReplicaPool(probe_interval=0.1, fail_threshold=1,
                      readmit_passes=2, probe_timeout=2.0)
    pool.attach("r0", s0.url)
    pool.attach("r1", s1.url)
    yield s0, s1, pool
    pool.close()
    for s in (s0, s1):
        try:
            s.kill()
        except Exception:
            pass


# -- circuit breaker state machine (pure, fake clock) ------------------------


def test_breaker_transitions_closed_open_half_open_closed():
    t = [100.0]
    b = CircuitBreaker(fail_threshold=3, open_s=1.0, clock=lambda: t[0])
    assert b.state == CLOSED and not b.blocked()
    b.record_failure()
    b.record_failure()
    assert b.state == CLOSED  # under threshold
    b.record_failure()
    assert b.state == OPEN and b.blocked() and b.opens == 1
    assert b.last_cause == "consecutive_failures"
    # the open interval must elapse before a probe is allowed
    t[0] += 0.5
    assert b.blocked()
    t[0] += 0.6
    assert not b.blocked()
    b.begin_attempt()  # the router picked it: half-open probe in flight
    assert b.state == HALF_OPEN and b.half_open_probes == 1
    assert b.blocked()  # a second pick must not double-probe
    b.record_success()
    assert b.state == CLOSED and b.closes == 1 and not b.blocked()
    # a success resets the consecutive count entirely
    b.record_failure()
    b.record_success()
    b.record_failure()
    b.record_failure()
    assert b.state == CLOSED


def test_breaker_half_open_failure_reopens_with_backoff():
    t = [0.0]
    b = CircuitBreaker(fail_threshold=1, open_s=1.0, max_open_s=3.0,
                       clock=lambda: t[0])
    b.record_failure()
    assert b.state == OPEN and b.open_until == pytest.approx(1.0)
    t[0] = 1.5
    b.begin_attempt()
    b.record_failure()  # the probe failed: reopen, interval doubled
    assert b.state == OPEN and b.opens == 2
    assert b.open_until == pytest.approx(1.5 + 2.0)
    assert b.last_cause == "half_open_probe_failed"
    t[0] = 4.0
    b.begin_attempt()
    b.record_failure()  # doubled again but capped at max_open_s
    assert b.open_until == pytest.approx(4.0 + 3.0)
    t[0] = 8.0
    b.begin_attempt()
    b.record_success()  # close resets the backoff ladder
    b.record_failure()
    assert b.open_until == pytest.approx(8.0 + 1.0)


def test_breaker_abandoned_half_open_probe_reclaims_after_grace():
    """Some router paths never resolve their forward (a 504
    busy-not-dead timeout, a streamed client that went away): an
    unresolved half-open probe must not blackhole the replica forever —
    after ``probe_grace_s`` the slot can be re-claimed, and the next
    resolved probe decides."""
    t = [0.0]
    b = CircuitBreaker(fail_threshold=1, open_s=1.0, probe_grace_s=5.0,
                       clock=lambda: t[0])
    b.record_failure()
    t[0] = 1.5
    b.begin_attempt()  # probe 1 claimed... and never resolved
    assert b.state == HALF_OPEN and b.blocked()
    t[0] = 4.0
    assert b.blocked()  # within grace: still one probe in flight
    t[0] = 7.0          # past 1.5 + 5.0: probe 1 is abandoned
    assert not b.blocked()
    b.begin_attempt()
    assert b.half_open_probes == 2
    assert b.blocked()  # probe 2 now owns the slot
    b.record_success()
    assert b.state == CLOSED and not b.blocked()


def test_breaker_latency_outlier_opens():
    t = [0.0]
    b = CircuitBreaker(fail_threshold=5, open_s=1.0, outlier_ms=100.0,
                       outlier_threshold=3, clock=lambda: t[0])
    for _ in range(2):
        b.record_success(latency_ms=500.0)
    assert b.state == CLOSED
    b.record_success(latency_ms=50.0)  # a fast answer resets the streak
    b.record_success(latency_ms=500.0)
    b.record_success(latency_ms=500.0)
    assert b.state == CLOSED
    b.record_success(latency_ms=500.0)
    assert b.state == OPEN and b.last_cause == "latency_outlier"


def test_retry_budget_ratio_floor_and_window():
    t = [0.0]
    rb = RetryBudget(ratio=0.5, min_retries=1, window_s=10.0,
                     clock=lambda: t[0])
    # floor: with zero primaries, exactly min_retries retries pass
    assert rb.allow_retry()
    assert not rb.allow_retry()
    assert rb.denied == 1
    # primaries buy more retries at the ratio
    for _ in range(4):
        rb.record_request()
    assert rb.allow_retry()      # budget = 1 + 0.5*4 = 3 > 1 used
    assert rb.allow_retry()
    assert not rb.allow_retry()  # 3 >= 3
    # the window slides: old entries stop counting against the budget
    t[0] = 11.0
    rb.record_request()
    assert rb.allow_retry()
    rep = rb.report()
    assert rep["window_primaries"] == 1 and rep["window_retries"] == 1
    assert rep["denied"] == 2


def test_retry_budget_disabled_ratio_zero():
    rb = RetryBudget(ratio=0.0, min_retries=0)
    assert all(rb.allow_retry() for _ in range(20))
    assert rb.denied == 0


# -- spill queue (pure) ------------------------------------------------------


def test_spill_queue_grants_in_policy_order_when_ready():
    ready = [False]
    q = SpillQueue(lambda: ready[0], capacity=8, max_wait_s=5.0,
                   poll_s=0.01, max_inflight=1).start()
    order = []

    def park(cls):
        out = q.park(cls=cls)
        assert not isinstance(out, Shed)
        order.append(cls)
        time.sleep(0.05)
        q.done(out)

    try:
        threads = [threading.Thread(target=park, args=("background",)),
                   threading.Thread(target=park, args=("interactive",))]
        threads[0].start()
        time.sleep(0.1)  # background parks first...
        threads[1].start()
        time.sleep(0.1)
        assert q.depth() == 2 and order == []  # nothing ready: all parked
        ready[0] = True
        for th in threads:
            th.join(timeout=5)
        # ...but the priority policy drains interactive first
        assert order == ["interactive", "background"]
        rep = q.report()
        assert rep["parked"] == 2 and rep["granted"] == 2
        assert rep["wait"]["count"] == 2
    finally:
        q.close()


def test_spill_queue_overflow_and_deadline_shed_with_estimate():
    q = SpillQueue(lambda: False, capacity=1, max_wait_s=0.3,
                   poll_s=0.01).start()
    try:
        results = []
        th = threading.Thread(
            target=lambda: results.append(q.park(cls="interactive")))
        th.start()
        time.sleep(0.1)
        # capacity 1 is taken: the second park overflows IMMEDIATELY,
        # priced with the queue's wait estimate
        out = q.park(cls="interactive")
        assert isinstance(out, Shed) and out.reason == "spill_overflow"
        assert out.code == 503 and out.retry_after_s > 0
        th.join(timeout=5)
        # the parked one expired at the deadline (never ready)
        assert isinstance(results[0], Shed)
        assert results[0].reason == "spill_deadline"
        assert results[0].retry_after_s > 0
        rep = q.report()
        assert rep["expired"] == 1 and rep["overflow"] == 1
        assert rep["depth"] == 0  # expired tickets leave the queue
    finally:
        q.close()


def test_spill_queue_respects_caller_wait_bound():
    q = SpillQueue(lambda: False, capacity=4, max_wait_s=30.0,
                   poll_s=0.01).start()
    try:
        t0 = time.monotonic()
        out = q.park(cls="interactive", wait_s=0.2)
        assert isinstance(out, Shed) and out.reason == "spill_deadline"
        assert time.monotonic() - t0 < 2.0
        assert isinstance(q.park(cls="interactive", wait_s=-1.0), Shed)
    finally:
        q.close()


# -- router: spill absorption ------------------------------------------------


def test_router_spill_absorbs_transient_fleet_wide_shed(stub_pair):
    """The tentpole claim: a transient fleet-wide shed burst completes
    with ZERO client-visible 429/503s when queue capacity suffices —
    the router parks the burst and drains it on recovery."""
    s0, s1, pool = stub_pair
    pool.probe_all()
    s0.cfg["shed"] = s1.cfg["shed"] = True
    router = FleetRouter(pool, affinity_on=False, max_retries=1,
                         backoff_s=0.01, backoff_cap_s=0.05,
                         spill_cap=16, spill_max_wait_s=10.0)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    results, errors = [], []

    def one(i):
        try:
            results.append(_post(f"{base}/invoke", {"tokens": [i]}))
        except Exception as e:  # noqa: BLE001 — collected for assert
            errors.append(repr(e))

    try:
        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # the burst is parked, not shed
        assert not errors and not results
        s0.cfg["shed"] = s1.cfg["shed"] = False  # fleet recovers
        for t in threads:
            t.join(timeout=15)
        assert not errors, f"client-visible errors: {errors[:3]}"
        assert len(results) == 4 and all(r["ok"] for r in results)
        rep = router.stats.report()
        assert rep["spill"]["spilled"] == 4
        assert rep["spill"]["drained"] >= 4
        assert rep["spill"]["expired"] == 0
        assert router.metrics()["router"]["spill"]["wait"]["count"] >= 4
    finally:
        router.stop()


def test_router_spill_deadline_sheds_with_wait_estimate(stub_pair):
    """Satellite: when the spill queue itself sheds, the response
    carries the queue's OWN wait estimate in the same wire format the
    server-side shed uses (integer Retry-After header + exact float
    retry_after_s in the body) — the shape the router's own
    ``_retry_after_s`` parses."""
    s0, s1, pool = stub_pair
    pool.probe_all()
    s0.cfg["shed"] = s1.cfg["shed"] = True  # and they never recover
    router = FleetRouter(pool, affinity_on=False, max_retries=1,
                         backoff_s=0.01, backoff_cap_s=0.05,
                         spill_cap=8, spill_max_wait_s=0.5)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/invoke", {"tokens": [1]})
        assert e.value.code == 503
        assert int(e.value.headers["Retry-After"]) >= 1
        body = json.loads(e.value.read())
        assert body["shed"] == "spill_deadline"
        assert body["retry_after_s"] > 0
        # the relayed format round-trips through the router's parser
        assert FleetRouter._retry_after_s(
            503, {}, json.dumps(body).encode()) == body["retry_after_s"]
        assert router.stats.report()["spill"]["expired"] == 1

        # the OpenAI surface sheds in the OpenAI error shape
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/v1/completions", {"prompt": [1]})
        err = json.loads(e.value.read())["error"]
        assert err["type"] == "overloaded_error"
        assert err["retry_after_s"] > 0
    finally:
        router.stop()


def test_router_spill_overflow_sheds_excess(stub_pair):
    """With the whole fleet EJECTED (nothing routable, nothing to grant
    onto), a burst past the queue capacity overflows immediately —
    bounded queue, explicit sheds — while the one parked request drains
    once a replica is revived and readmitted."""
    s0, s1, pool = stub_pair
    pool.start()
    port0 = s0.port
    s0.kill()
    s1.kill()
    pool.probe_all()
    assert all(r.state == EJECTED for r in pool.replicas.values())
    router = FleetRouter(pool, affinity_on=False, max_retries=0,
                         backoff_s=0.01, spill_cap=1,
                         spill_max_wait_s=15.0)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    outcomes = []
    s0b = None

    def one(i):
        try:
            outcomes.append(("ok", _post(f"{base}/invoke", {"tokens": [i]})))
        except urllib.error.HTTPError as e:
            outcomes.append(("shed", json.loads(e.read())))

    try:
        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # 1 parked; the others must have overflowed
        overflowed = [o for kind, o in outcomes if kind == "shed"]
        assert len(overflowed) == 2
        assert all(o["shed"] == "spill_overflow" and o["retry_after_s"] > 0
                   for o in overflowed)
        s0b = StubReplica("r0", port=port0)  # revive -> readmit -> drain
        for t in threads:
            t.join(timeout=15)
        served = [o for kind, o in outcomes if kind == "ok"]
        assert len(served) == 1 and served[0]["ok"]
        rep = router.stats.report()["spill"]
        assert rep["overflow"] == 2 and rep["spilled"] == 3
        assert rep["drained"] >= 1
    finally:
        router.stop()
        if s0b is not None:
            s0b.kill()


def test_router_streams_never_spill(stub_pair):
    """A parked stream would hold a socket open with nothing honest to
    send: streamed requests relay the fleet-wide shed immediately."""
    s0, s1, pool = stub_pair
    pool.probe_all()
    s0.cfg["shed"] = s1.cfg["shed"] = True
    router = FleetRouter(pool, affinity_on=False, max_retries=1,
                         backoff_s=0.01, backoff_cap_s=0.05,
                         spill_cap=8, spill_max_wait_s=30.0)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/invoke", {"tokens": [1], "stream": True})
        assert e.value.code == 503
        assert time.monotonic() - t0 < 5.0  # did not park for 30 s
        assert router.stats.report()["spill"]["spilled"] == 0
    finally:
        router.stop()


# -- router: retry budget ----------------------------------------------------


def test_retry_budget_exhaustion_under_fleet_wide_503(stub_pair):
    """Satellite: under a fleet-wide 503 storm, the budget stops the
    router from re-sending — each shed relays after ONE forward instead
    of max_retries+1, and the denial is counted."""
    s0, s1, pool = stub_pair
    pool.probe_all()
    s0.cfg["shed"] = s1.cfg["shed"] = True
    router = FleetRouter(pool, affinity_on=False, max_retries=3,
                         backoff_s=0.01, backoff_cap_s=0.05,
                         retry_budget=0.01, retry_budget_min=0)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        for i in range(3):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(f"{base}/invoke", {"tokens": [i]})
            assert e.value.code == 503  # the honest relayed shed
        rep = router.stats.report()
        assert rep["retry_budget_denied"] >= 3
        # the tiny ratio admits exactly one retry in the window; every
        # further re-send is refused — the fleet saw 4 forwards where
        # an unbudgeted max_retries=3 loop would have sent 12
        assert rep["retries"] == 1
        assert len(s0.bodies) + len(s1.bodies) == 4
        assert router.metrics()["router"]["retry_budget"]["denied"] >= 3
    finally:
        router.stop()


# -- router: circuit breakers ------------------------------------------------


def test_breaker_opens_on_dead_replica_and_half_open_readmits(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    # fail_threshold high: the POOL never ejects, isolating the breaker
    pool.fail_threshold = 100
    router = FleetRouter(pool, affinity_on=False, max_retries=2,
                         backoff_s=0.01, backoff_cap_s=0.05,
                         breaker_fails=2, breaker_open_s=0.4)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        port = s0.port
        s0.kill()
        # every request succeeds via failover; after 2 connect failures
        # the breaker opens and r0 stops being offered at all
        for i in range(6):
            assert _post(f"{base}/invoke", {"tokens": [i]})["ok"]
        b = router.breakers["r0"]
        assert b.state == OPEN and b.opens >= 1
        failovers_at_open = router.stats.report()["failovers"]
        for i in range(4):
            assert _post(f"{base}/invoke",
                         {"tokens": [i]})["replica"] == "r1"
        # open breaker = no further connection attempts at the corpse
        assert router.stats.report()["failovers"] == failovers_at_open

        # revive on the same port: after open_s the next pick half-open
        # probes it, success closes, and traffic returns
        s0b = StubReplica("r0", port=port)
        time.sleep(0.5)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and s0b.invokes == 0:
            _post(f"{base}/invoke", {"tokens": [9]})
            time.sleep(0.02)
        assert s0b.invokes >= 1, "traffic never returned to the revived " \
                                 "replica"
        assert b.state == CLOSED and b.closes >= 1
        assert b.half_open_probes >= 1
        rep = router.metrics()["router"]["breakers"]["r0"]
        assert rep["state"] == CLOSED
        s0b.kill()
    finally:
        router.stop()


# -- router-side network fault injection -------------------------------------


def test_fault_grammar_accepts_router_sites():
    plan = FaultPlan.from_spec(
        "route_connect:exception;route_body:exception@seg=2;"
        "route_latency:delay@ms=50;probe:exception@seg=3,n=6")
    assert len(plan.rules) == 4
    with pytest.raises(ValueError):
        FaultPlan.from_spec("route_nowhere:exception")


def test_injected_route_connect_drops_and_fails_over(stub_pair):
    """One injected drop: the request fails over to the other replica
    and still lands. (Two consecutive drops would exhaust a 2-replica
    fleet within one request — that shape is the spill tests' job.)"""
    s0, s1, pool = stub_pair
    pool.probe_all()
    plan = FaultPlan.from_spec("route_connect:exception@seg=1,n=1")
    router = FleetRouter(pool, affinity_on=False, max_retries=3,
                         backoff_s=0.01, backoff_cap_s=0.05, faults=plan)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        for i in range(4):
            assert _post(f"{base}/invoke", {"tokens": [i]})["ok"]
        rep = router.stats.report()
        assert rep["failovers"] >= 1 and rep["completed"] == 4
        assert plan.counts()["route_connect"] >= 4
    finally:
        router.stop()


def test_injected_route_latency_delays_but_delivers(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    plan = FaultPlan.from_spec("route_latency:delay@ms=200,n=1")
    router = FleetRouter(pool, affinity_on=False, faults=plan)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        t0 = time.monotonic()
        assert _post(f"{base}/invoke", {"tokens": [1]})["ok"]
        assert time.monotonic() - t0 >= 0.2
        assert router.stats.report()["failovers"] == 0
    finally:
        router.stop()


def test_injected_probe_fault_flaps_replica_through_pool(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()  # healthy baseline (counts on the EMPTY plan)
    # a fresh plan counts from zero: its calls 1-2 are the next sweep
    pool.faults = FaultPlan.from_spec("probe:exception@seg=1,n=2")
    pool.probe_all()  # plan calls 1-2: both probes fail -> both ejected
    assert {r.state for r in pool.replicas.values()} == {EJECTED}
    pool.probe_all()
    pool.probe_all()  # two clean passes -> readmitted
    assert all(r.state == READY for r in pool.replicas.values())
    assert all(r.ejections == 1 for r in pool.replicas.values())


# -- first-class attached replicas -------------------------------------------


def test_begin_drain_refuses_attached_replica(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    with pytest.raises(FleetError, match="attached.*probe-only"):
        pool.begin_drain("r0")
    assert pool.replicas["r0"].state == READY  # untouched


def test_rolling_restart_refuses_attach_only_pool(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    with pytest.raises(FleetError, match="attached"):
        pool.rolling_restart(live_floor=1)
    # not an AttributeError on the missing runtime, and nothing drained
    assert all(r.state == READY for r in pool.replicas.values())


def test_attached_replica_eject_readmit_zero_lost(stub_pair):
    """Attached replicas are first-class for health: kill one mid-
    traffic and every request still lands (failover), the corpse ejects
    at traffic speed, and the revived process readmits on consecutive
    probe passes — zero lost requests end to end."""
    s0, s1, pool = stub_pair
    pool.start()
    pool.probe_all()
    router = FleetRouter(pool, affinity_on=False, max_retries=3,
                         backoff_s=0.01, backoff_cap_s=0.1,
                         spill_cap=16, spill_max_wait_s=10.0)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    stop = threading.Event()
    ok = [0]
    failures = []

    def traffic():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                assert _post(f"{base}/invoke", {"tokens": [i % 7]})["ok"]
                ok[0] += 1
            except Exception as e:  # noqa: BLE001 — collected for assert
                failures.append(repr(e))
            time.sleep(0.02)

    threads = [threading.Thread(target=traffic) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.3)
        port = s0.port
        s0.kill()
        victim = pool.replicas["r0"]
        deadline = time.monotonic() + 10
        while victim.state != EJECTED and time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim.state == EJECTED
        time.sleep(0.3)  # traffic rides the survivor
        s0b = StubReplica("r0", port=port)
        deadline = time.monotonic() + 10
        while victim.state != READY and time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim.state == READY and victim.ejections == 1
        time.sleep(0.3)  # traffic over the healed fleet
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        router.stop()
        try:
            s0b.kill()
        except Exception:
            pass
    assert not failures, f"lost requests: {failures[:3]}"
    assert ok[0] > 10


# -- affinity-aware cache warming --------------------------------------------


def test_warm_prompt_extracts_whole_block_head():
    assert affinity.warm_prompt({"tokens": list(range(70))}, block=32) \
        == list(range(64))
    assert affinity.warm_prompt({"tokens": [1, 2, 3]}, block=32) is None
    assert affinity.warm_prompt({"prompt": "x" * 300}, block=32) \
        == "x" * 256
    # explicit prefix is part of the replayable head
    assert affinity.warm_prompt(
        {"prefix": list(range(32)), "tokens": [1] * 32}, block=32) \
        == list(range(32)) + [1] * 32
    assert affinity.warm_prompt({"n": 3}) is None


def test_readmitted_replica_gets_warmed_with_its_hot_prefixes(stub_pair):
    s0, s1, pool = stub_pair
    pool.start()
    pool.probe_all()
    router = FleetRouter(pool, affinity_on=True, block=4, max_retries=3,
                         backoff_s=0.01, backoff_cap_s=0.1,
                         warm_prefixes=4)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    stubs = {"r0": s0, "r1": s1}
    try:
        # one hot prefix, hammered: the router tracks it
        head = list(range(100, 112))  # 3 whole 4-token blocks
        for i in range(5):
            _post(f"{base}/invoke", {"tokens": head + [i]})
        key = affinity.prefix_key({"tokens": head + [0]}, block=4)
        target = affinity.pick_replica(key, sorted(pool.replicas))
        victim = pool.replicas[target]
        port = stubs[target].port
        stubs[target].kill()
        deadline = time.monotonic() + 10
        while victim.state != EJECTED and time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim.state == EJECTED
        revived = StubReplica(target, port=port)
        deadline = time.monotonic() + 10
        while victim.state != READY and time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim.state == READY
        # the warm request lands on the revived replica: its hot-prefix
        # head as a background-class 1-token completion
        deadline = time.monotonic() + 10
        warm = None
        while warm is None and time.monotonic() < deadline:
            warm = next((b for p, b in revived.bodies
                         if p == "/v1/completions"
                         and b.get("max_tokens") == 1), None)
            time.sleep(0.05)
        assert warm is not None, "readmitted replica never got a warm " \
                                 "request"
        assert warm["prompt"] == head and warm["temperature"] == 0
        assert router.stats.report()["warmed_prefixes"] >= 1
        revived.kill()
    finally:
        router.stop()


def test_router_healthz_reports_spill_depth(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    router = FleetRouter(pool, affinity_on=False, spill_cap=4)
    router.start_background()
    try:
        h = _get(f"http://127.0.0.1:{router.port}/healthz")
        assert h["ok"] and h["spill_depth"] == 0
    finally:
        router.stop()
