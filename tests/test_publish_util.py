"""The measurement scripts' shared BASELINE.json writer
(``scripts/publish_util.py``): merge semantics and atomicity.

Every behavior here was a real round-5 incident first: a config-level
refresh wiped the speculative sub-record, a one-level merge attaching a
methodology note replaced the kv_int8 sub-record and dropped its
published error bound, and a micro-exemplar record arriving over the
real-8B config mislabeled 8B data.
"""

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "scripts"))

import publish_util  # noqa: E402


def _write(tmp_path, doc):
    p = tmp_path / "BASELINE.json"
    p.write_text(json.dumps(doc))
    return p


def _read(p):
    return json.loads(p.read_text())


def test_merge_preserves_sibling_sub_records(tmp_path):
    p = _write(tmp_path, {"published": {"config5": {
        "recipe": publish_util.RECIPE_8B,
        "b1_decode_tok_s": 86.7,
        "speculative": {"spec_tok_s": 204.0}}}})
    publish_util.merge_publish({"config5": {"b1_decode_tok_s": 90.0}}, p)
    c5 = _read(p)["published"]["config5"]
    assert c5["b1_decode_tok_s"] == 90.0
    assert c5["speculative"]["spec_tok_s"] == 204.0


def test_merge_is_deep_for_nested_sub_records(tmp_path):
    # attaching a note must not replace the sub-record wholesale
    p = _write(tmp_path, {"published": {"config5": {"kv_int8": {
        "greedy_agreement": "64/64", "max_logprob_delta": 0.0283}}}})
    publish_util.merge_publish(
        {"config5": {"kv_int8": {"methodology_note": "flagged"}}}, p)
    kv = _read(p)["published"]["config5"]["kv_int8"]
    assert kv["greedy_agreement"] == "64/64"
    assert kv["max_logprob_delta"] == 0.0283
    assert kv["methodology_note"] == "flagged"


def test_micro_record_routes_to_config5_micro_over_8b(tmp_path):
    p = _write(tmp_path, {"published": {"config5": {
        "recipe": publish_util.RECIPE_8B,
        "speculative": {"spec_tok_s": 204.0}}}})
    publish_util.merge_publish({"config5": {
        "recipe": publish_util.MICRO_RECIPE, "p50_ms": 3.2}}, p)
    pub = _read(p)["published"]
    assert pub["config5_micro"]["p50_ms"] == 3.2
    assert pub["config5"]["speculative"]["spec_tok_s"] == 204.0


def test_micro_record_lands_in_config5_when_no_8b_record(tmp_path):
    p = _write(tmp_path, {"published": {}})
    publish_util.merge_publish({"config5": {
        "recipe": publish_util.MICRO_RECIPE, "p50_ms": 3.2}}, p)
    assert _read(p)["published"]["config5"]["p50_ms"] == 3.2


def test_write_doc_leaves_no_tmp_file(tmp_path):
    p = _write(tmp_path, {"published": {}})
    publish_util.merge_publish({"config1": {"ok": 1}}, p)
    assert _read(p)["published"]["config1"] == {"ok": 1}
    assert list(tmp_path.glob("*.tmp")) == []


def test_non_dict_existing_value_is_replaced(tmp_path):
    p = _write(tmp_path, {"published": {"config2": "legacy-string"}})
    publish_util.merge_publish({"config2": {"p50_ms": 1.0}}, p)
    assert _read(p)["published"]["config2"] == {"p50_ms": 1.0}
