"""AOT executable store: ship compiled programs inside the bundle.

Cold start on TPU is interpreter + PJRT init + trace/lower/compile
(BASELINE.md: ~10 s floor; SURVEY.md §9.6 names AOT as the make-or-break
weapon). The persistent compile cache (loader.attach_compile_cache) already
turns XLA *compilation* into a disk hit, but tracing + lowering a real
model is still seconds of Python. This module removes that too, with two
tiers stored under ``<bundle>/aot/``:

- **tier 2 — serialized executable** (``*.exec``): the PJRT-compiled
  program via ``jax.experimental.serialize_executable``. Zero trace, zero
  lower, zero compile at boot. Only valid for the exact (platform, jax,
  jaxlib) that produced it — the key encodes all three, and loading is
  best-effort (some PJRT plugins don't support executable serialization).
- **tier 1 — jax.export StableHLO** (``*.hlo``): portable serialized
  module. Boot skips tracing/lowering; the compile that remains is a
  persistent-cache hit because the builder warmed it.

Misses fall through to plain ``jax.jit`` and (best-effort) write both
artifacts so the *next* boot — or the built bundle, when the builder's
warm subprocess does this — is fast. The reference has no analog: its
"AOT" is shipping pre-built wheels (SURVEY.md §1); this is the same idea
one level down, at the XLA-program level.
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import time
from pathlib import Path
from typing import Any, Callable, Sequence

from lambdipy_tpu.utils.fsutil import atomic_write_bytes, atomic_write_text
from lambdipy_tpu.utils.logs import get_logger

log = get_logger("lambdipy.aot")

_SCHEMA = 1

# Latency gate for loaded AOT artifacts: a deserialized executable can run
# yet be pathologically slow (measured on the axon PJRT tunnel: ~3 s/call
# for a forward that plain jit serves in 0.2 ms — every call re-crossed the
# tunnel). A tier whose steady-state probe call exceeds this is rejected
# and the boot falls back to jit + the bundle's warm persistent cache.
_MAX_CALL_MS = float(os.environ.get("LAMBDIPY_AOT_MAX_CALL_MS", "500"))


def _mesh_sig(mesh) -> str | None:
    if mesh is None:
        return None
    return "x".join(f"{a}{mesh.shape[a]}" for a in mesh.axis_names)


def _env_key(mesh=None) -> dict:
    import jax
    import jaxlib

    return {
        "schema": _SCHEMA,
        "platform": jax.default_backend(),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "n_devices": len(jax.devices()),
        "mesh": _mesh_sig(mesh),
    }


class AotStore:
    """Directory of AOT artifacts for one bundle, keyed by entry name and
    the producing environment — including the payload's mesh shape, so a
    multi-device program warmed on one topology is never replayed on
    another (VERDICT r2 missing #4: meshed payloads re-traced every boot)."""

    def __init__(self, bundle_dir: Path, mesh=None,
                 gate_ms: float | None = None):
        self.dir = Path(bundle_dir) / "aot"
        self.mesh = mesh
        # per-store latency gate: the default suits sub-ms forward
        # programs; callers AOT-ing programs whose honest steady-state
        # call is long (a 64-token 8B decode runs ~700 ms) pass a gate
        # sized to that work, keeping the gate's actual target — a tier
        # that re-crosses the transport every call — detectable
        self.gate_ms = _MAX_CALL_MS if gate_ms is None else float(gate_ms)
        self.rejected_slow = False  # set when a tier loaded but failed the gate
        # set when a matching meta existed but produced no usable tier —
        # the signal that re-saving would just reproduce the same artifacts
        self.exhausted = False
        # artifacts deserialized ahead of time by preload(): name ->
        # (callable, tier). load() consumes these instead of re-reading
        # the tier file, and still probes them.
        self._preloaded: dict[str, tuple] = {}

    def _mesh_ctx(self):
        """Trace/compile/probe under the payload mesh (models read it for
        sharding hints and backend selection)."""
        if self.mesh is None:
            return contextlib.nullcontext()
        from lambdipy_tpu.parallel.mesh import use_mesh

        return use_mesh(self.mesh)

    def _paths(self, name: str) -> dict[str, Path]:
        import jax

        stem = f"{name}.{jax.default_backend()}"
        sig = _mesh_sig(self.mesh)
        if sig:
            stem += f".{sig}"
        return {
            "meta": self.dir / f"{stem}.json",
            "hlo": self.dir / f"{stem}.hlo",
            "exec": self.dir / f"{stem}.exec",
        }

    # -- save ---------------------------------------------------------------

    def save(self, name: str, fn: Callable,
             example_args: Sequence[Any]) -> tuple[dict, Callable]:
        """Export ``fn`` at ``example_args``'s shapes; write tier 1 always,
        tier 2 when the backend supports executable serialization.

        Returns ``(meta, jitted)`` — the same ``jax.jit`` object the export
        used, so a miss path can serve from it instead of re-tracing.
        Artifact writes are atomic and the meta (which declares the tiers)
        lands last: a crash mid-save leaves no meta, never a meta pointing
        at a torn tier file.
        """
        import jax
        import jax.export  # 0.4.x: submodule is not auto-imported

        self.dir.mkdir(parents=True, exist_ok=True)
        paths = self._paths(name)
        meta = _env_key(self.mesh)
        meta["tiers"] = []

        with self._mesh_ctx():
            jitted = jax.jit(fn)
            # plain call FIRST: this is the compile that flows through the
            # persistent-cache writer. A manual lower().compile()
            # pre-populates the jit dispatch cache WITHOUT writing the
            # persistent cache (observed: bundles warmed compile-last
            # shipped caches missing their own forward program), so order
            # matters here.
            jax.block_until_ready(jitted(*example_args))

            try:
                exported = jax.export.export(jitted)(*example_args)
                atomic_write_bytes(paths["hlo"], bytes(exported.serialize()))
                meta["tiers"].append("hlo")
                # warm the hlo-tier boot path too: the round-tripped module
                # hashes differently from the original jit, so compile it
                # once here to put ITS cache entry in the bundle
                jax.block_until_ready(jax.jit(exported.call)(*example_args))
            except Exception as e:
                log.warning("aot %s: jax.export failed: %s", name, e)

            # exec tier is single-chip only: a serialized multi-device
            # executable binds to concrete device ids; the hlo tier + warm
            # cache is the meshed cold-start path
            if self.mesh is None:
                try:
                    from jax.experimental import serialize_executable

                    compiled = jitted.lower(*example_args).compile()
                    payload = serialize_executable.serialize(compiled)
                    atomic_write_bytes(paths["exec"], pickle.dumps(payload))
                    meta["tiers"].append("exec")
                except Exception as e:
                    log.info("aot %s: executable serialization unavailable: %s",
                             name, e)

        if meta["tiers"]:
            atomic_write_text(paths["meta"], json.dumps(meta, indent=1))
        return meta, jitted

    def save_from_jitted(self, name: str, jitted: Callable,
                         example_args: Sequence[Any],
                         exec_only: bool = False) -> dict:
        """Export an ALREADY-warmED ``jax.jit`` object's program (the
        caller has invoked it at ``example_args``' shapes, so its compile
        is done and cached in-session). Used by the serving path to
        snapshot its compiled programs after warmup without paying the
        extra trace+compile that :meth:`save`'s fresh ``jax.jit`` would.

        ``exec_only`` skips the hlo tier (and its round-trip cache warm)
        when the caller knows only the executable tier can win.
        """
        import jax
        import jax.export  # 0.4.x: submodule is not auto-imported

        self.dir.mkdir(parents=True, exist_ok=True)
        paths = self._paths(name)
        meta = _env_key(self.mesh)
        meta["tiers"] = []
        with self._mesh_ctx():
            if self.mesh is None:
                try:
                    from jax.experimental import serialize_executable

                    # in-session this re-lower/compile is a compilation-
                    # cache hit, not a fresh compile — the caller already
                    # ran the program at these shapes
                    compiled = jitted.lower(*example_args).compile()
                    payload = serialize_executable.serialize(compiled)
                    atomic_write_bytes(paths["exec"], pickle.dumps(payload))
                    # self-test NOW (a deserialize + one call, seconds):
                    # on some platforms (observed: multi-device CPU) a
                    # serialized single-device executable cannot load
                    # back; shipping it would make every boot pay the
                    # failed attempt, and the skipped hlo warm below
                    # would leave the real fallback cold
                    fn = self._load_tier("exec", paths)
                    jax.device_get(fn(*example_args))
                    meta["tiers"].append("exec")
                except Exception as e:
                    paths["exec"].unlink(missing_ok=True)
                    log.info("aot %s: executable tier unavailable: %s",
                             name, e)
            if not exec_only:
                try:
                    exported = jax.export.export(jitted)(*example_args)
                    atomic_write_bytes(paths["hlo"],
                                       bytes(exported.serialize()))
                    # exec is probed first at load, so "hlo" goes last
                    meta["tiers"].append("hlo")
                    if "exec" not in meta["tiers"]:
                        # platforms that will actually BOOT from the hlo
                        # tier need its round-tripped module warmed into
                        # the persistent cache (same reasoning as
                        # save()); exec-capable platforms never probe it,
                        # so skip the extra compile there
                        jax.block_until_ready(
                            jax.jit(exported.call)(*example_args))
                except Exception as e:
                    log.warning("aot %s: jax.export failed: %s", name, e)
        if meta["tiers"]:
            atomic_write_text(paths["meta"], json.dumps(meta, indent=1))
        return meta

    def prune_slow_tiers(self, name: str, example_args: Sequence[Any]) -> list[str]:
        """Build-time self-test: load each just-saved tier on THIS platform
        and delete any that fail the latency gate, so the serve boot never
        pays a slow probe for a tier that can't win (e.g. the exec tier on
        the axon tunnel). Returns the pruned tier names."""
        import jax

        paths = self._paths(name)
        if not paths["meta"].is_file():
            return []
        try:
            meta = json.loads(paths["meta"].read_text())
        except Exception:
            return []
        pruned = []
        for tier in list(meta.get("tiers", ())):
            try:
                with self._mesh_ctx():
                    fn = self._load_tier(tier, paths)
                    if fn is None:
                        continue
                    # device_get, not block_until_ready: only a host fetch
                    # observes real completion through the remote tunnel
                    # (see _probe in load())
                    t0 = time.monotonic()
                    jax.device_get(fn(*example_args))
                    first_ms = (time.monotonic() - t0) * 1000.0
                    t0 = time.monotonic()
                    jax.device_get(fn(*example_args))
                    ms = (time.monotonic() - t0) * 1000.0
                if ms > self.gate_ms:
                    log.warning(
                        "aot %s: pruning %s tier (steady %.0fms, first %.0fms, "
                        "gate %.0fms)", name, tier, ms, first_ms, self.gate_ms)
                    meta["tiers"].remove(tier)
                    paths[tier].unlink(missing_ok=True)
                    pruned.append(tier)
            except Exception as e:
                log.warning("aot %s: pruning %s tier (failed self-test: %s)",
                            name, tier, e)
                meta["tiers"].remove(tier)
                paths[tier].unlink(missing_ok=True)
                pruned.append(tier)
        if pruned:
            # keep the meta even when no tiers survive: it records "tried
            # and pruned on this platform", which stops every subsequent
            # boot from re-exporting/re-probing the same losing artifacts
            atomic_write_text(paths["meta"], json.dumps(meta, indent=1))
        return pruned

    def has(self, name: str) -> bool:
        """Cheap existence check (one stat) so callers can skip building
        probe operands for artifacts that were never saved."""
        return self._paths(name)["meta"].is_file()

    def preload(self, prefix: str = "srv-") -> dict:
        """Deserialize (and device-load) every matching artifact's best
        tier WITHOUT probing. Executable deserialization + the remote
        program load need NO operands — the model weights don't have to
        be resident — so a boot overlaps this with the weight upload
        instead of paying programs-after-weights serially (VERDICT r5
        #5: at 8B through the tunnel the two phases were 54.6 s + 220 s
        back to back). ``load()`` later consumes the preloaded callable
        and runs its usual probe at first invoke, when params exist.

        Returns ``{"names": [...], "seconds": s}`` for the boot
        decomposition. Failures are per-artifact and silent — a broken
        artifact just falls back to load()'s normal path."""
        import jax

        t0 = time.monotonic()
        out: list[str] = []
        if not self.dir.is_dir():
            return {"names": out, "seconds": 0.0}
        sig = _mesh_sig(self.mesh)
        suffix = f".{jax.default_backend()}" + (f".{sig}" if sig else "")
        env = _env_key(self.mesh)
        for meta_path in sorted(self.dir.glob(f"{prefix}*{suffix}.json")):
            name = meta_path.name[: -len(suffix + ".json")]
            try:
                meta = json.loads(meta_path.read_text())
            except Exception:
                continue
            if any(meta.get(k) != env[k]
                   for k in ("schema", "platform", "jax", "jaxlib",
                             "n_devices", "mesh")):
                continue
            paths = self._paths(name)
            for tier in ("exec", "hlo"):
                if tier not in meta.get("tiers", ()):
                    continue
                try:
                    with self._mesh_ctx():
                        fn = self._load_tier(tier, paths)
                except Exception:
                    continue
                if fn is not None:
                    self._preloaded[name] = (fn, tier)
                    out.append(name)
                    break
        return {"names": out, "seconds": round(time.monotonic() - t0, 3)}

    def _load_tier(self, tier: str, paths: dict):
        """Deserialize one tier into a callable (no probing/gating)."""
        import jax
        import jax.export  # 0.4.x: submodule is not auto-imported

        if tier == "exec" and paths["exec"].is_file():
            from jax.experimental import serialize_executable

            payload = pickle.loads(paths["exec"].read_bytes())
            return serialize_executable.deserialize_and_load(*payload)
        if tier == "hlo" and paths["hlo"].is_file():
            exported = jax.export.deserialize(bytearray(paths["hlo"].read_bytes()))
            return jax.jit(exported.call)
        return None

    # -- load ---------------------------------------------------------------

    def load(self, name: str,
             example_args: Sequence[Any] | None = None) -> tuple[Callable, str] | None:
        """Return ``(callable, tier)`` for the best available artifact
        matching the current environment, or None.

        When ``example_args`` is given each candidate tier is probe-invoked
        before being returned — an AOT executable can deserialize fine yet
        fail at call time (observed: XLA:CPU AOT rejects a host whose CPU
        features differ from the compile machine), or run but be unusably
        slow (observed on the axon tunnel; see _MAX_CALL_MS). The first
        probe call doubles as the warmup invoke; the gate times a second,
        steady-state call.
        """
        paths = self._paths(name)
        if not paths["meta"].is_file():
            return None
        try:
            meta = json.loads(paths["meta"].read_text())
        except Exception:
            return None
        env = _env_key(self.mesh)
        if any(meta.get(k) != env[k]
               for k in ("schema", "platform", "jax", "jaxlib", "n_devices",
                         "mesh")):
            log.info("aot %s: environment mismatch (%s vs %s), ignoring",
                     name, meta, env)
            return None

        def _probe(fn: Callable, tier: str) -> bool:
            """Correctness + latency gate. Raises on breakage; returns
            False (and marks rejected_slow) on a gate failure.

            Timing uses ``jax.device_get`` of the result, not
            ``block_until_ready``: through the axon remote tunnel
            block_until_ready returns at submission (~0.03 ms) while the
            remote execution is still in flight — only a host fetch
            observes real completion. The gate is on the SECOND
            (steady-state) call: the first call of any tier legitimately
            pays one-time remote program load (~4 s measured for the exec
            tier) or remote compile, and doubles as the warmup. A tier
            whose steady call re-crosses the tunnel every time (~3 s/call,
            the failure this gate exists for) still fails."""
            if example_args is None:
                return True
            import jax

            t0 = time.monotonic()
            jax.device_get(fn(*example_args))
            first_ms = (time.monotonic() - t0) * 1000.0
            t0 = time.monotonic()
            jax.device_get(fn(*example_args))
            ms = (time.monotonic() - t0) * 1000.0
            if ms > self.gate_ms:
                self.rejected_slow = True
                log.warning(
                    "aot %s: %s tier steady call %.0fms (first %.0fms) "
                    "exceeds gate %.0fms; rejecting (plain jit + warm "
                    "cache will serve)", name, tier, ms, first_ms,
                    self.gate_ms)
                return False
            if first_ms > self.gate_ms:
                log.info("aot %s: %s tier first call %.0fms (one-time "
                         "program load), steady %.0fms", name, tier,
                         first_ms, ms)
            return True

        pre = self._preloaded.pop(name, None)
        tried = None
        if pre is not None and pre[1] in meta.get("tiers", ()):
            # deserialized ahead of time (preload(), overlapped with the
            # weight upload); only the probe remains
            fn, tried = pre
            try:
                with self._mesh_ctx():
                    if _probe(fn, tried):
                        return fn, tried
            except Exception as e:
                log.warning("aot %s: preloaded %s tier failed probe: %s",
                            name, tried, e)
        for tier in ("exec", "hlo"):
            if tier == tried or tier not in meta.get("tiers", ()):
                continue
            try:
                with self._mesh_ctx():
                    fn = self._load_tier(tier, paths)
                    if fn is not None and _probe(fn, tier):
                        return fn, tier
            except Exception as e:
                log.warning("aot %s: %s tier failed to load: %s", name, tier, e)
        self.exhausted = True  # meta matched this env; nothing usable in it
        return None


def cached_jit(ctx, name: str, fn: Callable, example_args: Sequence[Any],
               mesh=None) -> tuple[Callable, str]:
    """The handler-facing entry: AOT artifact if present, else ``jax.jit``
    plus a best-effort save so the next boot skips trace/lower/compile.

    ``ctx`` is a HandlerContext (anything with ``bundle_dir``). Artifacts
    are keyed by device count AND mesh shape — a meshed payload (``mesh``
    given) saves/loads the StableHLO tier under its (topology, mesh)
    signature, so a multi-device boot skips tracing once any boot on the
    same topology has run; the device-bound exec tier stays single-chip
    only. The returned callable is shape-specialized to ``example_args``
    on a hit; handlers keep a plain-jit fallback for other shapes. Returns
    ``(callable, source)``, source in {"exec", "hlo", "jit"}.
    """
    import jax

    store = AotStore(ctx.bundle_dir, mesh=mesh)
    hit = store.load(name, example_args)
    if hit is not None:
        return hit
    if store.exhausted or store.rejected_slow:
        # a matching meta already records that this platform's artifacts
        # don't work (or are slower than the gate) — re-saving would just
        # reproduce them; serve from jit, whose compile is a hit in the
        # bundle's warm persistent cache
        return jax.jit(fn), "jit"
    try:
        _, jitted = store.save(name, fn, example_args)
        store.prune_slow_tiers(name, example_args)
        return jitted, "jit"
    except Exception as e:  # bundle dir read-only, export unsupported, ...
        log.info("aot %s: save skipped: %s", name, e)
    return jax.jit(fn), "jit"
