"""Release store / prebuilt-fetch path tests (SURVEY.md §3.1 #4/#8/#9).

Covers the maintainer publish -> user fetch loop that defines the
reference's UX: deterministic packing, hardened extraction, the release
index (find/list, token-protected uploads), hash-verified caching, and the
CLI wiring (publish / fetch / releases / build --release-store).
"""

import importlib.util
import json
import sys
import tarfile

import pytest
from click.testing import CliRunner

from lambdipy_tpu.cli import main

# the CLI resolves prebuilt assets against the RUNNING interpreter's
# version — tests exercising that path must not hardcode one
PYVER = f"{sys.version_info.major}.{sys.version_info.minor}"
PYTAG = "py" + PYVER.replace(".", "")
from lambdipy_tpu.resolve.registry import ArtifactRegistry
from lambdipy_tpu.resolve.releases import (
    ReleaseError,
    ReleaseFetcher,
    ReleaseStore,
    pack_bundle,
    unpack_archive,
)


@pytest.fixture()
def bundle_dir(tmp_path):
    d = tmp_path / "bundle"
    (d / "site" / "pkg").mkdir(parents=True)
    (d / "site" / "pkg" / "__init__.py").write_text("VALUE = 42\n")
    (d / "handler.py").write_text("def handler(req): return req\n")
    (d / "manifest.json").write_text(json.dumps({"artifact_id": "demo-1"}))
    return d


def test_pack_is_deterministic(bundle_dir, tmp_path):
    a = pack_bundle(bundle_dir, tmp_path / "a.tar.gz")
    b = pack_bundle(bundle_dir, tmp_path / "b.tar.gz")
    assert a.read_bytes() == b.read_bytes()


def test_pack_unpack_roundtrip(bundle_dir, tmp_path):
    archive = pack_bundle(bundle_dir, tmp_path / "x.tar.gz")
    out = unpack_archive(archive, tmp_path / "out")
    assert (out / "site" / "pkg" / "__init__.py").read_text() == "VALUE = 42\n"
    assert (out / "handler.py").exists() and (out / "manifest.json").exists()


def test_unpack_rejects_path_escape(tmp_path):
    evil = tmp_path / "evil.tar.gz"
    with tarfile.open(evil, "w:gz") as tar:
        info = tarfile.TarInfo("../escape.txt")
        info.size = 0
        tar.addfile(info)
    with pytest.raises(ReleaseError, match="unsafe archive member"):
        unpack_archive(evil, tmp_path / "out")


def test_unpack_rejects_symlink_escape(tmp_path):
    evil = tmp_path / "evil.tar.gz"
    with tarfile.open(evil, "w:gz") as tar:
        info = tarfile.TarInfo("link")
        info.type = tarfile.SYMTYPE
        info.linkname = "../../outside"
        tar.addfile(info)
    with pytest.raises(ReleaseError, match="unsafe link"):
        unpack_archive(evil, tmp_path / "out")


def test_pack_preserves_dir_symlinks_and_empty_dirs(bundle_dir, tmp_path):
    (bundle_dir / "pkg-link").symlink_to("site/pkg", target_is_directory=True)
    (bundle_dir / "empty").mkdir()
    archive = pack_bundle(bundle_dir, tmp_path / "x.tar.gz")
    out = unpack_archive(archive, tmp_path / "out")
    assert (out / "pkg-link").is_symlink()
    assert (out / "pkg-link" / "__init__.py").exists()
    assert (out / "empty").is_dir()


def test_asset_rejects_unsafe_index_fields():
    from lambdipy_tpu.resolve.releases import Asset

    with pytest.raises(ReleaseError, match="unsafe asset"):
        Asset(name="x.tar.gz", tag="v1", size=1, hash="sha256:0",
              artifact_id="../../escape", recipe="demo", version="0.1",
              python="3.12", device="any", uploaded=0.0)


@pytest.fixture()
def store_with_asset(bundle_dir, tmp_path):
    store = ReleaseStore.create(tmp_path / "store")
    archive = pack_bundle(bundle_dir, tmp_path / "demo.tar.gz")
    asset = store.upload_asset(
        "v1", archive, artifact_id="demo-0.1-py312-any", recipe="demo",
        version="0.1", python="3.12", device="any")
    return store, asset


def test_store_index_and_find(store_with_asset):
    store, asset = store_with_asset
    assert store.list_releases() == ["v1"]
    assert [a.name for a in store.list_assets()] == [asset.name]
    found = store.find_asset(recipe="demo", python="3.12", device="cpu")
    assert found is not None and found.hash == asset.hash  # "any" matches cpu
    assert store.find_asset(recipe="demo", python="3.11") is None
    assert store.find_asset(recipe="demo", python="3.12", version="9.9") is None


def test_find_prefers_newest(store_with_asset, bundle_dir, tmp_path):
    store, _ = store_with_asset
    (bundle_dir / "extra.txt").write_text("v2 content\n")
    archive = pack_bundle(bundle_dir, tmp_path / "demo2.tar.gz")
    newer = store.upload_asset(
        "v2", archive, artifact_id="demo-0.2-py312-any", recipe="demo",
        version="0.2", python="3.12", device="any")
    found = store.find_asset(recipe="demo", python="3.12")
    assert found.artifact_id == newer.artifact_id


def test_protected_store_requires_token(bundle_dir, tmp_path, monkeypatch):
    monkeypatch.delenv("LAMBDIPY_RELEASE_TOKEN", raising=False)
    store = ReleaseStore.create(tmp_path / "store", protected=True)
    archive = pack_bundle(bundle_dir, tmp_path / "demo.tar.gz")
    with pytest.raises(ReleaseError, match="protected"):
        store.upload_asset("v1", archive, artifact_id="a", recipe="demo",
                           version="0.1", python="3.12", device="any")
    # token via env unlocks uploads; reads never need one
    monkeypatch.setenv("LAMBDIPY_RELEASE_TOKEN", "tok")
    authed = ReleaseStore(store.root)
    authed.upload_asset("v1", archive, artifact_id="a", recipe="demo",
                        version="0.1", python="3.12", device="any")
    assert ReleaseStore(store.root, token=None).list_assets()


def test_fetch_verifies_and_caches(store_with_asset, tmp_path):
    store, asset = store_with_asset
    fetcher = ReleaseFetcher(store, cache_dir=tmp_path / "cache")
    cached = fetcher.fetch(asset)
    assert cached.exists()
    # cache hit: the store copy can disappear and fetch still succeeds
    store.asset_path(asset).unlink()
    assert fetcher.fetch(asset) == cached


def test_fetch_rejects_tampered_asset(store_with_asset, tmp_path):
    store, asset = store_with_asset
    path = store.asset_path(asset)
    path.write_bytes(path.read_bytes() + b"tampered")
    fetcher = ReleaseFetcher(store, cache_dir=tmp_path / "cache")
    with pytest.raises(ReleaseError, match="failed verification"):
        fetcher.fetch(asset)


def test_fetch_into_registry(store_with_asset, tmp_path):
    store, asset = store_with_asset
    registry = ArtifactRegistry(tmp_path / "registry")
    fetcher = ReleaseFetcher(store, cache_dir=tmp_path / "cache")
    bundle = fetcher.fetch_into_registry(asset, registry)
    assert registry.has(asset.artifact_id)
    assert (bundle / "handler.py").exists()


def _has_pep517_build() -> bool:
    """True only when the PEP-517 'build' PACKAGE is importable. A bare
    ``find_spec("build") is not None`` check is wrong here: a stray
    ``build/`` output directory on sys.path (the default sdist/wheel
    output location!) resolves as a NAMESPACE package — a spec with
    ``origin=None`` — and the test would then run and die on import
    instead of skipping."""
    try:
        spec = importlib.util.find_spec("build")
    except (ImportError, ValueError):
        return False
    return spec is not None and spec.origin is not None


@pytest.mark.skipif(
    not _has_pep517_build(),
    reason="environment-bound: publishing certifi builds its sdist via the "
           "PEP-517 'build' package, which is not importable here "
           "(install with `pip install build` where the environment "
           "allows it); the prebuilt-asset halves of the loop are "
           "covered by the two tests below")
def test_cli_publish_fetch_loop(tmp_path):
    """End-to-end over the CLI: maintainer publishes certifi, a fresh user
    registry fetches it prebuilt, and `build --release-store` prefers the
    prebuilt asset over a local build."""
    runner = CliRunner()
    store_dir = str(tmp_path / "store")
    maint_reg = str(tmp_path / "maintainer-registry")
    r = runner.invoke(main, ["publish", "certifi", "--release-store", store_dir,
                             "--registry", maint_reg, "--no-warm"])
    assert r.exit_code == 0, r.output
    assert "published certifi-" in r.output

    r = runner.invoke(main, ["releases", "--release-store", store_dir])
    assert r.exit_code == 0 and "certifi-" in r.output

    user_reg = str(tmp_path / "user-registry")
    r = runner.invoke(main, ["fetch", "certifi", "--release-store", store_dir,
                             "--registry", user_reg])
    assert r.exit_code == 0, r.output
    assert ArtifactRegistry(user_reg).list()[0].recipe == "certifi"

    # build on a fresh registry takes the prebuilt path, no local build
    user_reg2 = str(tmp_path / "user-registry-2")
    r = runner.invoke(main, ["build", "certifi", "--release-store", store_dir,
                             "--registry", user_reg2])
    assert r.exit_code == 0, r.output
    assert "fetched prebuilt" in r.output
    # and a second build is a plain local cache hit
    r = runner.invoke(main, ["build", "certifi", "--release-store", store_dir,
                             "--registry", user_reg2])
    assert "cache hit" in r.output


def test_cli_build_any_asset_for_device_pinned_recipe(bundle_dir, tmp_path):
    """A device-pinned recipe must be able to consume an ``any``-device
    prebuilt asset, and later builds/deploy lookups must find the cached
    artifact even though its id differs from the locally computed one."""
    recipes = tmp_path / "recipes"
    recipes.mkdir()
    (recipes / "demo.toml").write_text(
        'schema = 1\nname = "demo"\nversion = "0.1"\ndevice = "cpu"\nrequires = []\n')
    store = ReleaseStore.create(tmp_path / "store")
    archive = pack_bundle(bundle_dir, tmp_path / "demo.tar.gz")
    store.upload_asset("v1", archive, artifact_id=f"demo-0.1-{PYTAG}-any",
                       recipe="demo", version="0.1", python=PYVER,
                       device="any")
    runner = CliRunner()
    reg = str(tmp_path / "registry")
    args = ["build", "demo", "--recipe-dir", str(recipes),
            "--release-store", str(tmp_path / "store"), "--registry", reg]
    r = runner.invoke(main, args)
    assert r.exit_code == 0, r.output
    assert "fetched prebuilt" in r.output
    r = runner.invoke(main, args)
    assert r.exit_code == 0, r.output
    assert f"cache hit: demo-0.1-{PYTAG}-any" in r.output


def test_cli_build_falls_back_when_asset_corrupt(bundle_dir, tmp_path):
    recipes = tmp_path / "recipes"
    recipes.mkdir()
    (recipes / "tinycert.toml").write_text(
        'schema = 1\nname = "tinycert"\nversion = "0.1"\ndevice = "any"\n'
        'requires = ["certifi"]\n')
    store = ReleaseStore.create(tmp_path / "store")
    archive = pack_bundle(bundle_dir, tmp_path / "t.tar.gz")
    asset = store.upload_asset(
        "v1", archive, artifact_id=f"tinycert-0.1-{PYTAG}-any",
        recipe="tinycert", version="0.1", python=PYVER, device="any")
    path = store.asset_path(asset)
    path.write_bytes(path.read_bytes() + b"x")  # corrupt after indexing
    r = CliRunner().invoke(main, [
        "build", "tinycert", "--recipe-dir", str(recipes),
        "--release-store", str(tmp_path / "store"),
        "--registry", str(tmp_path / "registry")])
    assert r.exit_code == 0, r.output
    assert "prebuilt fetch failed" in r.output
    assert f"built + published tinycert-0.1-{PYTAG}-any" in r.output


def test_cli_fetch_missing_asset_fails_cleanly(tmp_path):
    ReleaseStore.create(tmp_path / "store")
    r = CliRunner().invoke(main, ["fetch", "certifi", "--release-store",
                                  str(tmp_path / "store")])
    assert r.exit_code != 0
    assert "no prebuilt asset" in r.output
