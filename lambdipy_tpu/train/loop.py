"""Trainer: the resumable SPMD training loop.

Ties the pieces together — deterministic sharded data (data/loader.py),
the donated jit train step with TP/FSDP shardings (train/step.py), orbax
checkpointing with exact resume (train/checkpoint.py) — into one loop with
structured-JSON step logs, periodic saves that include the loader cursor,
and crash-resume that replays the identical batch sequence. The reference
has no training at all (SURVEY.md §3.2); this is the rebuild's training
lifecycle, built TPU-first: the jitted step dispatches asynchronously, so
host work (next_batch) overlaps device work, and only logging steps force
a device sync.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

from lambdipy_tpu.utils.logs import get_logger, log_event

log = get_logger("lambdipy.train")


@dataclass
class TrainerConfig:
    total_steps: int
    learning_rate: float = 1e-3
    log_every: int = 10
    ckpt_every: int = 100
    keep_ckpts: int = 3
    fsdp: bool = True
    aux_weight: float = 0.01
    # optimizer stack (train/step.py make_optimizer): global-norm clipping,
    # warmup / cosine decay, gradient accumulation (total_steps counts
    # micro-steps; params update every accum_steps-th step). All default
    # OFF: the defaults must keep the plain-adamw opt_state structure so
    # checkpoints written before these knobs existed still exact-resume.
    grad_clip: float | None = None
    warmup_steps: int = 0
    schedule: str = "constant"  # "constant" | "cosine"
    weight_decay: float = 0.0
    accum_steps: int = 1
    # numerics sanitizer (utils/debug.py): NaN in any step output raises
    # FloatingPointError at the producing primitive. Debug only — forces
    # a device sync per step.
    debug_numerics: bool = False


@dataclass
class TrainerReport:
    steps_run: int
    final_step: int
    resumed_from: int | None
    history: list[dict] = field(default_factory=list)  # logged metric rows


class Trainer:
    """Resumable training over a mesh.

    ``model_apply(params, tokens) -> logits`` (plus optional
    ``model_apply_aux`` for MoE balance losses); ``params`` is the INIT
    pytree — when ``ckpt_dir`` holds a checkpoint, training resumes from
    it instead (same shapes required, enforced by orbax restore).
    """

    def __init__(self, model_apply: Callable, params, mesh, rules, loader,
                 cfg: TrainerConfig, *, ckpt_dir: Path | str | None = None,
                 model_apply_aux: Callable | None = None):
        import jax

        from lambdipy_tpu.train.checkpoint import TrainCheckpointer
        from lambdipy_tpu.train.step import make_optimizer, sharded_train_step

        self.cfg = cfg
        self.mesh = mesh
        self.loader = loader
        self.model_apply = model_apply
        self._jax = jax
        optimizer = make_optimizer(
            cfg.learning_rate, total_steps=cfg.total_steps,
            warmup_steps=cfg.warmup_steps, schedule=cfg.schedule,
            grad_clip=cfg.grad_clip, weight_decay=cfg.weight_decay,
            accum_steps=cfg.accum_steps)
        self.step_fn, self.state, self.batch_sharding = sharded_train_step(
            model_apply, params, mesh, rules,
            learning_rate=cfg.learning_rate, fsdp=cfg.fsdp,
            model_apply_aux=model_apply_aux, aux_weight=cfg.aux_weight,
            optimizer=optimizer)

        self.ckpt: Any = None
        self.resumed_from: int | None = None
        if ckpt_dir is not None:
            self.ckpt = TrainCheckpointer(
                ckpt_dir, max_to_keep=cfg.keep_ckpts,
                save_interval_steps=cfg.ckpt_every)
            restored, at = self.ckpt.restore(
                {"train": self.state, "loader": loader.state_dict()})
            if restored is not None:
                self.state = restored["train"]
                loader.restore(jax.tree_util.tree_map(int, restored["loader"]))
                self.resumed_from = at
                log_event(log, "trainer resumed", step=at)

    @property
    def step(self) -> int:
        """Device-authoritative step counter (forces a sync)."""
        return int(self._jax.device_get(self.state.step))

    def run(self) -> TrainerReport:
        """Train until ``cfg.total_steps`` (absolute, resume-aware)."""
        import contextlib

        from lambdipy_tpu.utils.debug import debug_numerics

        with (debug_numerics() if self.cfg.debug_numerics
              else contextlib.nullcontext()):
            return self._run()

    def _run(self) -> TrainerReport:
        jax = self._jax
        start = self.step
        history: list[dict] = []

        for host_step in range(start + 1, self.cfg.total_steps + 1):
            batch = self.loader.place(self.loader.next_batch(), self.mesh,
                                      self.batch_sharding)
            self.state, metrics = self.step_fn(self.state, batch)
            # the host-side counter mirrors state.step without a sync;
            # metrics are only materialized on logging steps
            if host_step % self.cfg.log_every == 0 or \
                    host_step == self.cfg.total_steps:
                row = {"step": host_step,
                       **{k: round(float(jax.device_get(v)), 5)
                          for k, v in metrics.items()}}
                history.append(row)
                log_event(log, "train step", **row)
            if self.ckpt is not None:
                # CheckpointManager's save_interval_steps decides cadence
                self.ckpt.save(host_step,
                               {"train": self.state,
                                "loader": self.loader.state_dict()})
        if self.ckpt is not None and start < self.cfg.total_steps:
            if self.ckpt.latest_step() != self.cfg.total_steps:
                # final state is always durable, even off-cadence (a
                # cadence save of the same step would collide -> skip)
                self.ckpt.save(self.cfg.total_steps,
                               {"train": self.state,
                                "loader": self.loader.state_dict()}, force=True)
            self.ckpt.wait()
        final = self.step
        return TrainerReport(steps_run=final - start, final_step=final,
                             resumed_from=self.resumed_from, history=history)

    def evaluate(self, eval_loader, *, batches: int = 8) -> float:
        """Mean next-token CE over ``batches`` eval batches (no updates)."""
        import jax

        if not hasattr(self, "_eval_fn"):
            import jax.numpy as jnp

            model_apply = self.model_apply

            # built once (not per evaluate() call — re-tracing would pay a
            # full recompile on every periodic eval)
            @jax.jit
            def eval_loss(params, tokens):
                logits = model_apply(params, tokens[:, :-1])
                targets = tokens[:, 1:]
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
                nll = -jnp.take_along_axis(logp, targets[..., None],
                                           axis=-1)[..., 0]
                return jnp.mean(nll)

            self._eval_fn = eval_loss

        total = 0.0
        with self.mesh:
            for _ in range(batches):
                batch = eval_loader.place(eval_loader.next_batch(), self.mesh,
                                          self.batch_sharding)
                total += float(jax.device_get(
                    self._eval_fn(self.state.params, batch)))
        return total / batches

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Flush and release the checkpoint manager's background workers."""
        if self.ckpt is not None:
            self.ckpt.wait()
            self.ckpt.close()
            self.ckpt = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
