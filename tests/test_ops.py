"""Pallas op tests: kernel (interpret mode) vs pure-jax oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lambdipy_tpu.ops.attention import flash_attention, mha_reference


def _rand(shape, seed):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape), jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    b, s, h, d = 2, 256, 2, 64
    q, k, v = (_rand((b, s, h, d), i) for i in range(3))
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3)


def test_flash_attention_gqa_broadcast():
    b, s, h, kvh, d = 1, 128, 4, 2, 64
    q = _rand((b, s, h, d), 0)
    k = _rand((b, s, kvh, d), 1)
    v = _rand((b, s, kvh, d), 2)
    ref = mha_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3)


def test_flash_attention_untileable_falls_back():
    b, s, h, d = 1, 10, 2, 16  # s=10 doesn't tile
    q, k, v = (_rand((b, s, h, d), i) for i in range(3))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-5, atol=1e-5)
