"""Fleet subsystem: affinity hashing, pool health state machine, router
failover/retry/hedging (scriptable stub replicas — no device, so these
stay in the tight tier-1 phase-2 budget), and — marked ``slow``, run by
run_tier1.sh phase 5 — everything that boots real bundle servers:
router-vs-direct bitwise parity, the readiness split on a live server,
affinity concentrating the fleet prefix-cache hit rate, and subprocess
fault injection with SIGKILL + supervisor re-admission and a rolling
restart under traffic."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from lambdipy_tpu.fleet import (
    DRAINING,
    EJECTED,
    READY,
    FleetRouter,
    ReplicaPool,
    affinity,
)

from test_runtime import make_model_bundle


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _post(url, payload, timeout=120, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


# -- affinity hashing (pure) -------------------------------------------------


def test_prefix_key_leading_blocks():
    # keys depend only on the leading WHOLE blocks: same 64-token prefix,
    # different suffixes -> same key
    a = affinity.prefix_key({"tokens": list(range(64)) + [7, 8]}, block=32)
    b = affinity.prefix_key({"tokens": list(range(64)) + [9]}, block=32)
    assert a is not None and a == b
    # a different prefix changes the key
    c = affinity.prefix_key({"tokens": [5] * 64 + [7, 8]}, block=32)
    assert c != a
    # sub-block prompts key on the whole prompt (co-locate exact repeats)
    s1 = affinity.prefix_key({"tokens": [1, 2, 3]}, block=32)
    s2 = affinity.prefix_key({"tokens": [1, 2, 3]}, block=32)
    s3 = affinity.prefix_key({"tokens": [1, 2, 4]}, block=32)
    assert s1 == s2 and s1 != s3
    # an explicit client prefix is part of the effective prompt
    p1 = affinity.prefix_key({"prefix": list(range(32)), "tokens": [1, 2]},
                             block=32)
    p2 = affinity.prefix_key({"tokens": list(range(32)) + [3, 4]}, block=32)
    assert p1 == p2
    # ...including for string-suffix and prefix-only bodies: the prefix
    # is the reusable KV, so all three co-locate
    t1 = affinity.prefix_key({"prefix": list(range(32)), "text": "abc"},
                             block=32)
    t2 = affinity.prefix_key({"prefix": list(range(32)), "text": "xyz"},
                             block=32)
    t3 = affinity.prefix_key({"prefix": list(range(32))}, block=32)
    assert t1 == t2 == t3
    assert affinity.prefix_key({"prefix": [9] * 32, "text": "abc"},
                               block=32) != t1
    # the key window is BOUNDED: prompts sharing the first key_blocks
    # blocks co-locate even when their (multi-block) suffixes diverge —
    # a 512-token system prompt + distinct long user turns is exactly
    # the traffic affinity exists for
    shared = list(range(512))
    long_a = affinity.prefix_key(
        {"tokens": shared + [1] * 100}, block=32)
    long_b = affinity.prefix_key(
        {"tokens": shared + [2] * 100}, block=32)
    assert long_a == long_b
    assert affinity.prefix_key(
        {"tokens": list(range(7, 519)) + [1] * 100}, block=32) != long_a
    # OpenAI shape: token-array prompt and string prompt both key
    assert affinity.prefix_key({"prompt": list(range(40))}, block=32) \
        == affinity.prefix_key({"tokens": list(range(40))}, block=32)
    assert affinity.prefix_key({"prompt": "x" * 200}, block=32) \
        == affinity.prefix_key({"text": "x" * 200}, block=32)
    # nothing routable -> None
    assert affinity.prefix_key({"n": 3}, block=32) is None


def test_rendezvous_membership_stability():
    import random

    names = ["r0", "r1", "r2", "r3"]
    rng = random.Random(0)
    keys = [affinity.prefix_key(
        {"tokens": [rng.randrange(500) for _ in range(40)]})
        for _ in range(300)]
    before = {k: affinity.pick_replica(k, names) for k in keys}
    assert len(set(before.values())) == len(names)  # all replicas used
    # removing one replica remaps ONLY the keys that lived on it
    survivors = [n for n in names if n != "r2"]
    for k in keys:
        after = affinity.pick_replica(k, survivors)
        if before[k] != "r2":
            assert after == before[k]
        else:
            assert after in survivors


# -- stub replica ------------------------------------------------------------


class StubReplica:
    """Scriptable bundle-server stand-in: the /healthz /metrics /invoke
    /v1/completions contract the router needs, plus knobs tests flip
    mid-flight (shed / draining / warming / delay / pid)."""

    def __init__(self, name, *, port=0):
        self.name = name
        self.cfg = {"shed": False, "draining": False, "warming": False,
                    "delay_s": 0.0, "retry_after": 1, "pid": 1000,
                    "prefix_cache": {"hits": 0, "misses": 0,
                                     "hit_tokens": 0},
                    "spec": {"sp_standdown": 0,
                             "sp_standdown_reasons": {}}}
        self.cfg["kv_shed"] = False   # /v1/kv/import answers 503
        self.cfg["kv_frame"] = b"LKV1-stub-frame"  # /v1/kv/export body
        # opt-in chunked export: a list of wire frames (LKVS header +
        # LKVC chunks, e.g. from kvwire.encode_stream) served as a
        # chunked response when the export request asks stream=true —
        # the pipelined-relay tests ride this; None keeps the
        # monolithic LKV1 behavior above
        self.cfg["kv_stream_frames"] = None
        # /v1/kv/probe: None = report the whole asked head as present
        # (the dedup-preserving default); an int scripts a partial/empty
        # match (a stale ship-dedup entry the router should PULL for)
        self.cfg["kv_probe_matched"] = None
        self.invokes = 0
        self.exports = 0
        self.probes = 0
        self.imports = []  # raw frames received on /v1/kv/import
        self.deletes = []  # session ids received on DELETE /v1/sessions
        self.bodies = []  # (path, parsed body) of every POST received
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    ready = (not stub.cfg["draining"]
                             and not stub.cfg["warming"])
                    self._send(200, {"ok": True, "ready": ready,
                                     "draining": stub.cfg["draining"],
                                     "warming": stub.cfg["warming"],
                                     "pid": stub.cfg["pid"]})
                elif self.path == "/metrics":
                    self._send(200, {
                        "count": stub.invokes,
                        "handler": {"prefix_cache": stub.cfg["prefix_cache"],
                                    "spec": stub.cfg["spec"]},
                    })
                else:
                    self._send(404, {"ok": False})

            def _frame(self, b):
                self.wfile.write(f"{len(b):x}\r\n".encode() + b + b"\r\n")

            def do_POST(self):
                if "chunked" in (self.headers.get("Transfer-Encoding")
                                 or "").lower():
                    # de-chunk a streamed import body (the pipelined
                    # relay's import leg); the reassembled bytes land
                    # in stub.imports like a monolithic frame would. A
                    # relay dying mid-stream (no terminal chunk) closes
                    # the connection without recording an import — the
                    # rollback behavior the real server implements.
                    raw = b""
                    try:
                        while True:
                            size = int(
                                self.rfile.readline(66).strip(), 16)
                            if size == 0:
                                self.rfile.readline()
                                break
                            raw += self.rfile.read(size)
                            self.rfile.read(2)
                    except (ValueError, OSError):
                        self.close_connection = True
                        return
                else:
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length)
                if self.path == "/v1/kv/import":
                    # binary frame, not JSON; scriptable backpressure
                    if stub.cfg["kv_shed"]:
                        ra = stub.cfg["retry_after"]
                        self._send(503, {"ok": False, "shed": True,
                                         "reason": "kv_import",
                                         "retry_after_s": float(ra)},
                                   {"Retry-After": str(ra)})
                        return
                    stub.imports.append(raw)
                    self._send(200, {"ok": True, "inserted": 2,
                                     "present": 0, "mode": "dense"})
                    return
                body = json.loads(raw or b"{}")
                stub.bodies.append((self.path, body))
                if self.path == "/v1/kv/probe":
                    stub.probes += 1
                    matched = stub.cfg["kv_probe_matched"]
                    if matched is None:
                        matched = len(body.get("tokens") or [])
                    self._send(200, {"ok": True, "matched": int(matched)})
                    return
                if self.path == "/v1/kv/export":
                    if stub.cfg["shed"] or stub.cfg["draining"]:
                        ra = stub.cfg["retry_after"]
                        self._send(503, {"ok": False, "shed": True,
                                         "reason": "draining",
                                         "retry_after_s": float(ra)},
                                   {"Retry-After": str(ra)})
                        return
                    stub.exports += 1
                    frames = stub.cfg["kv_stream_frames"]
                    if body.get("stream") and frames is not None:
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-lkv-stream")
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        for f in frames:
                            self._frame(f)
                        self.wfile.write(b"0\r\n\r\n")
                        return
                    frame = stub.cfg["kv_frame"]
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length", str(len(frame)))
                    self.end_headers()
                    self.wfile.write(frame)
                    return
                if stub.cfg["delay_s"]:
                    time.sleep(stub.cfg["delay_s"])
                if stub.cfg["shed"] or stub.cfg["draining"]:
                    ra = stub.cfg["retry_after"]
                    self._send(503, {"ok": False, "shed": True,
                                     "reason": "draining",
                                     "retry_after_s": float(ra)},
                               {"Retry-After": str(ra)})
                    return
                stub.invokes += 1
                if body.get("stream"):
                    sse = self.path == "/v1/completions"
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/event-stream" if sse
                                     else "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    if sse:
                        self._frame(b'data: {"choices": [{"tokens": [1],'
                                    b' "text": ""}]}\n\n')
                        self._frame(b"data: [DONE]\n\n")
                    else:
                        self._frame(json.dumps(
                            {"ok": True, "tokens": [[1]],
                             "replica": stub.name}).encode() + b"\n")
                        self._frame(json.dumps(
                            {"ok": True, "done": True, "n_new": 1,
                             "replica": stub.name}).encode() + b"\n")
                    self.wfile.write(b"0\r\n\r\n")
                    return
                self._send(200, {"ok": True, "replica": stub.name,
                                 "echo": body.get("tokens"),
                                 "session":
                                     self.headers.get("x-session-id")
                                     or body.get("session_id"),
                                 "priority":
                                     self.headers.get("x-priority")})

            def do_DELETE(self):
                if self.path.startswith("/v1/sessions/"):
                    sid = self.path[len("/v1/sessions/"):]
                    stub.deletes.append(sid)
                    self._send(200, {"ok": True, "session": sid,
                                     "released": True})
                    return
                self._send(404, {"ok": False})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), H)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def kill(self):
        """Abrupt death: the port refuses connections afterwards."""
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def stub_pair():
    s0, s1 = StubReplica("r0"), StubReplica("r1")
    pool = ReplicaPool(probe_interval=0.1, fail_threshold=1,
                       readmit_passes=2, probe_timeout=2.0)
    pool.attach("r0", s0.url)
    pool.attach("r1", s1.url)
    yield s0, s1, pool
    pool.close()
    for s in (s0, s1):
        try:
            s.kill()
        except Exception:
            pass


# -- pool health state machine ----------------------------------------------


def test_pool_eject_readmit_and_draining(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    assert {r.name for r in pool.routable()} == {"r0", "r1"}

    # readiness false (drain begun) = alive but NOT routable, NOT ejected
    s0.cfg["draining"] = True
    pool.probe_all()
    r0 = pool.replicas["r0"]
    assert [r.name for r in pool.routable()] == ["r1"]
    assert r0.state == READY and not r0.ready and r0.ejections == 0
    s0.cfg["draining"] = False
    pool.probe_all()
    assert len(pool.routable()) == 2

    # warm-in-flight is the same not-routable-but-live story
    s0.cfg["warming"] = True
    pool.probe_all()
    assert [r.name for r in pool.routable()] == ["r1"]
    s0.cfg["warming"] = False
    pool.probe_all()

    # abrupt death -> ejected after fail_threshold(=1) consecutive fails
    port = s0.port
    s0.kill()
    pool.probe_all()
    assert r0.state == EJECTED and r0.ejections == 1

    # revival (same port, new worker pid) -> readmitted only after
    # readmit_passes consecutive passes, with the restart counted
    s0b = StubReplica("r0", port=port)
    s0b.cfg["pid"] = 2000
    pool.probe_all()
    assert r0.state == EJECTED  # one pass is not enough
    pool.probe_all()
    assert r0.state == READY and r0.restarts == 1
    s0b.kill()


# -- router: routing, failover, retry ---------------------------------------


def test_router_spreads_and_fails_over(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    router = FleetRouter(pool, affinity_on=False,
                         max_retries=2, backoff_cap_s=0.2)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        for i in range(6):
            out = _post(f"{base}/invoke", {"tokens": [i], "n": 1})
            assert out["ok"] and out["echo"] == [i]
        # round-robin tie-break spreads affinity-off traffic
        assert s0.invokes >= 2 and s1.invokes >= 2
        assert s0.invokes + s1.invokes == 6

        # kill one replica: concurrent traffic must all succeed via
        # retries, and the dead replica ejects at TRAFFIC speed (the
        # router reports the connection failure; no probe needed)
        s0.kill()
        results = []

        def worker(i):
            results.append(_post(f"{base}/invoke", {"tokens": [i]}))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 8 and all(r["ok"] for r in results)
        assert all(r["replica"] == "r1" for r in results)
        assert pool.replicas["r0"].state == EJECTED
        rep = router.stats.report()
        assert rep["failovers"] >= 1 and rep["retries"] >= 1
        assert rep["completed"] >= 14
    finally:
        router.stop()


def test_router_honors_retry_after_shed(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    router = FleetRouter(pool, affinity_on=False, max_retries=2,
                         backoff_s=0.01, backoff_cap_s=0.2)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        # one replica shedding: every request still lands on the other
        s0.cfg["shed"] = True
        for i in range(4):
            out = _post(f"{base}/invoke", {"tokens": [i]})
            assert out["ok"] and out["replica"] == "r1"
        assert router.stats.report()["retries"] >= 1

        # the WHOLE fleet shedding: the shed response is relayed to the
        # client with its Retry-After intact, not a synthetic error
        s1.cfg["shed"] = True
        s0.cfg["retry_after"] = s1.cfg["retry_after"] = 7
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/invoke", {"tokens": [1]})
        assert e.value.code == 503
        assert e.value.headers["Retry-After"] == "7"
        body = json.loads(e.value.read())
        assert body["shed"] and body["retry_after_s"] == 7.0
    finally:
        router.stop()


def test_router_streaming_passthrough_and_stream_failover(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    router = FleetRouter(pool, affinity_on=False, max_retries=2,
                         backoff_cap_s=0.2)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        # ndjson /invoke pass-through
        req = urllib.request.Request(
            f"{base}/invoke",
            data=json.dumps({"tokens": [1], "stream": True}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.headers["Content-Type"] == "application/x-ndjson"
            lines = [json.loads(ln) for ln in resp if ln.strip()]
        assert lines[-1]["done"] and lines[0]["tokens"] == [[1]]

        # SSE /v1/completions pass-through
        req = urllib.request.Request(
            f"{base}/v1/completions",
            data=json.dumps({"prompt": [1], "stream": True}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.headers["Content-Type"] == "text/event-stream"
            events = [ln.decode().strip()[6:] for ln in resp
                      if ln.strip().startswith(b"data: ")]
        assert events[-1] == "[DONE]"

        # a dead replica is retried BEFORE any bytes are forwarded
        s0.kill()
        served = set()
        for i in range(4):
            req = urllib.request.Request(
                f"{base}/invoke",
                data=json.dumps({"tokens": [i],
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=30) as resp:
                lines = [json.loads(ln) for ln in resp if ln.strip()]
            assert lines[-1]["done"]
            served.add(lines[-1]["replica"])
        assert served == {"r1"}
    finally:
        router.stop()


def test_router_hedges_slow_primary(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    # a key whose rendezvous target we can find out, then slow down
    key = affinity.prefix_key({"tokens": list(range(64))}, block=32)
    target = affinity.pick_replica(key, ["r0", "r1"])
    slow, fast = (s0, s1) if target == "r0" else (s1, s0)
    slow.cfg["delay_s"] = 1.5
    router = FleetRouter(pool, affinity_on=True, block=32,
                         hedge_ms=100, hedge_floor_ms=50)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        t0 = time.monotonic()
        out = _post(f"{base}/invoke", {"tokens": list(range(64))})
        elapsed = time.monotonic() - t0
        assert out["ok"] and out["replica"] == fast.name
        assert elapsed < 1.4  # did not wait out the slow primary
        rep = router.stats.report()
        assert rep["hedges"] == 1 and rep["hedge_wins"] == 1
        assert pool.replicas[fast.name].hedged == 1
    finally:
        router.stop()


def test_router_healthz_and_metrics_aggregation(stub_pair):
    s0, s1, pool = stub_pair
    pool.probe_all()
    s0.cfg["prefix_cache"] = {"hits": 3, "misses": 1, "hit_tokens": 96}
    s1.cfg["prefix_cache"] = {"hits": 1, "misses": 1, "hit_tokens": 32}
    # sp-decode stand-downs aggregate BY REASON at the router (a sharded
    # replica quietly replicating its cache must be visible fleet-wide)
    s0.cfg["spec"] = {"sp_standdown": 2, "sp_standdown_reasons":
                      {"attn_backend=blocked": 2}}
    s1.cfg["spec"] = {"sp_standdown": 1, "sp_standdown_reasons":
                      {"spec_k_under_sp_mesh": 1}}
    router = FleetRouter(pool, affinity_on=True, block=32)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        for i in range(3):
            _post(f"{base}/invoke", {"tokens": list(range(32 + i))})
        health = _get(f"{base}/healthz")
        assert health["ok"] and health["routable"] == 2
        assert health["replicas"] == {"r0": READY, "r1": READY}
        m = _get(f"{base}/metrics")
        # fleet-wide prefix cache is the SUM over replicas
        assert m["fleet"]["prefix_cache"] == {
            "hits": 4, "misses": 2, "hit_tokens": 128,
            "hit_rate": round(4 / 6, 4)}
        assert m["fleet"]["spec_standdown"] == {
            "total": 3, "reasons": {"attn_backend=blocked": 2,
                                    "spec_k_under_sp_mesh": 1}}
        assert m["router"]["completed"] == 3
        assert m["router"]["affinity"]["requests"] == 3
        assert sum(rep["routed"] for rep in m["pool"].values()) == 3
        # per-replica raw /metrics ride along
        assert m["replicas"]["r0"]["count"] == s0.invokes
        # distinct 32-token prefixes: affinity keys differ, but each is
        # a HIT (target routable)
        assert m["router"]["affinity"]["hit_rate"] == 1.0
    finally:
        router.stop()


def test_router_draining_replica_loses_traffic_before_shedding(stub_pair):
    """The readiness split in action: once a replica reports
    ready: false, the router stops routing there BEFORE any request has
    to eat its 503."""
    s0, s1, pool = stub_pair
    pool.probe_all()
    router = FleetRouter(pool, affinity_on=False)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        s0.cfg["draining"] = True  # server would 503, probe says not ready
        pool.probe_all()
        before = s0.invokes
        for i in range(4):
            out = _post(f"{base}/invoke", {"tokens": [i]})
            assert out["replica"] == "r1"
        assert s0.invokes == before  # zero requests even reached it
        assert router.stats.report()["retries"] == 0
    finally:
        router.stop()


def test_router_serves_through_whole_fleet_warming(stub_pair):
    """Brownout guard: when EVERY replica reports ready: false because
    its background warm is still compiling (a fresh fleet's first burst
    of traffic), the router degrades to the live-but-warming replicas —
    they serve fine — instead of 503ing the fleet."""
    s0, s1, pool = stub_pair
    s0.cfg["warming"] = s1.cfg["warming"] = True
    pool.probe_all()
    assert pool.routable() == [] and len(pool.live_fallback()) == 2
    router = FleetRouter(pool, affinity_on=False)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        for i in range(4):
            assert _post(f"{base}/invoke", {"tokens": [i]})["ok"]
        assert router.stats.report()["no_replica"] == 0
        # once warm finishes, strict readiness routing resumes
        s0.cfg["warming"] = s1.cfg["warming"] = False
        pool.probe_all()
        assert len(pool.routable()) == 2
    finally:
        router.stop()


def test_pool_begin_drain_routes_away_immediately(stub_pair):
    """Rolling-drain step 1: begin_drain() flips routing away without
    waiting for the next probe. (The stubs stand in for MANAGED
    replicas here — begin_drain refuses attached ones, see
    tests/test_fleet_resilience.py.)"""
    s0, s1, pool = stub_pair
    pool.probe_all()
    for r in pool.replicas.values():
        r.managed = True
    router = FleetRouter(pool, affinity_on=False)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        pool.begin_drain("r1")
        assert pool.replicas["r1"].state == DRAINING
        for i in range(4):
            assert _post(f"{base}/invoke",
                         {"tokens": [i]})["replica"] == "r0"
        # end_drain aborts the drain (the chaos nemesis's undrain, an
        # operator changing their mind): r1 routes again, and a second
        # end_drain on a non-draining replica is a no-op
        pool.end_drain("r1")
        assert pool.replicas["r1"].routable
        pool.end_drain("r1")
        seen = {_post(f"{base}/invoke", {"tokens": [i]})["replica"]
                for i in range(8)}
        assert "r1" in seen
    finally:
        router.stop()


# -- deploy/_http_json edges the router leans on -----------------------------


def test_http_json_connection_refused_and_timeout():
    from lambdipy_tpu.runtime.deploy import _http_json

    # refused: nothing listening on a fresh port -> URLError, fast
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    t0 = time.monotonic()
    with pytest.raises(urllib.error.URLError):
        _http_json(f"http://127.0.0.1:{port}/healthz", timeout=5)
    assert time.monotonic() - t0 < 2.0

    # timeout: a listener that accepts but never answers must raise at
    # the caller's deadline, not hang the router's probe thread
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    try:
        t0 = time.monotonic()
        with pytest.raises(Exception) as e:
            _http_json(
                f"http://127.0.0.1:{srv.getsockname()[1]}/healthz",
                timeout=0.3)
        assert isinstance(e.value, (TimeoutError, urllib.error.URLError,
                                    socket.timeout))
        assert time.monotonic() - t0 < 3.0
    finally:
        srv.close()


# -- real-bundle parity through the router -----------------------------------


@pytest.fixture(scope="module")
def fleet_bundle(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fleet-bundle")
    return make_model_bundle(
        tmp, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "4"})


@pytest.fixture(scope="module")
def real_pair(fleet_bundle):
    from lambdipy_tpu.runtime.server import BundleServer

    servers = [BundleServer(fleet_bundle, warmup=False).start_background()
               for _ in range(2)]
    pool = ReplicaPool(probe_interval=0.2, fail_threshold=1,
                       readmit_passes=2)
    for i, s in enumerate(servers):
        pool.attach(f"b{i}", f"http://127.0.0.1:{s.port}")
    pool.probe_all()
    yield servers, pool
    pool.close()
    for s in servers:
        threading.Thread(target=s.stop, daemon=True).start()


@pytest.mark.slow
def test_bundle_server_readiness_split(real_pair, monkeypatch):
    servers, pool = real_pair
    s = servers[0]
    base = f"http://127.0.0.1:{s.port}"
    h = _get(f"{base}/healthz")
    assert h["ok"] and h["ready"] and not h["warming"]
    # warm in flight: still 200/ok (liveness) but flagged not ready
    monkeypatch.setattr(s.boot.state, "warming_fn", lambda: True)
    h = _get(f"{base}/healthz")
    assert h["ok"] and not h["ready"] and h["warming"]
    monkeypatch.undo()
    # drain begun: same split
    s.draining = True
    try:
        h = _get(f"{base}/healthz")
        assert h["ok"] and not h["ready"] and h["draining"]
    finally:
        s.draining = False


@pytest.mark.slow
def test_router_parity_real_servers(real_pair):
    """Acceptance: router-fronted responses are bitwise identical to
    direct single-replica responses — greedy and seeded-sampled,
    streamed and non-streamed."""
    servers, pool = real_pair
    direct = f"http://127.0.0.1:{servers[0].port}"
    router = FleetRouter(pool, affinity_on=True, block=32)
    router.start_background()
    base = f"http://127.0.0.1:{router.port}"
    try:
        greedy = {"prompt": [1, 2, 3], "max_tokens": 6, "temperature": 0}
        sampled = {"prompt": [1, 2, 3], "max_tokens": 6,
                   "temperature": 0.8, "top_k": 5, "seed": 7}
        for body in (greedy, sampled):
            d = _post(f"{direct}/v1/completions", body)
            r = _post(f"{base}/v1/completions", body)
            # queue_wait_ms is a per-request timing measurement (stamped
            # at sched grant) — each request measures its own wait, so
            # bitwise parity applies to everything BUT it
            assert d.pop("queue_wait_ms", None) is not None
            assert r.pop("queue_wait_ms", None) is not None
            assert d == r  # whole response: tokens, usage, finish_reason

        def sse_events(url, body):
            req = urllib.request.Request(
                url, data=json.dumps({**body, "stream": True,
                                      "segment": 2}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=120) as resp:
                return [ln for ln in resp if ln.strip()]

        for body in (greedy, sampled):
            assert sse_events(f"{direct}/v1/completions", body) == \
                sse_events(f"{base}/v1/completions", body)

        # /invoke ndjson streaming parity
        body = {"tokens": [1, 2, 3], "max_new_tokens": 6, "stream": True,
                "segment": 3}
        assert sse_events(f"{direct}/invoke", body) == \
            sse_events(f"{base}/invoke", body)

        # affinity keeps a repeated prompt on one replica
        routed_before = {n: r.routed for n, r in pool.replicas.items()}
        for _ in range(4):
            _post(f"{base}/v1/completions", greedy)
        moved = {n: pool.replicas[n].routed - routed_before[n]
                 for n in routed_before}
        assert sorted(moved.values()) == [0, 4]
    finally:
        router.stop()


# -- slow: affinity concentrates the prefix cache ----------------------------


@pytest.mark.slow
def test_affinity_raises_fleet_prefix_hit_rate(tmp_path):
    """Acceptance: shared-prefix traffic achieves a HIGHER fleet
    prefix-cache hit rate with affinity on than off. Fresh prefix groups
    per phase keep the comparison cold-for-cold on the same servers."""
    import numpy as np

    from lambdipy_tpu.runtime.server import BundleServer

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "4", "prefix_cache_mb": "64",
               "prefix_block": "16"})
    servers = [BundleServer(bundle, warmup=False).start_background()
               for _ in range(2)]
    pool = ReplicaPool(probe_interval=0.2)
    for i, s in enumerate(servers):
        pool.attach(f"p{i}", f"http://127.0.0.1:{s.port}")
    pool.probe_all()

    def run_phase(affinity_on, seed):
        phase_rng = np.random.default_rng(seed)
        router = FleetRouter(pool, affinity_on=affinity_on, block=16)
        router.start_background()
        base = f"http://127.0.0.1:{router.port}"
        try:
            before = router.metrics()["fleet"]["prefix_cache"]
            for _ in range(2):  # two distinct shared-prefix groups
                shared = phase_rng.integers(1, 500, 32).tolist()
                for _ in range(5):
                    suffix = phase_rng.integers(1, 500, 4).tolist()
                    out = _post(f"{base}/v1/completions",
                                {"prompt": shared + suffix,
                                 "max_tokens": 4, "temperature": 0},
                                timeout=600)
                    assert out["choices"][0]["tokens"]
            after = router.metrics()["fleet"]["prefix_cache"]
        finally:
            router.stop()
        hits = after["hits"] - before["hits"]
        misses = after["misses"] - before["misses"]
        assert hits + misses == 10
        return hits / 10

    try:
        rate_on = run_phase(True, seed=1)
        rate_off = run_phase(False, seed=2)
        assert rate_on > rate_off, (rate_on, rate_off)
        # with affinity each group pays ONE cold miss; round-robin
        # spreads each group across both replicas' caches
        assert rate_on >= 0.8
    finally:
        pool.close()
        for s in servers:
            threading.Thread(target=s.stop, daemon=True).start()


# -- slow: subprocess fault injection + rolling restart ----------------------


@pytest.mark.slow
def test_fleet_fault_injection_and_rolling_restart(tmp_path):
    """Acceptance: with 2 supervised replicas under concurrent traffic,
    SIGKILL of one replica's worker loses zero requests (retries route
    to the survivor), the dead replica is ejected within one probe
    interval, the supervisor respawns it AT ITS REGISTERED URL
    (port-pinning) and the pool re-admits it — all visible in the fleet
    metrics. Then a rolling restart drains both replicas one at a time
    without ever dropping below the live floor."""
    import os
    import signal

    from lambdipy_tpu.runtime.deploy import LocalRuntime

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "2"})
    env = {
        "LAMBDIPY_PLATFORM": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "LAMBDIPY_STABLE_UPTIME_S": "5",
        "LAMBDIPY_MAX_BACKOFF_S": "1",
    }
    rt = LocalRuntime(tmp_path / "deployments.json")
    pool = ReplicaPool(probe_interval=0.5, fail_threshold=1,
                       readmit_passes=2)
    pool.spawn_fleet(bundle, 2, base_name="fi", runtime=rt, env=env)
    pool.start()
    router = FleetRouter(pool, affinity_on=True, block=32, max_retries=3,
                         backoff_cap_s=0.5,
                         request_timeout=120).start_background()
    base = f"http://127.0.0.1:{router.port}"
    stop_traffic = threading.Event()
    ok_count = [0]
    failures = []

    def traffic():
        i = 0
        while not stop_traffic.is_set():
            i += 1
            try:
                out = _post(f"{base}/invoke",
                            {"tokens": [1 + (i % 7), 2, 3],
                             "max_new_tokens": 2}, timeout=120)
                assert out["ok"]
                ok_count[0] += 1
            except Exception as e:  # noqa: BLE001 — collected for assert
                failures.append(repr(e))
            time.sleep(0.05)

    threads = [threading.Thread(target=traffic) for _ in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(2)  # let traffic establish on the healthy fleet

        # SIGKILL the WORKER of fi-r1 (healthz pid — the supervisor in
        # front of it must stay up to respawn)
        victim = pool.replicas["fi-r1"]
        url_before, worker_pid = victim.url, victim.pid
        assert worker_pid and worker_pid != rt.get("fi-r1").pid
        os.kill(worker_pid, signal.SIGKILL)

        deadline = time.monotonic() + 30
        while victim.state != EJECTED and time.monotonic() < deadline:
            time.sleep(0.1)
        assert victim.state == EJECTED, "dead replica was not ejected"

        # supervisor respawn -> probe passes -> re-admission, same URL
        deadline = time.monotonic() + 180
        while victim.state != READY and time.monotonic() < deadline:
            time.sleep(0.5)
        assert victim.state == READY, "replica was never re-admitted"
        assert victim.url == url_before  # port pinned across restart
        assert victim.pid != worker_pid and victim.restarts >= 1
        time.sleep(2)  # traffic over the healed fleet

        assert not failures, f"lost requests: {failures[:3]}"
        assert ok_count[0] > 20
        m = router.metrics()
        assert m["router"]["retries"] >= 1
        assert m["pool"]["fi-r1"]["ejections"] == 1

        # rolling restart under (light) traffic: floor holds, zero lost
        pool.rolling_restart(live_floor=1, ready_timeout=180)
        deadline = time.monotonic() + 30  # a stale probe may flap one
        while time.monotonic() < deadline and \
                not all(r.routable for r in pool.replicas.values()):
            time.sleep(0.5)
        assert all(r.routable for r in pool.replicas.values())
        time.sleep(1)
        assert not failures, f"rolling restart lost: {failures[:3]}"
    finally:
        stop_traffic.set()
        for t in threads:
            t.join(timeout=30)
        router.stop()
        pool.stop_all()
    assert rt.list() == []
