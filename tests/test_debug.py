"""Numerics debug checks (utils/debug.py; SURVEY.md §6 sanitizer row)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lambdipy_tpu.utils.debug import apply_debug_env, debug_numerics


def test_debug_numerics_raises_at_producing_op():
    @jax.jit
    def bad(x):
        return jnp.sqrt(x) + 1.0  # sqrt(-1) -> nan

    # silently nan without the sanitizer...
    assert np.isnan(float(bad(jnp.float32(-1.0))))
    # ...raises with it
    with debug_numerics():
        with pytest.raises(FloatingPointError):
            jax.block_until_ready(bad(jnp.float32(-1.0)))


def test_debug_numerics_restores_flags():
    prior = jax.config.jax_debug_nans
    with debug_numerics():
        assert jax.config.jax_debug_nans is True
    assert jax.config.jax_debug_nans == prior


def test_apply_debug_env(monkeypatch):
    monkeypatch.setenv("LAMBDIPY_DEBUG_NANS", "1")
    try:
        assert apply_debug_env() == {"debug_nans": True}
        assert jax.config.jax_debug_nans is True
    finally:
        jax.config.update("jax_debug_nans", False)


@pytest.mark.slow  # heavyweight parity; subsystem keeps a fast test
def test_trainer_debug_numerics_catches_nan(cpu_devices):
    """A poisoned step fails fast under TrainerConfig.debug_numerics
    instead of logging nan losses forever."""
    from lambdipy_tpu.data.loader import ShardedLoader, TokenSource
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.parallel.mesh import make_mesh
    from lambdipy_tpu.train.loop import Trainer, TrainerConfig

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    # poison one weight: the forward nans immediately
    params["params"]["layer_0"]["q_proj"]["kernel"] = (
        params["params"]["layer_0"]["q_proj"]["kernel"].at[0, 0].set(jnp.nan))
    mesh = make_mesh({"dp": 2}, devices=cpu_devices[:2])
    tokens = np.tile(np.arange(50, dtype=np.int32), 40)
    loader = ShardedLoader(TokenSource(tokens, 16), 4, seed=0,
                           process_index=0, process_count=1)
    cfg = TrainerConfig(total_steps=2, log_every=1, debug_numerics=True)
    with mesh:
        trainer = Trainer(adapter.forward, params, mesh, adapter.tp_rules,
                          loader, cfg)
        with pytest.raises(FloatingPointError):
            trainer.run()
