"""Pipeline parallelism: GPipe-style microbatched schedule over ``pp``.

The reference has no distributed components (SURVEY.md §3.2); this is new
TPU-first surface. Stage s of the network lives on pp-rank s (stage params
are stacked on a leading dim sharded over ``pp``), a batch is split into
microbatches, and activations flow stage→stage via ``lax.ppermute`` — one
ICI hop per tick, compute overlapping communication, the whole schedule one
``lax.scan`` under jit (no Python control flow, static shapes, SURVEY.md
§6 distributed row).

Schedule: ``num_microbatches + num_stages - 1`` ticks. At tick t, stage 0
ingests microbatch t (while t < nmb), every stage applies its local
``stage_fn``, the last stage banks the finished microbatch ``t - (S-1)``,
and outputs rotate forward. Warmup/drain bubbles run on zero activations
and their outputs are discarded — the standard GPipe bubble cost of
``(S-1)/(nmb+S-1)``, minimized by choosing nmb >> S.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from lambdipy_tpu.parallel.mesh import shard_map_compat

from lambdipy_tpu.parallel.sharding import no_shard_hints


def split_microbatches(batch, num_microbatches: int):
    """[B, ...] -> [nmb, B/nmb, ...] (leading-dim split, order preserved)."""

    def split(leaf):
        b = leaf.shape[0]
        if b % num_microbatches:
            raise ValueError(
                f"batch {b} not divisible by num_microbatches={num_microbatches}")
        return leaf.reshape((num_microbatches, b // num_microbatches) + leaf.shape[1:])

    return jax.tree_util.tree_map(split, batch)


def merge_microbatches(out):
    """Inverse of :func:`split_microbatches`."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((-1,) + leaf.shape[2:]), out)


def stack_stage_params(stage_params: list):
    """Stack S per-stage pytrees (identical treedefs/shapes) into one pytree
    with a leading stage dim, ready to shard ``P("pp", ...)``."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params)


def _pipeline_local(params, x, const, *, stage_fn, axis_name: str,
                    vary_axes: tuple[str, ...]):
    """Per-device body (inside shard_map). params: stage slice with leading
    dim 1; x: [nmb, mb, ...] microbatches (pp-replicated); const: broadcast
    extras passed to every stage_fn call."""
    params = jax.tree_util.tree_map(lambda p: jnp.squeeze(p, axis=0), params)
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    nmb = x.shape[0]
    ticks = nmb + n_stages - 1
    # non-cyclic shift: stage i -> i+1; stage 0 receives zeros (overwritten
    # by the next microbatch), the last stage's output leaves the ring
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def varying(v):
        from lambdipy_tpu.parallel.mesh import pcast_varying

        return pcast_varying(v, vary_axes)

    state0 = varying(jnp.zeros_like(x[0]))
    out0 = varying(jnp.zeros_like(x))

    def tick(carry, t):
        state, out = carry
        x_t = varying(jax.lax.dynamic_index_in_dim(
            x, jnp.minimum(t, nmb - 1), axis=0, keepdims=False))
        inp = jnp.where(stage == 0, x_t, state)
        y = stage_fn(params, inp, const)
        # bank microbatch t-(S-1) on the last stage; other stages keep zeros
        # so the closing psum recovers the result everywhere
        widx = jnp.maximum(t - (n_stages - 1), 0)
        slot = jax.lax.dynamic_index_in_dim(out, widx, axis=0, keepdims=False)
        banked = jnp.where((stage == n_stages - 1) & (t >= n_stages - 1), y, slot)
        out = jax.lax.dynamic_update_index_in_dim(out, banked, widx, axis=0)
        state = jax.lax.ppermute(y, axis_name, perm)
        return (state, out), None

    (_, out), _ = jax.lax.scan(tick, (state0, out0), jnp.arange(ticks))
    return jax.lax.psum(out, axis_name)


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh: Mesh, *,
                   const=None, axis: str = "pp"):
    """Run microbatches through a pp-sharded stage pipeline.

    - ``stage_fn(stage_params, x, const) -> y`` with ``y.shape == x.shape``
      (the GPipe constraint: inter-stage activations are homogeneous);
    - ``stacked_params``: pytree with leading stage dim (see
      :func:`stack_stage_params`), sharded over ``axis``;
    - ``microbatches``: [nmb, mb, ...] array (see :func:`split_microbatches`);
      the mb dim is additionally sharded over dp/fsdp when those axes exist;
    - ``const``: pytree broadcast to every stage call (positions, masks).

    Returns [nmb, mb, ...] outputs, replicated over ``axis``.
    """
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh {mesh.axis_names} has no {axis!r} axis")
    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    x_spec = P(None, batch_axes if batch_axes else None)
    params_specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    const_specs = jax.tree_util.tree_map(lambda _: P(), const)
    fn = shard_map_compat(
        partial(_pipeline_local, stage_fn=stage_fn, axis_name=axis,
                vary_axes=batch_axes + (axis,)),
        mesh=mesh,
        in_specs=(params_specs, x_spec, const_specs),
        out_specs=x_spec,
    )
    # stage_fn bodies trace inside the manual region — whole-mesh
    # constraints (models' shard_hint calls) must not fire there
    with no_shard_hints():
        return fn(stacked_params, microbatches, const)
