"""KV-block wire framing for disaggregated prefill/decode serving.

A prefill-class replica exports the whole-block KV of a prompt head;
the router ships the frame to the affinity-chosen decode replica, whose
import is just a radix insert (runtime/prefixstore.py). The frame is the
ONLY thing that crosses the wire, so its contract is deliberately
minimal and self-describing:

``LKV1 | u32 header_len | header JSON | raw leaf bytes``

The header names the covered tokens, the block width, and the per-layer
leaf template (name, dtype, shape) — one template, because every block
of every layer stores the same store-layout leaves (``k``/``v`` float,
or ``k_int8``/``k_scale``/``v_int8``/``v_scale`` under ``kv_quant``:
int8 scales travel as first-class leaves, not a side channel). The body
is raw array bytes in a fixed order — block-major, then layer, then
leaf name sorted — so decode needs no per-array framing.

Decoding VALIDATES before any array is built: magic, header JSON, leaf
sanity, and the exact byte length the template implies. A truncated,
padded, or shape-lying frame raises :class:`ValueError` — the import
endpoint maps that to a 400, and a garbage frame can never insert
mis-shaped KV into a serving replica's radix tree.

Dtypes round-trip by name through numpy, with the ml_dtypes extended
set (``bfloat16``) resolved explicitly — a bf16 bundle ships its KV
bitwise, not through a float32 detour.

CHUNKED STREAM (the pipelined ship): the monolithic ``LKV1`` frame
serializes a full head-sized transfer behind the LAST prefill chunk —
at a cross-host RTT the wire sits idle while the prefill runs, then the
prefill replica sits idle while the wire drains. The stream format
splits the same payload into frames the export side can flush as soon
as the prefix-store walk produces each block group:

``LKVS | u32 len | stream header JSON``          (no body)
``LKVC | u32 len | chunk header JSON | raw leaf bytes``  (repeated)

The stream header carries everything ``LKV1``'s did — tokens, block
width, layer count, the per-layer leaf template, total ``n_blocks`` —
so the receiver can validate every later chunk against it and knows
exactly when the stream is complete (no end marker: completeness is
``blocks received == n_blocks``, and a connection that dies earlier IS
the truncation signal). Each chunk header names its absolute ``start``
block index, its block count, and its exact body byte length, so a
relay can re-frame the byte stream without knowing the leaf template;
chunks must arrive strictly in order (``start == blocks received``) —
an out-of-order, overlapping, or over-long chunk is rejected like any
other garbage, before its bytes become arrays.

:class:`FrameSplitter` is the relay-side re-framer (bytes -> whole
frames, no array decoding); :class:`StreamDecoder` is the receiver-side
strict validator (frames -> numpy block groups, template-checked).
"""

from __future__ import annotations

import json
import struct

import numpy as np

MAGIC = b"LKV1"
STREAM_MAGIC = b"LKVS"
CHUNK_MAGIC = b"LKVC"
# a header bigger than this is not a header — bound the allocation a
# hostile length prefix could ask for before json parsing sees it
_MAX_HEADER = 1 << 20
# chunk bodies are block-group sized (a few MB at 8B scale); a body
# claim past this is a lying header, not a big ship
_MAX_CHUNK_BODY = 1 << 30

# leaf names the store layout can produce; anything else is garbage
_LEAF_NAMES = {"k", "v", "k_int8", "k_scale", "v_int8", "v_scale"}


def np_dtype(name: str) -> np.dtype:
    """``np.dtype`` from its wire name, resolving the ml_dtypes extended
    set (bfloat16 & friends) that plain numpy does not register."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise ValueError(f"unknown KV wire dtype {name!r}") from None


def _leaf_template_of(first_block) -> list:
    """``[name, dtype name, shape]`` rows (name-sorted) from one block's
    first-layer leaf dict — the wire's self-description."""
    names = sorted(first_block[0])
    out = []
    for name in names:
        arr = np.asarray(first_block[0][name])
        out.append([name, arr.dtype.name, [int(d) for d in arr.shape]])
    return out


def _parse_leaves(raw) -> list:
    """Header ``leaves`` rows -> ``[(name, np.dtype, shape)]``."""
    return [(str(n), np_dtype(str(d)), tuple(int(x) for x in s))
            for n, d, s in raw]


def _leaf_sizes(leaves, block: int) -> list[int]:
    """Per-leaf byte size, validating each leaf's geometry against the
    frame's block width. Raises ValueError on anything malformed."""
    names = [n for n, _, _ in leaves]
    if len(set(names)) != len(names) or not set(names) <= _LEAF_NAMES:
        raise ValueError(f"bad KV frame leaf names {names}")
    per_leaf = []
    for name, dt, shape in leaves:
        if len(shape) != 4 or shape[0] != 1 or shape[1] != block or \
                any(d <= 0 for d in shape):
            raise ValueError(
                f"bad KV frame leaf shape {shape} for {name!r}")
        n = dt.itemsize
        for d in shape:
            n *= d
        per_leaf.append(n)
    return per_leaf


def _pack_body(blocks, names) -> list[bytes]:
    out = []
    for blk in blocks:
        for entry in blk:
            for name in names:
                arr = np.ascontiguousarray(np.asarray(entry[name]))
                out.append(arr.tobytes())
    return out


def _unpack_blocks(body, n_blocks: int, layers: int, leaves,
                   per_leaf) -> list:
    blocks, off = [], 0
    for _ in range(n_blocks):
        blk = []
        for _ in range(layers):
            entry = {}
            for (name, dt, shape), nbytes in zip(leaves, per_leaf):
                entry[name] = np.frombuffer(
                    body, dtype=dt, count=nbytes // dt.itemsize,
                    offset=off).reshape(shape)
                off += nbytes
            blk.append(entry)
        blocks.append(blk)
    return blocks


def _parse_json_header(data: bytes, magic: bytes, off: int = 0):
    """``magic | u32 len | header JSON`` at ``off`` -> (header dict,
    offset past the header). Raises ValueError on garbage; returns
    ``None`` when ``data`` is merely too short (caller buffers more)."""
    if len(data) - off < len(magic) + 4:
        return None
    if data[off:off + len(magic)] != magic:
        raise ValueError(
            f"bad KV frame magic {data[off:off + len(magic)]!r} "
            f"(want {magic!r})")
    (hlen,) = struct.unpack_from("<I", data, off + len(magic))
    if hlen <= 0 or hlen > _MAX_HEADER:
        raise ValueError(f"implausible KV frame header length {hlen}")
    hstart = off + len(magic) + 4
    if len(data) < hstart + hlen:
        return None
    try:
        header = json.loads(data[hstart:hstart + hlen])
    except (ValueError, UnicodeDecodeError) as e:
        raise ValueError(f"unparseable KV frame header: {e}") from None
    if not isinstance(header, dict) or header.get("v") != 1:
        raise ValueError("unsupported KV frame version")
    return header, hstart + hlen


def encode_frame(tokens, block: int, blocks) -> bytes:
    """Serialize ``blocks`` — a list over blocks, each a list over layers
    of ``{leaf name: array [1, block, kv_heads, d-or-1]}`` (the
    :func:`lambdipy_tpu.models.llama.slice_cache_blocks` shape) — into
    one self-describing frame covering ``tokens`` (whole blocks)."""
    tokens = [int(t) for t in tokens]
    block = int(block)
    if not blocks:
        raise ValueError("nothing to encode: no blocks")
    if len(tokens) != len(blocks) * block:
        raise ValueError(
            f"{len(tokens)} tokens do not cover {len(blocks)} x "
            f"{block}-token blocks")
    first = blocks[0]
    leaves = _leaf_template_of(first)
    names = [n for n, _, _ in leaves]
    header = {
        "v": 1,
        "tokens": tokens,
        "block": block,
        "layers": len(first),
        "n_blocks": len(blocks),
        "leaves": leaves,
    }
    hbytes = json.dumps(header).encode()
    out = [MAGIC, struct.pack("<I", len(hbytes)), hbytes]
    for blk in blocks:
        if len(blk) != len(first):
            raise ValueError("blocks disagree on layer count")
    out.extend(_pack_body(blocks, names))
    return b"".join(out)


def decode_frame(data: bytes):
    """Parse + validate a frame back into ``(tokens, block, blocks)``
    with numpy arrays. Raises :class:`ValueError` on anything malformed
    — the decode replica must reject garbage before it touches the
    radix tree."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise ValueError("KV frame must be bytes")
    data = bytes(data)
    parsed = _parse_json_header(data, MAGIC)
    if parsed is None:
        if len(data) >= len(MAGIC) and data[:len(MAGIC)] != MAGIC:
            raise ValueError("bad KV frame magic")
        raise ValueError("truncated KV frame header")
    header, body_off = parsed
    try:
        tokens = [int(t) for t in header["tokens"]]
        block = int(header["block"])
        layers = int(header["layers"])
        n_blocks = int(header["n_blocks"])
        leaves = _parse_leaves(header["leaves"])
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"bad KV frame header: {e}") from None
    if block <= 0 or layers <= 0 or n_blocks <= 0 or not leaves:
        raise ValueError("bad KV frame header: non-positive geometry")
    if len(tokens) != n_blocks * block:
        raise ValueError("KV frame tokens do not cover its blocks")
    per_leaf = _leaf_sizes(leaves, block)
    body = data[body_off:]
    expect = n_blocks * layers * sum(per_leaf)
    if len(body) != expect:
        raise ValueError(
            f"KV frame body is {len(body)} bytes, header implies "
            f"{expect}")
    return tokens, block, _unpack_blocks(body, n_blocks, layers, leaves,
                                         per_leaf)


# -- chunked stream (the pipelined ship) --------------------------------------


def encode_stream_header(tokens, block: int, layers: int,
                         leaves) -> bytes:
    """The ``LKVS`` frame opening a chunked ship: everything the
    monolithic header carried, emitted BEFORE any block exists —
    ``leaves`` is the store-layout template (``[name, dtype name,
    shape]`` rows), a constant of the server config, so the export can
    flush this while the first prefill chunk is still running."""
    tokens = [int(t) for t in tokens]
    block = int(block)
    if block <= 0 or not tokens or len(tokens) % block:
        raise ValueError(
            f"{len(tokens)} stream tokens do not cover whole "
            f"{block}-token blocks")
    header = {
        "v": 1,
        "tokens": tokens,
        "block": block,
        "layers": int(layers),
        "n_blocks": len(tokens) // block,
        "leaves": [[str(n), str(d), [int(x) for x in s]]
                   for n, d, s in leaves],
    }
    hbytes = json.dumps(header).encode()
    return b"".join([STREAM_MAGIC, struct.pack("<I", len(hbytes)),
                     hbytes])


def encode_chunk(start: int, blocks) -> bytes:
    """One ``LKVC`` frame: the block group ``blocks`` (same per-block
    shape as :func:`encode_frame`'s) at absolute block index ``start``.
    The chunk header carries its exact body byte length so a relay can
    re-frame the stream without the leaf template."""
    if not blocks:
        raise ValueError("nothing to encode: empty chunk")
    leaves = _leaf_template_of(blocks[0])
    names = [n for n, _, _ in leaves]
    body = _pack_body(blocks, names)
    nbody = sum(len(b) for b in body)
    header = {"v": 1, "start": int(start), "n_blocks": len(blocks),
              "body": nbody}
    hbytes = json.dumps(header).encode()
    return b"".join([CHUNK_MAGIC, struct.pack("<I", len(hbytes)),
                     hbytes] + body)


def pack_block_body(blocks, names) -> bytes:
    """Serialize blocks into one contiguous ``LKVC`` body under an
    ALREADY-DERIVED leaf-name order — the offload spill primitive
    (runtime/offload.py): the caller derived the template once at
    attach time, so the hot spill loop never pays
    :func:`_leaf_template_of`'s per-array introspection again."""
    return b"".join(_pack_body(blocks, names))


def encode_chunk_packed(start: int, n_blocks: int, body: bytes) -> bytes:
    """One ``LKVC`` frame over an already-packed ``body`` (see
    :func:`pack_block_body`). Byte-identical to :func:`encode_chunk`'s
    output for the same blocks, but the body bytes are REUSED — re-
    framing an offloaded page for a batched re-online costs one small
    JSON header, not a numpy re-serialization."""
    header = {"v": 1, "start": int(start), "n_blocks": int(n_blocks),
              "body": len(body)}
    hbytes = json.dumps(header).encode()
    return b"".join([CHUNK_MAGIC, struct.pack("<I", len(hbytes)),
                     hbytes, body])


def encode_stream(tokens, block: int, blocks, *,
                  group: int = 4) -> list[bytes]:
    """Whole-payload convenience (tests, scriptable stubs): the same
    ``(tokens, block, blocks)`` :func:`encode_frame` takes, as a header
    frame plus ``group``-block chunk frames."""
    tokens = [int(t) for t in tokens]
    if not blocks:
        raise ValueError("nothing to encode: no blocks")
    if len(tokens) != len(blocks) * int(block):
        raise ValueError(
            f"{len(tokens)} tokens do not cover {len(blocks)} x "
            f"{block}-token blocks")
    frames = [encode_stream_header(tokens, block, len(blocks[0]),
                                   _leaf_template_of(blocks[0]))]
    group = max(1, int(group))
    for i in range(0, len(blocks), group):
        frames.append(encode_chunk(i, blocks[i:i + group]))
    return frames


class FrameSplitter:
    """Relay-side re-framer: raw bytes in, whole ``(kind, frame)``
    tuples out (kind ``"header"`` | ``"chunk"``), no array decoding.
    Chunk body lengths come from the chunk headers' own ``body`` field
    (bounds-checked, verified against the leaf template downstream by
    :class:`StreamDecoder`), and block counts are tracked against the
    stream header so the relay knows — without trusting the transport's
    EOF — whether the stream it forwarded was complete."""

    def __init__(self):
        self._buf = b""
        self.total_blocks: int | None = None
        self.blocks_seen = 0

    @property
    def complete(self) -> bool:
        return (self.total_blocks is not None
                and self.blocks_seen >= self.total_blocks)

    def feed(self, data: bytes) -> list[tuple[str, bytes]]:
        """Buffer ``data``; return every whole frame now available.
        Raises ValueError on garbage (bad magic, lying lengths, chunks
        past the declared total, frames after completion)."""
        self._buf += bytes(data)
        out: list[tuple[str, bytes]] = []
        while True:
            frame = self._next_frame()
            if frame is None:
                return out
            out.append(frame)

    def _next_frame(self):
        buf = self._buf
        if len(buf) < 4:
            return None
        magic = buf[:4]
        if self.total_blocks is None:
            if magic != STREAM_MAGIC:
                raise ValueError(
                    f"KV stream must open with {STREAM_MAGIC!r}, got "
                    f"{magic!r}")
            parsed = _parse_json_header(buf, STREAM_MAGIC)
            if parsed is None:
                return None
            header, end = parsed
            try:
                self.total_blocks = int(header["n_blocks"])
            except (KeyError, TypeError, ValueError):
                raise ValueError("KV stream header lacks n_blocks") \
                    from None
            if self.total_blocks <= 0:
                raise ValueError("KV stream header: no blocks")
            self._buf = buf[end:]
            return "header", buf[:end]
        if self.complete:
            raise ValueError("trailing bytes after a complete KV stream")
        if magic != CHUNK_MAGIC:
            raise ValueError(
                f"bad KV chunk magic {magic!r} (want {CHUNK_MAGIC!r})")
        parsed = _parse_json_header(buf, CHUNK_MAGIC)
        if parsed is None:
            return None
        header, body_start = parsed
        try:
            n_blocks = int(header["n_blocks"])
            nbody = int(header["body"])
        except (KeyError, TypeError, ValueError):
            raise ValueError("KV chunk header lacks n_blocks/body") \
                from None
        if n_blocks <= 0 or nbody < 0 or nbody > _MAX_CHUNK_BODY:
            raise ValueError(
                f"implausible KV chunk geometry (blocks={n_blocks}, "
                f"body={nbody})")
        if self.blocks_seen + n_blocks > self.total_blocks:
            raise ValueError(
                f"KV chunk overruns the stream ({self.blocks_seen} + "
                f"{n_blocks} > {self.total_blocks} blocks)")
        end = body_start + nbody
        if len(buf) < end:
            return None
        self.blocks_seen += n_blocks
        self._buf = buf[end:]
        return "chunk", buf[:end]


class StreamDecoder:
    """Receiver-side strict validator: frames (or raw bytes) in, typed
    events out. The header event carries the parsed geometry; each
    chunk event carries ``(start, blocks)`` with numpy arrays, checked
    against the header's leaf template, the frame's own byte length,
    and strict in-order delivery (``start ==`` blocks received so far).
    A stream is only :attr:`complete` when every declared block
    arrived — truncation is therefore always detectable."""

    def __init__(self):
        self._split = FrameSplitter()
        self.tokens: list | None = None
        self.block = 0
        self.layers = 0
        self._leaves = None
        self._per_leaf = None
        self.blocks_received = 0

    @property
    def complete(self) -> bool:
        return (self.tokens is not None
                and self.blocks_received * self.block == len(self.tokens))

    def feed(self, data: bytes) -> list[tuple]:
        """Returns ``[("header", {tokens, block, layers}), ...,
        ("chunk", (start, blocks)), ...]`` for every frame completed by
        ``data``. Raises ValueError on any malformed, out-of-order, or
        template-lying frame."""
        out = []
        for kind, frame in self._split.feed(data):
            if kind == "header":
                out.append(("header", self._on_header(frame)))
            else:
                out.append(("chunk", self._on_chunk(frame)))
        return out

    def _on_header(self, frame: bytes) -> dict:
        header, _ = _parse_json_header(frame, STREAM_MAGIC)
        try:
            self.tokens = [int(t) for t in header["tokens"]]
            self.block = int(header["block"])
            self.layers = int(header["layers"])
            n_blocks = int(header["n_blocks"])
            self._leaves = _parse_leaves(header["leaves"])
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"bad KV stream header: {e}") from None
        if self.block <= 0 or self.layers <= 0 or not self._leaves:
            raise ValueError("bad KV stream header: non-positive "
                             "geometry")
        if len(self.tokens) != n_blocks * self.block:
            raise ValueError("KV stream tokens do not cover its blocks")
        self._per_leaf = _leaf_sizes(self._leaves, self.block)
        return {"tokens": self.tokens, "block": self.block,
                "layers": self.layers, "n_blocks": n_blocks}

    def _on_chunk(self, frame: bytes) -> tuple[int, list]:
        header, body_start = _parse_json_header(frame, CHUNK_MAGIC)
        start = int(header.get("start", -1))
        n_blocks = int(header["n_blocks"])
        if start != self.blocks_received:
            raise ValueError(
                f"KV chunk out of order: starts at block {start}, "
                f"expected {self.blocks_received}")
        body = frame[body_start:]
        expect = n_blocks * self.layers * sum(self._per_leaf)
        if len(body) != expect:
            raise ValueError(
                f"KV chunk body is {len(body)} bytes, the stream's "
                f"leaf template implies {expect}")
        blocks = _unpack_blocks(body, n_blocks, self.layers,
                                self._leaves, self._per_leaf)
        self.blocks_received += n_blocks
        return start, blocks

def decode_stream(frames) -> tuple:
    """Whole-stream convenience (tests): frames -> ``(tokens, block,
    blocks)``, with every per-chunk validation applied. Raises
    ValueError on truncation (missing blocks at end of input)."""
    dec = StreamDecoder()
    blocks: list = []
    for frame in frames:
        for kind, payload in dec.feed(frame):
            if kind == "chunk":
                blocks.extend(payload[1])
    if not dec.complete:
        raise ValueError(
            f"truncated KV stream: {dec.blocks_received} block(s) "
            f"arrived of {(len(dec.tokens) // dec.block) if dec.tokens else '?'}")
    return dec.tokens, dec.block, blocks
