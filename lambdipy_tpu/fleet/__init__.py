"""Replica fleet: prefix-affinity router + health-driven replica pool.

The front door that multiplies the per-replica serve stack across N
supervised bundle servers — see pool.py (spawn/probe/eject/readmit/
rolling drain), affinity.py (rendezvous hashing over leading token
blocks, matching the radix prefix cache), and router.py (the HTTP
front-door with retry/hedge/metrics-aggregation).
"""

from lambdipy_tpu.fleet.affinity import DEFAULT_BLOCK, pick_replica, prefix_key
from lambdipy_tpu.fleet.pool import (
    DRAINING,
    EJECTED,
    READY,
    STOPPED,
    FleetError,
    Replica,
    ReplicaPool,
)
from lambdipy_tpu.fleet.router import FleetRouter

__all__ = [
    "DEFAULT_BLOCK",
    "DRAINING",
    "EJECTED",
    "READY",
    "STOPPED",
    "FleetError",
    "FleetRouter",
    "Replica",
    "ReplicaPool",
    "pick_replica",
    "prefix_key",
]
