"""bench.py orchestration: staged probes, per-stage timeouts, wedge
diagnosis, fallback, and compile-cache persistence across attempts
(VERDICT r2 weak #4). All runs forced onto CPU with the tiny model so no
real chip is touched."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run_bench(tmp_path, extra_env, timeout=900):
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.update({
        "LAMBDIPY_BENCH_FORCE_PLATFORM": "cpu",
        "LAMBDIPY_BENCH_MODEL": "resnet50-tiny",
        "LAMBDIPY_BENCH_CACHE": str(tmp_path / "compile-cache"),
        **extra_env,
    })
    proc = subprocess.run([sys.executable, BENCH], capture_output=True,
                          text=True, env=env, timeout=timeout)
    line = proc.stdout.strip().splitlines()[-1]
    return proc.returncode, json.loads(line)


@pytest.mark.slow
def test_bench_happy_path_reports_stages(tmp_path):
    rc, out = _run_bench(tmp_path, {})
    assert rc == 0
    assert out["metric"] == "resnet50-tiny_b1_fwd_p50"
    assert out["value"] > 0 and out["platform"] == "cpu"
    assert out["stages"]["device.devices"] == "ok"
    assert out["stages"]["device.matmul"] == "ok"
    assert out["stages"]["device.model"] == "ok"


@pytest.mark.slow
def test_bench_wedge_is_diagnosed_and_falls_back(tmp_path):
    """A wedged primary attempt is killed by the per-stage timeout, named
    in the stages log, and the fallback attempt still produces a metric."""
    rc, out = _run_bench(tmp_path, {
        "LAMBDIPY_BENCH_WEDGE": "device.devices",
        "LAMBDIPY_BENCH_PROBE_TIMEOUT": "20",
    })
    assert rc == 0
    assert "wedge" in out["stages"]["device.devices"]
    assert out["stages"]["cpu.model"] == "ok"
    assert out["value"] > 0


@pytest.mark.slow
def test_bench_model_wedge_reuses_compile_cache(tmp_path):
    """Kill the primary attempt at the model stage; the retry must hit the
    persistent compile cache (first_compile_s collapses)."""
    rc_cold, cold = _run_bench(tmp_path, {})
    rc, out = _run_bench(tmp_path, {
        "LAMBDIPY_BENCH_WEDGE": "device.model",
        "LAMBDIPY_BENCH_TIMEOUT": "30",
    })
    assert rc_cold == 0 and rc == 0
    assert "wedge" in out["stages"]["device.model"]
    assert out["stages"]["cpu.model"] == "ok"
    # cached compile must be far cheaper than the cold one
    assert out["first_compile_s"] <= max(0.5, cold["first_compile_s"] / 2)
