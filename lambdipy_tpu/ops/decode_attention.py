"""Length-aware blocked decode attention: Pallas TPU kernel + pure-jax
reference.

Decode is memory-bound and the static-cache decode path reads the FULL
``cache_len`` K/V window every step — a row 300 tokens into an 8k-window
server streams all 8k positions from HBM per token. This op makes decode
KV bytes scale with each row's *actual* context instead of its allocated
window (the mechanism of PagedAttention / Flash-Decoding, specialized to
the repo's contiguous static cache):

- grid is ``(batch x kv_heads, kv_blocks)`` with the kv dimension
  innermost — TPU grid execution is sequential, so the online-softmax
  f32 scratch accumulators (running max / sum / weighted-V) carry across
  kv steps exactly like ``ops/attention.py``'s ``_flash_kernel``;
- a per-row ``active_len`` operand rides in scalar-prefetch (SMEM):
  blocks fully past a row's length SKIP their compute under ``pl.when``,
  and their K/V BlockSpec index maps CLAMP to the row's last active
  block — Pallas elides the DMA when consecutive grid steps map to the
  same block, so the skipped blocks cost neither FLOPs nor HBM bytes.
  The partially-active boundary block masks per-position;
- GQA-aware: each program attends ONE kv head against its ``group`` =
  heads/kv_heads query rows, so grouped K/V is read once per kv head,
  never re-read per query head;
- composes with the int8 KV layout (``models/llama.py _kv_quantize``):
  int8 values + per-position f32 scales stream through the same blocked
  index maps and dequantize in VMEM right before the dot.

The pure-jax :func:`decode_attention_reference` is the numerics oracle
and the CPU fallback. Its math mirrors ``models/llama.py _attend``
operation for operation (same einsums, same f32 ``/ sqrt(d)`` scaling,
same ``-1e9`` mask fill), so with a float KV cache its output is
BITWISE the dense decode path's — the parity the blocked backend's
on/off tests assert. ``decode_attention`` is the dispatcher the model
layer calls: the kernel on TPU when shapes tile, the reference
everywhere else (an interpret-mode Pallas call per decode-scan step
would crawl on CPU; tests exercise the kernel explicitly via
``interpret=True``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9  # matches models/llama.py _attend's mask fill


def decode_attention_reference(q, k, v, active_len, *, scale=None):
    """Length-masked GQA decode attention, dense-path-bitwise.

    q: [b, s, h, d] (s = 1 for decode steps); k/v: [b, t, kvh, d] float
    (kv heads grouped, NOT pre-broadcast); active_len: [b] int32 — row r
    attends positions ``< active_len[r]``. Returns [b, s, h, d].

    The computation is ``models/llama.py _attend`` with the validity
    mask built from ``active_len``: same grouped einsums, f32 logits
    divided by ``sqrt(d)``, ``-1e9`` fill, f32 softmax — so on the same
    inputs the output is bitwise the dense decode path's.
    """
    b, s, h, d = q.shape
    t = k.shape[1]
    kvh = k.shape[2]
    group = h // kvh
    qg = q.reshape(b, s, kvh, group, d)
    logits = jnp.einsum("bskgd,btkd->bkgst", qg, k).astype(jnp.float32)
    if scale is None:
        logits = logits / jnp.sqrt(d).astype(jnp.float32)
    else:
        logits = logits * jnp.float32(scale)
    valid = (jnp.arange(t)[None, :]
             < jnp.asarray(active_len, jnp.int32)[:, None])  # [b, t]
    logits = jnp.where(valid[:, None, None, None, :], logits,
                       jnp.float32(NEG_INF))
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)


def windowed_decode_attention_reference(q, k, v, base, local_len, window,
                                        *, scale=None):
    """LOGICAL-window decode attention over a dense big-window cache —
    the long-context tier's parity oracle.

    k/v hold the FULL logical context ``[b, T, kvh, d]`` (T >= window);
    row r's attention runs over the sliding view ``[base[r], base[r] +
    window)`` with ``local_len[r]`` positions valid inside it — exactly
    the view the block table maps for the windowed paged path
    (``models/llama.py _lpaged_seg_fn``). Implemented as slice-then-
    :func:`decode_attention_reference`: the sliced computation has
    IDENTICAL shapes and operations to what the gathered-window path
    computes on the same values, so their outputs are bitwise equal by
    the same shape-identity argument the paged reference rests on. (A
    mask-over-full-T formulation is mathematically equal but reduces
    over a different tree — allclose, not bitwise — so the SLICE is the
    oracle.)"""
    b = q.shape[0]
    base = jnp.broadcast_to(jnp.asarray(base, jnp.int32), (b,))
    k_win = jax.vmap(
        lambda kk, b0: jax.lax.dynamic_slice_in_dim(kk, b0, window, 0)
    )(k, base)
    v_win = jax.vmap(
        lambda vv, b0: jax.lax.dynamic_slice_in_dim(vv, b0, window, 0)
    )(v, base)
    return decode_attention_reference(q, k_win, v_win, local_len,
                                      scale=scale)


def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                   acc_ref, *, block_k: int, scale: float, quant: bool,
                   ks_ref=None, vs_ref=None):
    """One (row, kv-block) grid step. Scratch m/l/acc carry the online
    softmax across the sequential kv dimension; blocks past the row's
    active length skip compute entirely (their data was never fetched —
    the clamped index map re-addressed the previous block)."""
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    nk = pl.num_programs(1)
    alen = lens_ref[bh]

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(ki * block_k < alen)
    def _compute():
        q = q_ref[0]  # [group, d]
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]
        if quant:
            k = k.astype(jnp.float32) * ks_ref[0].astype(jnp.float32)
            v = v.astype(jnp.float32) * vs_ref[0].astype(jnp.float32)
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [group, block_k]
        pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < alen, s, NEG_INF)
        m_prev = m_ref[...]  # [group, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def blocked_decode_attention(q, k, v, active_len, *, k_scale=None,
                             v_scale=None, scale=None, block_k: int = 128,
                             interpret: bool | None = None):
    """The Pallas blocked decode kernel. q: [b, 1, h, d]; k/v:
    [b, t, kvh, d] (float, or int8 with ``k_scale``/``v_scale``
    [b, t, kvh, 1] f32); active_len: [b] int32, PER-ROW >= 1 — a decode
    step always attends at least its own freshly-written position (the
    model passes ``index + 1``), and the kernel relies on that: at
    ``active_len = 0`` no block ever computes, so the finalize would
    emit exact zeros where the reference emits the uniform-softmax mean
    of V. Falls back to the reference when shapes don't tile
    (t % block_k, or a multi-token q). ``interpret=None`` auto-selects
    interpret mode on the CPU backend."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    b, s, h, d = q.shape
    t = k.shape[1]
    kvh = k.shape[2]
    group = h // kvh
    quant = k_scale is not None
    block_k = min(block_k, t)
    if s != 1 or t % block_k:
        kd, vd = k, v
        if quant:
            kd = k.astype(q.dtype) * k_scale.astype(q.dtype)
            vd = v.astype(q.dtype) * v_scale.astype(q.dtype)
        return decode_attention_reference(q, kd, vd, active_len, scale=scale)
    scale = float(d ** -0.5 if scale is None else scale)
    nk = t // block_k

    # fold to per-(row, kv-head) programs: q [b*kvh, group, d],
    # k/v [b*kvh, t, d] — each program reads ONE kv head once for all
    # its group query heads (the GQA byte win)
    qf = q.reshape(b, kvh, group, d).reshape(b * kvh, group, d)

    def fold_kv(x, w):
        return x.transpose(0, 2, 1, 3).reshape(b * kvh, t, w)

    kf, vf = fold_kv(k, d), fold_kv(v, d)
    lens = jnp.repeat(jnp.asarray(active_len, jnp.int32).reshape(b), kvh)

    def kv_index(bh, ki, lens_ref):
        # clamp past-the-length blocks to the row's LAST active block:
        # consecutive identical block indices elide the DMA, so inactive
        # blocks cost no HBM traffic (their compute is pl.when-skipped)
        last = jnp.maximum(
            (lens_ref[bh] + block_k - 1) // block_k - 1, 0)
        return (bh, jnp.minimum(ki, last), 0)

    in_specs = [
        pl.BlockSpec((1, group, d), lambda bh, ki, lens: (bh, 0, 0)),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
    ]
    operands = [qf, kf, vf]
    if quant:
        in_specs += [
            pl.BlockSpec((1, block_k, 1), kv_index),
            pl.BlockSpec((1, block_k, 1), kv_index),
        ]
        operands += [fold_kv(k_scale, 1), fold_kv(v_scale, 1)]

    def kernel(lens_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
        else:
            ks_ref, vs_ref = None, None
            o_ref, m_ref, l_ref, acc_ref = rest
        _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                       acc_ref, block_k=block_k, scale=scale, quant=quant,
                       ks_ref=ks_ref, vs_ref=vs_ref)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * kvh, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, group, d), lambda bh, ki, lens: (bh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * kvh, group, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(lens, *operands)
    return out.reshape(b, kvh, group, d).reshape(b, 1, h, d)


def paged_decode_attention_reference(q, k_pages, v_pages, block_tables,
                                     active_len, *, k_scale_pages=None,
                                     v_scale_pages=None, scale=None):
    """Pure-jax oracle for PAGED decode attention, mirroring
    :func:`decode_attention_reference` operation for operation after one
    extra step: materialize each row's KV from its block table.

    q: [b, s, h, d]; k_pages/v_pages: [P, page, kvh, d] — the paged KV
    arena (``models/llama.py init_page_arena``); block_tables: [b, nb]
    int32 — row r's absolute positions ``[j*page, (j+1)*page)`` live in
    arena page ``block_tables[r, j]``; active_len: [b]. Table entries at
    or past a row's length may point anywhere (the null page): their
    values are masked to exact zeros by the same ``active_len`` mask the
    dense reference applies, so on tables whose gathered values equal a
    dense cache's the output is BITWISE the dense reference's."""
    b, nb = block_tables.shape
    page = k_pages.shape[1]
    tbl = jnp.asarray(block_tables, jnp.int32).reshape(-1)

    def gather(pages):
        g = jnp.take(pages, tbl, axis=0)  # [b*nb, page, kvh, w]
        return g.reshape(b, nb * page, *pages.shape[2:])

    k, v = gather(k_pages), gather(v_pages)
    if k_scale_pages is not None:
        k = k.astype(q.dtype) * gather(k_scale_pages).astype(q.dtype)
        v = v.astype(q.dtype) * gather(v_scale_pages).astype(q.dtype)
    return decode_attention_reference(q, k, v, active_len, scale=scale)


def _paged_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, m_ref,
                  l_ref, acc_ref, *, page: int, scale: float,
                  quant: bool, ks_ref=None, vs_ref=None):
    """One (row x kv-head, kv-page) grid step of the paged kernel: the
    same online-softmax math as ``_decode_kernel``, with the K/V block
    fetched through the row's BLOCK TABLE instead of a contiguous
    offset. The table itself is consumed ONLY by the ``kv_index``
    BlockSpec maps (scalar prefetch) — inside the kernel body the
    indirection is already done, so only the shapes differ (refs carry
    a singleton kv-head axis cut from the arena)."""
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    nk = pl.num_programs(1)
    alen = lens_ref[bh]

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(ki * page < alen)
    def _compute():
        q = q_ref[0]           # [group, d]
        k = k_ref[0, :, 0, :]  # [page, d]
        v = v_ref[0, :, 0, :]
        if quant:
            k = k.astype(jnp.float32) * ks_ref[0, :, 0, :].astype(jnp.float32)
            v = v.astype(jnp.float32) * vs_ref[0, :, 0, :].astype(jnp.float32)
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [group, page]
        pos = ki * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < alen, s, NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def paged_blocked_decode_attention(q, k_pages, v_pages, block_tables,
                                   active_len, *, k_scale_pages=None,
                                   v_scale_pages=None, scale=None,
                                   interpret: bool | None = None):
    """The Pallas PAGED decode kernel: the length-aware blocked kernel
    with the contiguous clamp in its K/V index maps replaced by a BLOCK
    TABLE lookup riding scalar-prefetch — each (row x kv-head, page)
    program DMAs exactly the arena page its table names, so a row's KV
    never has to be contiguous (and prefix pages shared between rows
    are fetched from one physical location). Shapes as
    :func:`paged_decode_attention_reference`; q must be single-token
    ([b, 1, h, d]). Past-the-length pages clamp to the row's LAST
    active table entry — consecutive identical page ids elide the DMA,
    the same early-exit economics as the contiguous kernel."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    b, s, h, d = q.shape
    if s != 1:
        return paged_decode_attention_reference(
            q, k_pages, v_pages, block_tables, active_len,
            k_scale_pages=k_scale_pages, v_scale_pages=v_scale_pages,
            scale=scale)
    page = k_pages.shape[1]
    kvh = k_pages.shape[2]
    group = h // kvh
    nb = block_tables.shape[1]
    quant = k_scale_pages is not None
    scale = float(d ** -0.5 if scale is None else scale)

    qf = q.reshape(b, kvh, group, d).reshape(b * kvh, group, d)
    lens = jnp.repeat(jnp.asarray(active_len, jnp.int32).reshape(b), kvh)
    tables = jnp.asarray(block_tables, jnp.int32)

    def kv_index(bh, ki, lens_ref, tables_ref):
        # the paged indirection: the page COORDINATE comes from the
        # row's table, clamped to its last active entry so inactive
        # grid steps re-address the previous page (DMA elided) exactly
        # like the contiguous kernel's clamp
        last = jnp.maximum((lens_ref[bh] + page - 1) // page - 1, 0)
        pid = tables_ref[bh // kvh, jnp.minimum(ki, last)]
        return (pid, 0, bh % kvh, 0)

    in_specs = [
        pl.BlockSpec((1, group, d), lambda bh, ki, lens, tabs: (bh, 0, 0)),
        pl.BlockSpec((1, page, 1, d), kv_index),
        pl.BlockSpec((1, page, 1, d), kv_index),
    ]
    operands = [qf, k_pages, v_pages]
    if quant:
        in_specs += [
            pl.BlockSpec((1, page, 1, 1), kv_index),
            pl.BlockSpec((1, page, 1, 1), kv_index),
        ]
        operands += [k_scale_pages, v_scale_pages]

    def kernel(lens_ref, tables_ref, q_ref, k_ref, v_ref, *rest):
        # tables_ref rides scalar prefetch for the kv_index maps only
        del tables_ref
        if quant:
            ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
        else:
            ks_ref, vs_ref = None, None
            o_ref, m_ref, l_ref, acc_ref = rest
        _paged_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref,
                      m_ref, l_ref, acc_ref, page=page,
                      scale=scale, quant=quant, ks_ref=ks_ref,
                      vs_ref=vs_ref)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b * kvh, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, group, d),
                               lambda bh, ki, lens, tabs: (bh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * kvh, group, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(lens, tables, *operands)
    return out.reshape(b, kvh, group, d).reshape(b, 1, h, d)


def paged_decode_attention(q, k_pages, v_pages, block_tables, active_len,
                           *, k_scale_pages=None, v_scale_pages=None,
                           scale=None, interpret: bool | None = None):
    """Backend dispatcher for paged decode attention, mirroring
    :func:`decode_attention`: the block-table kernel on TPU for
    single-token steps, the gather-then-dense reference everywhere else
    (bitwise the dense path on float KV — the runtime's paged engine
    gathers through the same tables, so the two agree by
    construction)."""
    if jax.default_backend() == "tpu" and q.shape[1] == 1:
        return paged_blocked_decode_attention(
            q, k_pages, v_pages, block_tables, active_len,
            k_scale_pages=k_scale_pages, v_scale_pages=v_scale_pages,
            scale=scale, interpret=interpret)
    return paged_decode_attention_reference(
        q, k_pages, v_pages, block_tables, active_len,
        k_scale_pages=k_scale_pages, v_scale_pages=v_scale_pages,
        scale=scale)


def decode_attention(q, k, v, active_len, *, k_scale=None, v_scale=None,
                     scale=None, block_k: int = 128,
                     interpret: bool | None = None):
    """Backend dispatcher for the ``attn_backend="blocked"`` decode path.

    On TPU with tileable shapes: the blocked kernel (real early-exit —
    bytes scale with ``active_len``). Everywhere else: the pure-jax
    reference, whose output is bitwise the dense path's on float KV —
    the byte win on the XLA path comes from the runtime's window
    bucketing instead (``runtime/continuous.py``), which shrinks ``t``
    itself. Inputs/shapes as :func:`blocked_decode_attention`."""
    if jax.default_backend() == "tpu" and q.shape[1] == 1 \
            and k.shape[1] % min(block_k, k.shape[1]) == 0:
        return blocked_decode_attention(
            q, k, v, active_len, k_scale=k_scale, v_scale=v_scale,
            scale=scale, block_k=block_k, interpret=interpret)
    if k_scale is not None:
        k = k.astype(q.dtype) * k_scale.astype(q.dtype)
        v = v.astype(q.dtype) * v_scale.astype(q.dtype)
    return decode_attention_reference(q, k, v, active_len, scale=scale)
