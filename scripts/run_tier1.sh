#!/usr/bin/env bash
# Tier-1 gate, runnable locally and in CI.
#
# Phase 1 fails FAST on collection errors: a module-level import break
# (like the tomllib one that silently knocked out 7 test files on
# Python 3.10) must turn the build red by itself, not hide behind
# --continue-on-collection-errors in the main run.
#
# Phase 2 is the EXACT tier-1 command from ROADMAP.md (its exit code
# still gates; the only change is that success falls through to phase 3
# instead of exiting inline).
#
# Phase 3 is a quick forced-CPU bench.py smoke (tiny model) so a bench
# orchestration regression turns tier-1 red, not measurement day.

set -u
cd "$(dirname "$0")/.."

echo "== phase 1: collection must be clean =="
rm -f /tmp/_t1_collect.log
env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --collect-only --continue-on-collection-errors \
    -p no:cacheprovider 2>&1 | tee /tmp/_t1_collect.log
if grep -qE '^ERROR |[0-9]+ errors? in ' /tmp/_t1_collect.log; then
    echo "FATAL: test collection errors (see above)" >&2
    exit 1
fi

echo "== phase 2: tier-1 suite (ROADMAP.md verbatim) =="
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

# Phase 3: a quick CPU bench smoke — the staged orchestration (tiny
# model, forced-cpu attempt) end to end, so a bench.py regression turns
# tier-1 red instead of surfacing at measurement time. rc != 0 fails.
echo "== phase 3: bench.py CPU smoke =="
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
    LAMBDIPY_BENCH_FORCE_PLATFORM=cpu LAMBDIPY_BENCH_MODEL=resnet50-tiny \
    python bench.py; then
    echo "FATAL: bench.py CPU smoke failed" >&2
    exit 1
fi

# Phase 4: decode-window sweep smoke (CPU reference path) — asserts
# token parity between windowed and full-window decode AND that the
# KV-read savings_ratio is < 1 for short rows and monotone in prompt
# length, so a length-aware-decode regression turns tier-1 red.
echo "== phase 4: decode-window bench smoke =="
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --decode-window; then
    echo "FATAL: bench.py --decode-window smoke failed" >&2
    exit 1
fi
exit 0
