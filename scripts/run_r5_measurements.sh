#!/bin/bash
# Round-5 on-chip measurement suite (VERDICT r5 #1, #4-#9): runs every
# measurement mode sequentially, each under its own timeout so a tunnel
# wedge skips one mode instead of hanging the suite. Raw stdout/stderr
# per mode land in $OUT; published records go to BASELINE.json via the
# modes' own --publish.
set -u
cd /root/repo
OUT=${OUT:-/tmp/r5m}
mkdir -p "$OUT"

run() {
  local name=$1 to=$2
  shift 2
  echo "=== $name start $(date -u +%FT%TZ)" | tee -a "$OUT/driver.log"
  timeout "$to" "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
  local rc=$?
  echo "=== $name rc=$rc end $(date -u +%FT%TZ)" | tee -a "$OUT/driver.log"
}

# VERDICT r5 #1 first: the speculative number is the round's top ask.
run spec_k8 2400 python scripts/measure_8b.py --speculative --publish
run spec_k4 1200 python scripts/measure_8b.py --speculative --k 4
run spec_k16 1200 python scripts/measure_8b.py --speculative --k 16
# Driver-shaped artifact with the decode8b stage on-chip (weak #1).
run bench 2400 python bench.py
# Refresh the headline b1/b8 + prefill-512 record.
run decode 2400 python scripts/measure_8b.py --publish
# VERDICT r5 #6: engine concurrent throughput.
run concurrent 2400 python scripts/measure_8b.py --concurrent --publish
# VERDICT r5 #7: int8-KV at 8B dims, 1k context.
run kvquant 3000 python scripts/measure_8b.py --kv-quant --publish
# VERDICT r5 #4 + #9: prefill table incl. flash + chunked at 8k.
run prefill 3600 python scripts/measure_8b.py --prefill-table --publish
# VERDICT r5 #5: overlapped cold start, measured end-to-end at 8B.
run coldstart 3600 python scripts/measure_8b.py --cold-start --publish
echo "=== suite done $(date -u +%FT%TZ)" | tee -a "$OUT/driver.log"
