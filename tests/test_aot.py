"""AOT store: serialized executables / StableHLO shipped in the bundle
(runtime/aot.py). The contract under test: miss -> plain jit + artifacts
written; hit -> identical numerics without re-tracing; any corruption or
environment mismatch -> silent fallback to jit."""

import json
from types import SimpleNamespace

import jax.numpy as jnp
import numpy as np
import pytest

from lambdipy_tpu.models import registry
from lambdipy_tpu.runtime.aot import AotStore, cached_jit


@pytest.fixture()
def tiny_model():
    adapter = registry.get("resnet50-tiny").build(dtype="float32")
    params = adapter.init_params(seed=0, batch_size=1)
    x = adapter.example_batch(1)[0]
    return adapter, params, x


def _ctx(tmp_path):
    return SimpleNamespace(bundle_dir=tmp_path)


def test_miss_jits_and_writes_artifacts(tmp_path, tiny_model):
    adapter, params, x = tiny_model
    fn, src = cached_jit(_ctx(tmp_path), "forward", adapter.forward, (params, x))
    assert src == "jit"
    out = np.asarray(fn(params, x))
    aot_dir = tmp_path / "aot"
    metas = list(aot_dir.glob("forward.*.json"))
    assert metas, "miss should write AOT artifacts for the next boot"
    meta = json.loads(metas[0].read_text())
    assert "hlo" in meta["tiers"]
    assert np.all(np.isfinite(out))


def test_hit_matches_jit_numerics(tmp_path, tiny_model):
    adapter, params, x = tiny_model
    ctx = _ctx(tmp_path)
    fn0, src0 = cached_jit(ctx, "forward", adapter.forward, (params, x))
    expected = np.asarray(fn0(params, x))

    fn1, src1 = cached_jit(ctx, "forward", adapter.forward, (params, x))
    assert src1 in ("exec", "hlo"), f"second boot should hit AOT, got {src1}"
    got = np.asarray(fn1(params, x))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)


def test_env_mismatch_falls_back_to_jit(tmp_path, tiny_model):
    adapter, params, x = tiny_model
    ctx = _ctx(tmp_path)
    cached_jit(ctx, "forward", adapter.forward, (params, x))
    meta_path = next((tmp_path / "aot").glob("forward.*.json"))
    meta = json.loads(meta_path.read_text())
    meta["jaxlib"] = "0.0.0-other"
    meta_path.write_text(json.dumps(meta))

    store = AotStore(tmp_path)
    assert store.load("forward") is None


def test_corrupt_artifact_falls_back(tmp_path, tiny_model):
    adapter, params, x = tiny_model
    ctx = _ctx(tmp_path)
    cached_jit(ctx, "forward", adapter.forward, (params, x))
    for f in (tmp_path / "aot").glob("forward.*"):
        if f.suffix in (".hlo", ".exec"):
            f.write_bytes(b"garbage")
    fn, src = cached_jit(ctx, "forward", adapter.forward, (params, x))
    assert src == "jit"
    assert np.all(np.isfinite(np.asarray(fn(params, x))))


def test_aot_hit_still_serves_other_batch_sizes(tmp_path):
    """An AOT artifact is shape-specialized to the spec's example batch;
    requests with a different batch must still work (plain-jit fallback in
    handlers._aot_or_jit), not 500."""
    from lambdipy_tpu.runtime import handlers

    spec = {"model": "resnet50-tiny", "dtype": "float32", "batch_size": 1}
    ctx = SimpleNamespace(bundle_dir=tmp_path, manifest={}, params_dir=None,
                          spec=spec)
    handlers.image_classify_handler(spec, ctx)  # miss: writes artifacts
    h = handlers.image_classify_handler(spec, ctx)
    assert h.meta["aot"] in ("exec", "hlo")

    adapter = registry.get("resnet50-tiny").build(dtype="float32")
    batch2 = np.asarray(adapter.example_batch(2)[0], dtype=np.float32)
    out = h.invoke({"image": batch2.tolist()})
    assert out["ok"] and len(out["top1"]) == 2
    out1 = h.invoke({"random": True})
    assert out1["ok"] and len(out1["top1"]) == 1


def test_different_dtype_entry_points_coexist(tmp_path):
    adapter = registry.get("resnet50-tiny").build(dtype="bfloat16")
    params = adapter.init_params(seed=0, batch_size=1)
    x = adapter.example_batch(1)[0]
    ctx = _ctx(tmp_path)
    store = AotStore(tmp_path)
    store.save("fwd_bf16", adapter.forward, (params, x))
    hit = store.load("fwd_bf16", (params, x))
    assert hit is not None
    fn, tier = hit
    out = np.asarray(fn(params, x), dtype=np.float32)
    assert out.dtype == np.float32 and np.all(np.isfinite(out))
    assert jnp.asarray(x).dtype == jnp.bfloat16
