"""ResNet-50 in flax.linen, bf16-first for the v5e MXU.

BASELINE.json config 3 / north star: image-classify at <15 ms p50 on
v5e-1. Design notes for the MXU: NHWC layout (XLA's native TPU conv
layout), bf16 activations and conv kernels, fp32 batch-norm statistics
(numerics), no dynamic shapes anywhere.
"""

from __future__ import annotations

from collections.abc import Sequence
from functools import partial

import jax.numpy as jnp
from flax import linen as nn


class BottleneckBlock(nn.Module):
    features: int
    strides: int = 1
    projection: bool = False
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32)
        residual = x
        y = conv(self.features, (1, 1), name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), strides=(self.strides, self.strides),
                 padding=[(1, 1), (1, 1)], name="conv2")(y)
        y = norm(name="bn2")(y)
        y = nn.relu(y)
        y = conv(self.features * 4, (1, 1), name="conv3")(y)
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if self.projection:
            residual = conv(self.features * 4, (1, 1),
                            strides=(self.strides, self.strides), name="proj_conv")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5,
                         dtype=self.dtype, param_dtype=jnp.float32, name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, block_count in enumerate(self.stage_sizes):
            features = self.width * (2 ** i)
            for j in range(block_count):
                x = BottleneckBlock(
                    features=features,
                    strides=2 if (i > 0 and j == 0) else 1,
                    projection=(j == 0),
                    dtype=self.dtype,
                    name=f"stage{i + 1}_block{j + 1}",
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        # classifier head in fp32 for logit numerics
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


def resnet50(num_classes: int = 1000, dtype=jnp.bfloat16, width: int = 64) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes,
                  width=width, dtype=dtype)


def resnet_tiny(num_classes: int = 10, dtype=jnp.bfloat16) -> ResNet:
    """Small variant for tests and CPU-mesh dry runs."""
    return ResNet(stage_sizes=(1, 1), num_classes=num_classes, width=8, dtype=dtype)
