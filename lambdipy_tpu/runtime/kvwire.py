"""KV-block wire framing for disaggregated prefill/decode serving.

A prefill-class replica exports the whole-block KV of a prompt head;
the router ships the frame to the affinity-chosen decode replica, whose
import is just a radix insert (runtime/prefixstore.py). The frame is the
ONLY thing that crosses the wire, so its contract is deliberately
minimal and self-describing:

``LKV1 | u32 header_len | header JSON | raw leaf bytes``

The header names the covered tokens, the block width, and the per-layer
leaf template (name, dtype, shape) — one template, because every block
of every layer stores the same store-layout leaves (``k``/``v`` float,
or ``k_int8``/``k_scale``/``v_int8``/``v_scale`` under ``kv_quant``:
int8 scales travel as first-class leaves, not a side channel). The body
is raw array bytes in a fixed order — block-major, then layer, then
leaf name sorted — so decode needs no per-array framing.

Decoding VALIDATES before any array is built: magic, header JSON, leaf
sanity, and the exact byte length the template implies. A truncated,
padded, or shape-lying frame raises :class:`ValueError` — the import
endpoint maps that to a 400, and a garbage frame can never insert
mis-shaped KV into a serving replica's radix tree.

Dtypes round-trip by name through numpy, with the ml_dtypes extended
set (``bfloat16``) resolved explicitly — a bf16 bundle ships its KV
bitwise, not through a float32 detour.
"""

from __future__ import annotations

import json
import struct

import numpy as np

MAGIC = b"LKV1"
# a header bigger than this is not a header — bound the allocation a
# hostile length prefix could ask for before json parsing sees it
_MAX_HEADER = 1 << 20

# leaf names the store layout can produce; anything else is garbage
_LEAF_NAMES = {"k", "v", "k_int8", "k_scale", "v_int8", "v_scale"}


def np_dtype(name: str) -> np.dtype:
    """``np.dtype`` from its wire name, resolving the ml_dtypes extended
    set (bfloat16 & friends) that plain numpy does not register."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise ValueError(f"unknown KV wire dtype {name!r}") from None


def encode_frame(tokens, block: int, blocks) -> bytes:
    """Serialize ``blocks`` — a list over blocks, each a list over layers
    of ``{leaf name: array [1, block, kv_heads, d-or-1]}`` (the
    :func:`lambdipy_tpu.models.llama.slice_cache_blocks` shape) — into
    one self-describing frame covering ``tokens`` (whole blocks)."""
    tokens = [int(t) for t in tokens]
    block = int(block)
    if not blocks:
        raise ValueError("nothing to encode: no blocks")
    if len(tokens) != len(blocks) * block:
        raise ValueError(
            f"{len(tokens)} tokens do not cover {len(blocks)} x "
            f"{block}-token blocks")
    first = blocks[0]
    names = sorted(first[0])
    leaves = []
    for name in names:
        arr = np.asarray(first[0][name])
        leaves.append([name, arr.dtype.name, [int(d) for d in arr.shape]])
    header = {
        "v": 1,
        "tokens": tokens,
        "block": block,
        "layers": len(first),
        "n_blocks": len(blocks),
        "leaves": leaves,
    }
    hbytes = json.dumps(header).encode()
    out = [MAGIC, struct.pack("<I", len(hbytes)), hbytes]
    for blk in blocks:
        if len(blk) != len(first):
            raise ValueError("blocks disagree on layer count")
        for entry in blk:
            for name in names:
                arr = np.ascontiguousarray(np.asarray(entry[name]))
                out.append(arr.tobytes())
    return b"".join(out)


def decode_frame(data: bytes):
    """Parse + validate a frame back into ``(tokens, block, blocks)``
    with numpy arrays. Raises :class:`ValueError` on anything malformed
    — the decode replica must reject garbage before it touches the
    radix tree."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise ValueError("KV frame must be bytes")
    data = bytes(data)
    if len(data) < len(MAGIC) + 4 or data[:len(MAGIC)] != MAGIC:
        raise ValueError("bad KV frame magic")
    (hlen,) = struct.unpack_from("<I", data, len(MAGIC))
    if hlen <= 0 or hlen > _MAX_HEADER:
        raise ValueError(f"implausible KV frame header length {hlen}")
    hstart = len(MAGIC) + 4
    if len(data) < hstart + hlen:
        raise ValueError("truncated KV frame header")
    try:
        header = json.loads(data[hstart:hstart + hlen])
    except (ValueError, UnicodeDecodeError) as e:
        raise ValueError(f"unparseable KV frame header: {e}") from None
    if not isinstance(header, dict) or header.get("v") != 1:
        raise ValueError("unsupported KV frame version")
    try:
        tokens = [int(t) for t in header["tokens"]]
        block = int(header["block"])
        layers = int(header["layers"])
        n_blocks = int(header["n_blocks"])
        leaves = [(str(n), np_dtype(str(d)), tuple(int(x) for x in s))
                  for n, d, s in header["leaves"]]
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"bad KV frame header: {e}") from None
    if block <= 0 or layers <= 0 or n_blocks <= 0 or not leaves:
        raise ValueError("bad KV frame header: non-positive geometry")
    if len(tokens) != n_blocks * block:
        raise ValueError("KV frame tokens do not cover its blocks")
    names = [n for n, _, _ in leaves]
    if len(set(names)) != len(names) or not set(names) <= _LEAF_NAMES:
        raise ValueError(f"bad KV frame leaf names {names}")
    per_leaf = []
    for name, dt, shape in leaves:
        if len(shape) != 4 or shape[0] != 1 or shape[1] != block or \
                any(d <= 0 for d in shape):
            raise ValueError(
                f"bad KV frame leaf shape {shape} for {name!r}")
        n = dt.itemsize
        for d in shape:
            n *= d
        per_leaf.append(n)
    body = data[hstart + hlen:]
    expect = n_blocks * layers * sum(per_leaf)
    if len(body) != expect:
        raise ValueError(
            f"KV frame body is {len(body)} bytes, header implies "
            f"{expect}")
    blocks = []
    off = 0
    for _ in range(n_blocks):
        blk = []
        for _ in range(layers):
            entry = {}
            for (name, dt, shape), nbytes in zip(leaves, per_leaf):
                entry[name] = np.frombuffer(
                    body, dtype=dt, count=nbytes // dt.itemsize,
                    offset=off).reshape(shape)
                off += nbytes
            blk.append(entry)
        blocks.append(blk)
    return tokens, block, blocks
