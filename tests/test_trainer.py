"""Trainer loop: learning, logging cadence, checkpoint resume continuity,
eval, and the `lambdipy train` CLI surface."""

import json

import numpy as np
import pytest

from lambdipy_tpu.data import ShardedLoader, TokenSource
from lambdipy_tpu.models import registry
from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
from lambdipy_tpu.train.loop import Trainer, TrainerConfig


def _patterned_tokens(n=4000):
    return np.tile(np.arange(50, dtype=np.int32), n // 50)


def _loader(seq_len=16, batch=4, seed=5):
    return ShardedLoader(TokenSource(_patterned_tokens(), seq_len), batch,
                         seed=seed, process_index=0, process_count=1)


def test_trainer_learns_and_logs(cpu_devices):
    import jax

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
    cfg = TrainerConfig(total_steps=12, log_every=4)
    with use_mesh(mesh):
        trainer = Trainer(adapter.forward, params, mesh, adapter.tp_rules,
                          _loader(), cfg)
        report = trainer.run()
    assert report.final_step == 12 and report.steps_run == 12
    assert [r["step"] for r in report.history] == [4, 8, 12]
    assert report.history[-1]["loss"] < report.history[0]["loss"]


@pytest.mark.slow  # >14 s; sibling tests keep this surface in tier-1 (wall budget)
def test_trainer_resume_continues_exactly(cpu_devices, tmp_path):
    import jax

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])

    # one uninterrupted 8-step run
    with use_mesh(mesh):
        solo = Trainer(adapter.forward, params, mesh, adapter.tp_rules,
                       _loader(), TrainerConfig(total_steps=8, log_every=8))
        solo_report = solo.run()
        solo_params = jax.device_get(solo.state.params)

    # the same 8 steps as 4 + crash + resume 4
    with use_mesh(mesh):
        first = Trainer(adapter.forward, params, mesh, adapter.tp_rules,
                        _loader(), TrainerConfig(total_steps=4, log_every=4,
                                                 ckpt_every=2),
                        ckpt_dir=tmp_path / "ck")
        first.run()
    with use_mesh(mesh):
        second = Trainer(adapter.forward, params, mesh, adapter.tp_rules,
                         _loader(seed=999),  # wrong seed: must be overridden
                         TrainerConfig(total_steps=8, log_every=8,
                                       ckpt_every=2),
                         ckpt_dir=tmp_path / "ck")
        assert second.resumed_from == 4
        assert second.loader.state.seed == 5  # loader cursor restored
        report = second.run()
        resumed_params = jax.device_get(second.state.params)
    assert report.final_step == 8 and report.steps_run == 4

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-5, atol=1e-6),
        solo_params, resumed_params)
    assert report.history[-1]["loss"] == pytest.approx(
        solo_report.history[-1]["loss"], rel=1e-4)


def test_trainer_evaluate(cpu_devices):
    import jax

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
    with use_mesh(mesh):
        trainer = Trainer(adapter.forward, params, mesh, adapter.tp_rules,
                          _loader(), TrainerConfig(total_steps=10, log_every=10))
        before = trainer.evaluate(_loader(seed=77), batches=2)
        trainer.run()
        after = trainer.evaluate(_loader(seed=77), batches=2)
    assert np.isfinite(before) and np.isfinite(after)
    assert after < before  # 10 steps on patterned data must help


def test_train_cli_runs_and_resumes(tmp_path):
    from click.testing import CliRunner

    from lambdipy_tpu.cli import main

    np.save(tmp_path / "toks.npy", _patterned_tokens())
    args = ["train", "--model", "llama-tiny", "--data", str(tmp_path / "toks.npy"),
            "--steps", "4", "--batch", "4", "--seq-len", "16",
            "--ckpt-dir", str(tmp_path / "ck"), "--ckpt-every", "2",
            "--mesh", "dp=1"]
    r = CliRunner().invoke(main, args)
    assert r.exit_code == 0, r.output
    out = json.loads(r.output.strip().splitlines()[-1])
    assert out["final_step"] == 4 and out["resumed_from"] is None

    r2 = CliRunner().invoke(main, [*args[:5], "--steps", "6", *args[7:]])
    assert r2.exit_code == 0, r2.output
    out2 = json.loads(r2.output.strip().splitlines()[-1])
    assert out2["resumed_from"] == 4 and out2["final_step"] == 6
    assert out2["steps_run"] == 2


def test_train_cli_rejects_bad_mesh(tmp_path):
    from click.testing import CliRunner

    from lambdipy_tpu.cli import main

    np.save(tmp_path / "toks.npy", _patterned_tokens())
    r = CliRunner().invoke(main, ["train", "--data", str(tmp_path / "toks.npy"),
                                  "--steps", "1", "--mesh", "dp2"])
    assert r.exit_code != 0
    assert "bad --mesh entry" in r.output
