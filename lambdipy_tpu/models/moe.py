"""Sparse Mixture-of-Experts MLP with expert parallelism over ``ep``.

New TPU-first surface (the reference has no model code at all — SURVEY.md
§3.2); this is the Mixtral-style sparse FFN for the Llama family
(models/llama.py wires it in when ``LlamaConfig.moe_experts > 0``).

TPU-first choices:
- **Dense dispatch** (GShard/Switch formulation): routing becomes one-hot
  einsums over a *static* expert-capacity dim — [tokens, experts, capacity]
  dispatch/combine tensors, no gather/scatter, no dynamic shapes, everything
  tiles onto the MXU and jits cleanly. Overflow tokens are dropped (their
  residual path carries them), the standard capacity-factor trade.
- **Expert parallelism is annotation**: expert-stacked weights [E, ...]
  shard ``P("ep", ...)`` via the registry rules, and the dispatched
  activations get a ``with_sharding_constraint`` so XLA inserts the
  all-to-all over ICI (scaling-book recipe; no hand-rolled transport).
- fp32 router and softmax (routing is precision-sensitive), bf16 expert
  matmuls; the load-balance auxiliary loss (Switch eq. 4 shape) is sown as
  an intermediate for the train step to read.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn




def route_topk(probs, top_k: int, capacity: int, valid=None):
    """GShard-style top-k routing with a static per-expert capacity.

    probs: [t, e] fp32 router probabilities. Returns
    (dispatch [t, e, c] {0,1}, combine [t, e, c] fp32, aux_loss scalar).
    Slot priority: all tokens' first choices are seated before any second
    choice, so a token's top expert is the last to drop it on overflow.
    ``valid``: optional [t] bool — invalid (padding) tokens are never
    seated and are excluded from the balance loss.
    """
    t, e = probs.shape
    gates, idx = jax.lax.top_k(probs, top_k)  # [t, k]
    gates = gates / jnp.maximum(jnp.sum(gates, axis=-1, keepdims=True), 1e-9)
    onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [t, k, e]
    if valid is not None:
        onehot = onehot * valid.astype(jnp.float32)[:, None, None]

    # accumulate per slot (static tiny top_k loop) so peak memory stays at
    # the [t, e, c] of the result tensors instead of top_k times that
    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    counts = jnp.zeros((e,), jnp.float32)  # queue length after prior slots
    for slot in range(top_k):
        oh = onehot[:, slot, :]  # [t, e]
        pos = jnp.cumsum(oh, axis=0) - 1.0 + counts[None, :]
        keep = (pos < capacity) & (oh > 0)
        seated = jnp.where(
            keep[..., None],
            jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32),
            0.0)  # [t, e, c]
        dispatch = dispatch + seated
        combine = combine + seated * gates[:, slot][:, None, None]
        counts = counts + jnp.sum(oh, axis=0)

    # Switch-Transformer load-balance loss: E * <frac tokens per expert> ·
    # <mean router prob per expert>; minimized at uniform routing
    w = (jnp.ones((t,), jnp.float32) if valid is None
         else valid.astype(jnp.float32))
    n = jnp.maximum(jnp.sum(w), 1.0)
    frac_tokens = jnp.sum(onehot[:, 0, :], axis=0) / n  # first-choice assignment
    mean_probs = jnp.sum(probs * w[:, None], axis=0) / n
    aux = e * jnp.sum(frac_tokens * mean_probs)
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Top-k routed SwiGLU experts, expert dim sharded over ``ep``.

    ``quant="int8"``: expert weights stored int8 with per-(expert, output-
    channel) fp32 scales — the experts are the dominant parameters of an
    MoE model, so they must join the 1-byte/param HBM budget that int8
    serving relies on (same scheme as llama.py QDense; real weights come
    through llama.quantize_params which handles the 3-D expert stacks).
    """

    num_experts: int
    mlp: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    quant: str | None = None
    # Routing-group size (GShard): tokens route within fixed-size groups,
    # so per-group capacity is a CONSTANT and the dispatch/combine tensors
    # are [g, gs, e, c] — linear in total tokens, not the O(t^2) of a
    # single global group whose capacity grows with t.
    group_size: int = 256

    def _expert_weight(self, name: str, shape):
        if self.quant == "int8":
            def init_int8(key, shape, _dtype):
                w = nn.initializers.lecun_normal(batch_axis=(0,))(
                    key, shape, jnp.float32)
                scale = jnp.max(jnp.abs(w), axis=1, keepdims=True) / 127.0
                return jnp.round(w / jnp.maximum(scale, 1e-8)).astype(jnp.int8)

            w_i8 = self.param(f"{name}_int8", init_int8, shape, jnp.int8)
            scale = self.param(
                f"{name}_scale",
                nn.initializers.constant(1.0 / (127.0 * shape[1] ** 0.5)),
                (shape[0], 1, shape[2]), jnp.float32)
            return w_i8.astype(self.dtype) * scale.astype(self.dtype)
        return self.param(name, nn.initializers.lecun_normal(batch_axis=(0,)),
                          shape, self.dtype)

    @nn.compact
    def __call__(self, x):
        b, s, hidden = x.shape
        e, m = self.num_experts, self.mlp
        tokens = x.reshape(b * s, hidden)
        t = tokens.shape[0]
        gs = min(t, self.group_size)
        g = -(-t // gs)
        pad = g * gs - t
        capacity = max(1, int(self.capacity_factor * self.top_k * gs / e))

        router = self.param("router", nn.initializers.lecun_normal(),
                            (hidden, e), jnp.float32)
        probs = jax.nn.softmax(tokens.astype(jnp.float32) @ router, axis=-1)
        valid = jnp.ones((t,), jnp.bool_)
        if pad:
            tokens = jnp.pad(tokens, ((0, pad), (0, 0)))
            probs = jnp.pad(probs, ((0, pad), (0, 0)))
            valid = jnp.pad(valid, (0, pad))
        vg = valid.reshape(g, gs)
        dispatch, combine, aux = jax.vmap(
            lambda p, v: route_topk(p, self.top_k, capacity, valid=v))(
                probs.reshape(g, gs, e), vg)
        # combine per-group balance losses weighted by valid-token count —
        # an unweighted mean would let a mostly-padding tail group's few
        # tokens dominate the gradient
        n_g = jnp.sum(vg.astype(jnp.float32), axis=-1)
        self.sow("intermediates", "moe_aux_loss",
                 jnp.sum(aux * n_g) / jnp.maximum(jnp.sum(n_g), 1.0))

        w_gate = self._expert_weight("experts_gate", (e, hidden, m))
        w_up = self._expert_weight("experts_up", (e, hidden, m))
        w_down = self._expert_weight("experts_down", (e, m, hidden))

        from lambdipy_tpu.parallel.sharding import shard_hint

        # dispatch all-to-all: token groups (dp-sharded) -> expert shards
        # (ep); [g, e, c, h] with c constant per group => linear in tokens
        xe = jnp.einsum("gtec,gth->gech", dispatch.astype(self.dtype),
                        tokens.reshape(g, gs, hidden).astype(self.dtype))
        xe = shard_hint(xe, None, "ep")
        gate = jnp.einsum("gech,ehm->gecm", xe, w_gate)
        up = jnp.einsum("gech,ehm->gecm", xe, w_up)
        ye = jnp.einsum("gecm,emh->gech", nn.silu(gate) * up, w_down)
        ye = shard_hint(ye, None, "ep")
        # combine all-to-all back to token order, weighted by router gates
        out = jnp.einsum("gtec,gech->gth", combine.astype(self.dtype), ye)
        out = out.reshape(g * gs, hidden)[:t]
        return out.reshape(b, s, hidden).astype(x.dtype)


def moe_aux_loss(intermediates) -> jax.Array:
    """Sum every sown ``moe_aux_loss`` in an intermediates collection."""
    leaves = [
        jnp.sum(jnp.asarray(v))
        for path, v in jax.tree_util.tree_leaves_with_path(intermediates)
        if any(getattr(k, "key", None) == "moe_aux_loss" for k in path)
    ]
    return sum(leaves, jnp.float32(0.0))
