"""Speculative decoding inside the continuous engine (spec_k): batched
draft/verify with collector rollback, bitwise the plain engine for
greedy AND seeded-sampled rows (chain-deterministic acceptance).

Wall-clock discipline: every non-slow test shares ONE engine shape
(slots=2, segment=4, kb=4) over the session tiny_server so the
("spec_seg", ...) program family compiles once for the module; the
bench gate (`bench.py --spec`, tier-1 phase 10) carries the expensive
matrix (paged, depths, concurrency scale) — the `slow`-marked tests
here are its in-repo twins."""

import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from lambdipy_tpu.runtime.continuous import ContinuousBatcher


def _mk(tiny_server, **kw):
    args = dict(slots=2, segment=4, spec_k=4)
    args.update(kw)
    return ContinuousBatcher(tiny_server, **args)


def _fresh_metrics(cb):
    """Engines share the server's SpecDecodeStats by default (one
    /metrics surface); tests that assert counters isolate them."""
    from lambdipy_tpu.runtime.metrics import SpecDecodeStats

    cb.spec_metrics = SpecDecodeStats()
    return cb.spec_metrics


def test_spec_engine_matches_solo_greedy(tiny_server):
    """The bitwise contract: concurrent staggered rows through a
    spec_k engine emit exactly their solo greedy outputs — speculation
    changes tokens-per-weight-read, never the tokens."""
    cb = _mk(tiny_server)
    prompts = [[1, 2, 3, 5], [9, 8, 7]]
    n = 12
    solo = [tiny_server.generate(p, max_new_tokens=n) for p in prompts]
    results = [None] * 2

    def run(i):
        time.sleep(0.01 * i)  # staggered arrivals, mid-flight joins
        results[i] = cb.generate(prompts[i], max_new_tokens=n)

    with ThreadPoolExecutor(max_workers=2) as ex:
        list(ex.map(run, range(2)))
    for i in range(2):
        np.testing.assert_array_equal(results[i], solo[i],
                                      err_msg=f"request {i} diverged")
    stats = cb.stats()
    assert stats["spec"]["k"] == 4
    assert stats["spec"]["steps"] > 0


def test_spec_engine_sampled_rows_bitwise(tiny_server):
    """Seeded-sampled rows keep their reproducibility promise through
    the verify chunks: acceptance re-derives the row's own PRNG chain,
    so the engine output equals solo sampling bitwise — the property
    rejection-sampling verification cannot offer."""
    cb = _mk(tiny_server)
    prompts = [[5, 6, 7], [1, 2, 3, 4]]
    kws = [dict(temperature=0.9, seed=7),
           dict(temperature=0.7, top_k=16, top_p=0.9, seed=3)]
    solo = [tiny_server.generate(p, max_new_tokens=10, **kw)
            for p, kw in zip(prompts, kws)]
    with ThreadPoolExecutor(max_workers=2) as ex:
        outs = list(ex.map(
            lambda a: cb.generate(a[0], max_new_tokens=10, **a[1]),
            zip(prompts, kws)))
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o, solo[i], err_msg=f"row {i}")


def test_spec_engine_accepts_on_repetitive_decode(tiny_server):
    """A prompt whose greedy decode cycles verifies >1 token per weight
    read through the engine, and the counters ride stats()['spec']."""
    cb = _mk(tiny_server)
    metrics = _fresh_metrics(cb)
    ref = tiny_server.generate([5, 6, 7, 8], max_new_tokens=32)
    out = cb.generate([5, 6, 7, 8], max_new_tokens=32)
    np.testing.assert_array_equal(out, ref)
    rep = metrics.report()
    assert rep["tokens_per_step"] > 1.0, rep
    assert rep["emitted_tokens"] >= 32, rep
    assert rep["acceptance_rate"] > 0.0, rep
    assert rep["tokens_per_step_hist"], rep


def test_spec_engine_eos_inside_accepted_block(tiny_server):
    """EOS emitted mid-draft-block latches exactly like the plain
    engine: host-side truncation + filler parity with the fused path."""
    cb = _mk(tiny_server)
    free = tiny_server.generate([5, 6, 7, 8], max_new_tokens=10)[0]
    eos = int(free[3])
    ref = tiny_server.generate([5, 6, 7, 8], max_new_tokens=10,
                               eos_id=eos)
    out = cb.generate([5, 6, 7, 8], max_new_tokens=10, eos_id=eos)
    np.testing.assert_array_equal(out, ref)


def test_spec_engine_stream_and_logprobs(tiny_server):
    """Streamed chunks (per-segment slices of accepted tokens)
    concatenate to the fused output, and logprobs ride the same fetch."""
    cb = _mk(tiny_server)
    ref_t, ref_l = tiny_server.generate([1, 2, 3], max_new_tokens=12,
                                        return_logprobs=True)
    got = list(cb.generate_stream([1, 2, 3], max_new_tokens=12,
                                  return_logprobs=True))
    st = np.concatenate([c for c, _ in got], axis=1)
    sl = np.concatenate([lp for _, lp in got], axis=1)
    np.testing.assert_array_equal(st[:, :12], ref_t)
    np.testing.assert_allclose(sl[:, :12], ref_l, rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # bench.py --spec (tier-1 phase 10) gates depth-1/2
# parity on every CI pass; this is its in-repo twin
def test_spec_engine_pipeline_depth2(tiny_server):
    """Depth-2 pipelining composes: in-flight records carry
    dispatch-time draft state (lookup extrapolated across in-flight
    steps), the collector reconciles from fetched truth, and outputs
    stay bitwise depth-1's (== solo's) for greedy and sampled rows.
    Same engine shape as the rest of the module — depth is host-side,
    so no new programs compile."""
    prompts = [[5, 6, 7, 8], [2, 4, 6]]
    solo = [tiny_server.generate(p, max_new_tokens=16) for p in prompts]
    solo_s = tiny_server.generate([5, 6, 7, 8], max_new_tokens=16,
                                  temperature=0.8, seed=5)
    cb = _mk(tiny_server, pipeline_depth=2)
    with ThreadPoolExecutor(max_workers=2) as ex:
        outs = list(ex.map(
            lambda p: cb.generate(p, max_new_tokens=16), prompts))
    for o, r in zip(outs, solo):
        np.testing.assert_array_equal(o, r)
    np.testing.assert_array_equal(
        cb.generate([5, 6, 7, 8], max_new_tokens=16, temperature=0.8,
                    seed=5), solo_s)


def test_spec_engine_prefix_rows_join(tiny_server):
    """A prefix= row joins the speculative engine from its cached KV;
    the prefix tokens feed the drafts and output parity holds."""
    cb = _mk(tiny_server)
    prefix, suffix = list(range(1, 20)), [4, 5]
    ref = tiny_server.generate(prefix + suffix, max_new_tokens=12)
    out = cb.generate(suffix, max_new_tokens=12, prefix=prefix)
    np.testing.assert_array_equal(out, ref)
    assert cb.prefix_joins == 1


def test_spec_k_normalization(tiny_server):
    """spec_k <= 1 disables (k=1 IS the plain path); k bucketizes to a
    pow-2 so the program count stays bounded."""
    assert ContinuousBatcher(tiny_server, spec_k=0).spec_k == 0
    assert ContinuousBatcher(tiny_server, spec_k=1).spec_k == 0
    assert ContinuousBatcher(tiny_server, spec_k=3).spec_k == 4
    assert ContinuousBatcher(tiny_server, spec_k=8).spec_k == 8


def test_spec_engine_replay_after_failure(tiny_server, monkeypatch):
    """An engine failure mid-spec-decode replays no-bytes rows through a
    restarted engine bitwise (chain-deterministic acceptance makes the
    replay independent of what the new drafts propose)."""
    ref = tiny_server.generate([5, 6, 7, 8], max_new_tokens=12,
                               temperature=0.8, seed=9)
    cb = _mk(tiny_server, max_replays=1)
    real = cb._spec_draft
    state = {"n": 0}

    def flaky(entry, kb, q=None, **kw):
        state["n"] += 1
        if state["n"] == 2:
            raise RuntimeError("injected draft-time failure")
        return real(entry, kb, q, **kw)

    monkeypatch.setattr(cb, "_spec_draft", flaky)
    out = cb.generate([5, 6, 7, 8], max_new_tokens=12, temperature=0.8,
                      seed=9)
    np.testing.assert_array_equal(out, ref)
    assert cb.fault_stats.replays_attempted >= 1


@pytest.mark.slow  # fresh model + paged program family; bench.py --spec
# (tier-1 phase 10) runs the paged parity matrix on every CI pass
def test_spec_engine_paged_parity():
    """The paged twin (_spec_pseg_fn): gather/verify/scatter through
    block tables, rejected tails absorbed by the null page — cold,
    prefix-hit (zero-copy pages) and sampled rows all bitwise solo."""
    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
    from lambdipy_tpu.runtime.pagepool import PagePool, page_width
    from lambdipy_tpu.runtime.prefixstore import PrefixStore

    adapter = registry.get("llama-tiny").build()
    cfg = adapter.config
    server = adapter.make_server(adapter.init_params(seed=0))
    block = 16
    page = page_width(cfg.max_len, block)
    n_pages = 2 * (cfg.max_len // page) + 1
    pool = PagePool(n_pages=n_pages, page=page,
                    page_bytes=page_kv_bytes(cfg, page),
                    make_arena=lambda n=n_pages: init_page_arena(
                        cfg, n, page))
    cb = ContinuousBatcher(server, slots=2, segment=4, page_pool=pool,
                           spec_k=4)
    store = PrefixStore(server, block=block, budget_mb=16, pool=pool)
    cb.prefix_pages_fn = store.acquire_pages

    ref = server.generate([5, 6, 7, 8], max_new_tokens=12)
    np.testing.assert_array_equal(
        cb.generate([5, 6, 7, 8], max_new_tokens=12), ref)
    row = list(range(1, 33)) + [4, 5]
    refp = server.generate(row, max_new_tokens=12)
    for _ in range(2):  # cold walk, then the zero-copy page hit
        m = store.route(row)
        out = (cb.generate(np.asarray(row[m:], np.int32),
                           max_new_tokens=12,
                           prefix=np.asarray(row[:m], np.int32))
               if m > 0 else cb.generate(row, max_new_tokens=12))
        np.testing.assert_array_equal(out, refp)
    refs = server.generate([9, 8, 7], max_new_tokens=12,
                           temperature=0.9, seed=4)
    np.testing.assert_array_equal(
        cb.generate([9, 8, 7], max_new_tokens=12, temperature=0.9,
                    seed=4), refs)
    with cb._lock:
        while cb._engine_running:
            cb._lock.wait(0.05)
    pool.check_invariants()


@pytest.mark.slow  # two bundle loads; the spec_k extra is one int cast
# away from the tested ContinuousBatcher wiring, and bench phase 10
# exercises engine spec on every CI pass
def test_handler_spec_k_extra(tmp_path):
    """Bundle extra spec_k reaches the engine; batching.spec appears on
    the stats surface; tokens match the spec-off bundle's."""
    from lambdipy_tpu.runtime.loader import load_bundle
    from tests.test_runtime import make_model_bundle

    plain_bundle = make_model_bundle(
        tmp_path / "plain", model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "16", "batch_mode": "continuous",
               "batch_max": "2", "batch_segment": "4"})
    plain = load_bundle(plain_bundle, warmup=False)
    ref = plain.handler.invoke(plain.state, {"tokens": [5, 6, 7, 8]})

    bundle = make_model_bundle(
        tmp_path / "spec", model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "16", "batch_mode": "continuous",
               "batch_max": "2", "batch_segment": "4", "spec_k": "4"})
    report = load_bundle(bundle, warmup=False)
    out = report.handler.invoke(report.state, {"tokens": [5, 6, 7, 8]})
    assert out["ok"] and out["tokens"] == ref["tokens"]
    stats = report.state.stats()
    spec = stats["batching"]["spec"]
    assert spec["k"] == 4 and spec["steps"] > 0
    assert "acceptance_rate" in spec and "tokens_per_step" in spec
    # the solo-path surface reports through the same shared object
    assert stats["spec"]["steps"] == spec["steps"]
