"""Project resolution: requirements.txt / Pipfile / Pipfile.lock / pyproject.

Parses PEP-508 requirement lines (via :mod:`packaging`) from any of the
project-manifest formats the reference resolves (requirements.txt and
Pipfile/Pipfile.lock — SURVEY.md §3.1 #2; pyproject added for modern
projects), pins them against the locally installed distribution set (the
offline stand-in for PyPI resolution — SURVEY.md §8: no network; §2 table:
"resolve against local wheel store"), and splits the pinned list into
recipe-covered vs plain deps exactly as the reference's resolver does
(SURVEY.md §4 call stack A).
"""

from __future__ import annotations

import importlib.metadata
import json
from dataclasses import dataclass
from pathlib import Path

from packaging.requirements import InvalidRequirement
from packaging.requirements import Requirement as _PepRequirement
from packaging.utils import canonicalize_name
from packaging.version import Version

from lambdipy_tpu.recipes.store import RecipeStore
from lambdipy_tpu.utils.toml_compat import tomllib


class ResolutionError(ValueError):
    """Raised when a requirement cannot be parsed or satisfied locally."""


@dataclass(frozen=True)
class Requirement:
    """A parsed requirement, optionally pinned to a locally available version."""

    name: str  # canonical (lowercase, dash) name
    raw: str  # original line
    specifier: str  # e.g. "==2.0.2", may be ""
    pinned: str | None = None  # resolved exact version
    # environment marker evaluated once at parse time against the running
    # interpreter; False = dep is for another platform and should be dropped
    applies: bool = True

    @property
    def pin(self) -> str:
        if self.pinned is None:
            raise ResolutionError(f"requirement {self.raw!r} is not pinned")
        return f"{self.name}=={self.pinned}"


def parse_requirement(line: str) -> Requirement:
    try:
        pep = _PepRequirement(line)
    except InvalidRequirement as e:
        raise ResolutionError(f"invalid requirement {line!r}: {e}") from e
    return Requirement(
        name=canonicalize_name(pep.name),
        raw=line,
        specifier=str(pep.specifier),
        applies=pep.marker is None or pep.marker.evaluate(),
    )


def parse_requirements_text(text: str) -> list[Requirement]:
    """Parse requirements.txt content: one requirement per line, ``#``
    comments and blank lines skipped, pip option lines (-r/-e/--hash...)
    rejected explicitly rather than misparsed."""
    out: list[Requirement] = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        if line.startswith("-"):
            raise ResolutionError(
                f"line {lineno}: pip option lines ({line.split()[0]}) are not supported"
            )
        out.append(parse_requirement(line))
    return out


def _pipfile_entry(name: str, spec) -> Requirement:
    """One ``[packages]`` entry: ``"*"``, a specifier string, or an inline
    table (``{version = "...", extras = [...]}``). VCS/path/editable entries
    have no offline equivalent and are rejected explicitly."""
    if isinstance(spec, str):
        version = "" if spec == "*" else spec
        return parse_requirement(f"{name}{version}")
    if isinstance(spec, dict):
        unsupported = {"git", "path", "file", "editable"} & set(spec)
        if unsupported:
            raise ResolutionError(
                f"Pipfile entry {name!r}: {sorted(unsupported)} sources are "
                "not supported (offline resolver)")
        extras = spec.get("extras") or []
        extras_s = f"[{','.join(extras)}]" if extras else ""
        version = spec.get("version", "*")
        version = "" if version == "*" else version
        markers = spec.get("markers")
        line = f"{name}{extras_s}{version}"
        if markers:
            line += f"; {markers}"
        return parse_requirement(line)
    raise ResolutionError(f"Pipfile entry {name!r}: unsupported value {spec!r}")


def parse_pipfile_text(text: str, *, dev: bool = False) -> list[Requirement]:
    """Parse Pipfile content (``[packages]`` + optionally ``[dev-packages]``)."""
    try:
        doc = tomllib.loads(text)
    except tomllib.TOMLDecodeError as e:
        raise ResolutionError(f"invalid Pipfile: {e}") from e
    sections = ["packages"] + (["dev-packages"] if dev else [])
    out: list[Requirement] = []
    for section in sections:
        for name, spec in (doc.get(section) or {}).items():
            out.append(_pipfile_entry(name, spec))
    return out


def parse_pipfile_lock_text(text: str, *, dev: bool = False) -> list[Requirement]:
    """Parse Pipfile.lock content: exact ``==`` pins from ``default`` (and
    ``develop`` when ``dev``), which is what the reference resolves against
    when a lockfile exists."""
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise ResolutionError(f"invalid Pipfile.lock: {e}") from e
    sections = ["default"] + (["develop"] if dev else [])
    out: list[Requirement] = []
    for section in sections:
        for name, spec in (doc.get(section) or {}).items():
            if not isinstance(spec, dict) or "version" not in spec:
                raise ResolutionError(
                    f"Pipfile.lock entry {name!r}: missing pinned version")
            line = f"{name}{spec['version']}"
            if spec.get("markers"):  # other-platform pins must not abort resolution
                line += f"; {spec['markers']}"
            out.append(parse_requirement(line))
    return out


def parse_pyproject_text(text: str) -> list[Requirement]:
    """Parse ``[project] dependencies`` from pyproject.toml content."""
    try:
        doc = tomllib.loads(text)
    except tomllib.TOMLDecodeError as e:
        raise ResolutionError(f"invalid pyproject.toml: {e}") from e
    deps = (doc.get("project") or {}).get("dependencies", [])
    if not isinstance(deps, list):
        raise ResolutionError("pyproject.toml: [project] dependencies must be a list")
    return [parse_requirement(d) for d in deps]


def parse_project_file(path: Path) -> list[Requirement]:
    """Dispatch on the manifest file name, like the reference's resolver
    choosing between requirements.txt and Pipfile(.lock)."""
    path = Path(path)
    text = path.read_text()
    if path.name == "Pipfile.lock":
        return parse_pipfile_lock_text(text)
    if path.name == "Pipfile":
        return parse_pipfile_text(text)
    if path.name == "pyproject.toml":
        return parse_pyproject_text(text)
    return parse_requirements_text(text)


def installed_version(name: str) -> str | None:
    try:
        return importlib.metadata.version(name)
    except importlib.metadata.PackageNotFoundError:
        return None


def pin_against_local(req: Requirement) -> Requirement:
    """Pin a requirement against the locally installed distribution set.

    This is the offline resolver: the local env *is* the wheel store. A
    version conflict (installed version outside the specifier) is an error,
    matching the reference's behavior when no release asset matches.
    """
    version = installed_version(req.name)
    if version is None:
        raise ResolutionError(
            f"requirement {req.raw!r}: distribution {req.name!r} is not available "
            "in the local wheel store (offline environment)"
        )
    pep = _PepRequirement(req.raw)
    if req.specifier and not pep.specifier.contains(Version(version), prereleases=True):
        raise ResolutionError(
            f"requirement {req.raw!r} cannot be satisfied: local store has "
            f"{req.name}=={version}"
        )
    return Requirement(name=req.name, raw=req.raw, specifier=req.specifier,
                       pinned=version, applies=req.applies)


@dataclass(frozen=True)
class ProjectResolution:
    """Result of resolving a project: recipe-covered deps build via recipes,
    plain deps are vendored directly at package time (SURVEY.md §4 B)."""

    recipe_covered: tuple[tuple[Requirement, str], ...]  # (req, recipe name)
    plain: tuple[Requirement, ...]


def split_by_recipes(reqs: list[Requirement], store: RecipeStore) -> ProjectResolution:
    covered: list[tuple[Requirement, str]] = []
    plain: list[Requirement] = []
    for req in reqs:
        recipe = store.covering(req.name)
        if recipe is not None:
            covered.append((req, recipe.name))
        else:
            plain.append(req)
    return ProjectResolution(recipe_covered=tuple(covered), plain=tuple(plain))


def resolve_project(requirements_path: Path, store: RecipeStore) -> ProjectResolution:
    reqs = [r for r in parse_project_file(Path(requirements_path)) if r.applies]
    pinned = [pin_against_local(r) for r in reqs]
    return split_by_recipes(pinned, store)
