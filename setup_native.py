"""Build the native C extension in-place:

    python setup_native.py build_ext --inplace

Produces ``lambdipy_tpu/_native.*.so``. The framework works without it
(hashlib fallback in utils/fsutil.py); with it, manifest hashing of the
multi-hundred-MB TPU payloads runs at memory bandwidth.
"""

from setuptools import Extension, setup

setup(
    name="lambdipy-tpu-native",
    ext_modules=[
        Extension(
            "lambdipy_tpu._native",
            sources=["native/xxh64.c"],
            extra_compile_args=["-O3"],
        )
    ],
    script_args=None,
)
