"""Build orchestration: recipe -> staged site tree -> prune -> smoke.

The per-stage timing (stage/prune/smoke) feeds the build provenance
manifest, mirroring the post-build manifest of the TPU image exemplar
(SURVEY.md §3.4 ``jss:generate_manifest.sh:15-24``).
"""

from __future__ import annotations

import platform
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path

from lambdipy_tpu.buildengine.prune import PruneReport, prune_tree
from lambdipy_tpu.buildengine.sandbox import SandboxError, VenvSandbox, build_wheel, install_wheel
from lambdipy_tpu.buildengine.smoke import SmokeError, import_smoke
from lambdipy_tpu.buildengine.vendor import (
    VendorError,
    dependency_closure,
    find_distribution,
    import_names,
    vendor_distribution,
)
from lambdipy_tpu.bundle.baselayer import base_layer_dists, materialize_base_site
from lambdipy_tpu.recipes.schema import Recipe
from lambdipy_tpu.resolve.sources import SourceStore
from lambdipy_tpu.utils.logs import get_logger, log_event
from lambdipy_tpu.utils.timing import StageTimer

log = get_logger("lambdipy.build")


class BuildError(RuntimeError):
    pass


@dataclass
class BuildResult:
    recipe: Recipe
    site_dir: Path
    vendored: list[dict] = field(default_factory=list)
    # root requirements satisfied by the shared base layer (not copied)
    base_provided: list[dict] = field(default_factory=list)
    skipped_optional: list[str] = field(default_factory=list)
    prune: PruneReport | None = None
    smoke_versions: dict[str, str] = field(default_factory=dict)
    timings: dict[str, float] = field(default_factory=dict)

    def provenance(self) -> dict:
        """Build provenance for the bundle manifest."""
        return {
            "recipe": self.recipe.name,
            "recipe_version": self.recipe.version,
            "device": self.recipe.device,
            "python": f"{sys.version_info.major}.{sys.version_info.minor}",
            "platform": platform.platform(),
            "built_at": time.time(),
            "vendored": self.vendored,
            "base_provided": self.base_provided,
            "skipped_optional": self.skipped_optional,
            "prune": self.prune.as_dict() if self.prune else None,
            "smoke_versions": self.smoke_versions,
            "timings": self.timings,
        }


def _smoke_modules(result: BuildResult, recipe: Recipe) -> list[str]:
    mods: list[str] = []
    for rec in result.vendored + result.base_provided:
        mods.extend(rec.get("import_names", []))
    # heavyweight frameworks are smoke-tested at the package level only;
    # their internal extras (e.g. jaxlib's mlir sub-extensions) come along.
    blocklist = {"pkg_resources", "setuptools", "distutils-precedence"}
    return sorted({m for m in mods if m not in blocklist and not m.endswith(".pth")})


def build_recipe(recipe: Recipe, workdir: Path, *, sources: SourceStore | None = None,
                 run_smoke: bool = True) -> BuildResult:
    """Execute a recipe's build path into ``workdir/site``.

    Stages (SURVEY.md §4 A, build-path branch):
      1. stage: vendor installed dists, or sdist->wheel->unpack
      2. prune: recipe rules + XLA whitelist
      3. smoke: hermetic import of every vendored top-level module
    """
    workdir = Path(workdir)
    site_dir = workdir / "site"
    site_dir.mkdir(parents=True, exist_ok=True)
    result = BuildResult(recipe=recipe, site_dir=site_dir)
    timer = StageTimer()

    with timer.stage("stage"):
        if recipe.build.backend == "sdist":
            sources = sources or SourceStore()
            tree = sources.resolve(recipe.build.source)
            log_event(log, "building sdist", recipe=recipe.name, source=str(tree))
            wheel = build_wheel(tree, workdir / "wheels", env=recipe.build.env_dict())
            rec = install_wheel(wheel, site_dir)
            dist = find_distribution(rec["name"])
            rec["import_names"] = import_names(dist) if dist else [rec["name"].replace("-", "_")]
            result.vendored.append(rec)
        else:
            from packaging.requirements import Requirement as PepReq
            from packaging.utils import canonicalize_name

            base = base_layer_dists(recipe.base_layer)
            roots = [canonicalize_name(PepReq(r).name) for r in recipe.requires]
            closure = dependency_closure(list(recipe.requires))
            missing = [r for r in roots if r not in closure]
            if missing:
                raise BuildError(
                    f"recipe {recipe.name}: required distributions not installed "
                    f"in the local wheel store: {missing}")
            for name in closure:
                if name in base:
                    if name in roots:  # still smoke-tested via the base layer
                        dist = find_distribution(name)
                        result.base_provided.append({
                            "name": name,
                            "version": dist.version if dist else None,
                            "import_names": import_names(dist) if dist else [],
                        })
                    continue  # provided by the shared base layer
                result.vendored.append(vendor_distribution(name, site_dir))
            vendored_names = set(closure)
            for req in recipe.optional_requires:
                name = canonicalize_name(PepReq(req).name)
                opt_closure = dependency_closure([req])
                new_deps = [d for d in opt_closure
                            if d not in base and d not in vendored_names]
                # transactional: vendor only when the root and every new dep
                # are fully copyable, so a partial optional never leaves
                # orphan files or contradictory provenance in the bundle
                copyable = name in opt_closure and all(
                    (dist := find_distribution(d)) is not None and (dist.files or [])
                    for d in new_deps)
                if not copyable:
                    log_event(log, "optional distribution unavailable, skipping",
                              recipe=recipe.name, dist=name)
                    result.skipped_optional.append(name)
                    continue
                for dep in new_deps:
                    result.vendored.append(vendor_distribution(dep, site_dir))
                    vendored_names.add(dep)
        if recipe.build.steps:
            sandbox = VenvSandbox.create(workdir / "venv")
            for step in recipe.build.steps:
                sandbox.run(["bash", "-c", step], cwd=site_dir, env=recipe.build.env_dict())

    with timer.stage("prune"):
        result.prune = prune_tree(site_dir, recipe.prune)

    if run_smoke:
        with timer.stage("smoke"):
            mods = _smoke_modules(result, recipe)
            base_paths = None
            if recipe.base_layer != "none":
                # exactly the declared layer — NOT the whole host site-packages,
                # which would mask missing vendored files
                base_site = materialize_base_site(recipe.base_layer, workdir / "base-site")
                base_paths = [str(base_site)]
            try:
                result.smoke_versions = import_smoke(site_dir, mods, base_paths=base_paths)
            except SmokeError as e:
                raise BuildError(str(e)) from e

    result.timings = timer.report()
    log_event(log, "build complete", recipe=recipe.name,
              bytes=result.prune.bytes_after if result.prune else None,
              saved=result.prune.bytes_saved if result.prune else None,
              timings=result.timings)
    return result
