"""Pallas op tests: kernel (interpret mode) vs pure-jax oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lambdipy_tpu.ops.attention import flash_attention, mha_reference


def _rand(shape, seed):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape), jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    b, s, h, d = 2, 256, 2, 64
    q, k, v = (_rand((b, s, h, d), i) for i in range(3))
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3)


def test_flash_attention_gqa_broadcast():
    b, s, h, kvh, d = 1, 128, 4, 2, 64
    q = _rand((b, s, h, d), 0)
    k = _rand((b, s, kvh, d), 1)
    v = _rand((b, s, kvh, d), 2)
    ref = mha_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3)


def test_flash_attention_untileable_falls_back():
    b, s, h, d = 1, 10, 2, 16  # s=10 doesn't tile
    q, k, v = (_rand((b, s, h, d), i) for i in range(3))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-5, atol=1e-5)


# -- int8 weight-only matmul ------------------------------------------------


def _quant_weights(k, n, seed):
    import numpy as np

    rng = np.random.default_rng(seed)
    w = rng.normal(size=(k, n)).astype(np.float32)
    scale = np.abs(w).max(axis=0, keepdims=True) / 127.0
    w_i8 = np.round(w / scale).astype(np.int8)
    return jnp.asarray(w_i8), jnp.asarray(scale)


def test_int8_matmul_kernel_matches_reference():
    import numpy as np

    from lambdipy_tpu.ops.quant import int8_matmul, int8_matmul_reference

    m, k, n = 128, 256, 128
    x = jnp.asarray(np.random.default_rng(0).normal(size=(m, k)), jnp.float32)
    w_i8, scale = _quant_weights(k, n, 1)
    ref = int8_matmul_reference(x, w_i8, scale)
    out = int8_matmul(x, w_i8, scale, block_m=64, block_n=64, block_k=64,
                      interpret=True)
    # kernel applies scales on the f32 accumulator (more precise than the
    # reference's per-element bf16 dequant) -> bf16-rounding-sized deltas
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-2, atol=0.15)


def test_int8_matmul_fallback_on_odd_shapes():
    import numpy as np

    from lambdipy_tpu.ops.quant import int8_matmul, int8_matmul_reference

    m, k, n = 3, 96, 80  # m=3: decode-sized, won't tile
    x = jnp.asarray(np.random.default_rng(2).normal(size=(m, k)), jnp.float32)
    w_i8, scale = _quant_weights(k, n, 3)
    out = int8_matmul(x, w_i8, scale, interpret=True)
    # same math, but under jit XLA fuses the bf16 dequant differently
    np.testing.assert_allclose(np.asarray(int8_matmul_reference(x, w_i8, scale)),
                               np.asarray(out), rtol=2e-2, atol=0.1)


def test_qdense_pallas_backend_matches_xla():
    """QDense(int8, backend=pallas) routes through the kernel (interpret on
    CPU) and matches the XLA dequant path."""
    import numpy as np

    from lambdipy_tpu.models.llama import QDense

    x = jnp.asarray(np.random.default_rng(5).normal(size=(2, 64, 128)),
                    jnp.float32)
    ref_mod = QDense(256, "int8", jnp.float32, "xla")
    params = ref_mod.init(jax.random.PRNGKey(0), x)
    ref = ref_mod.apply(params, x)
    out = QDense(256, "int8", jnp.float32, "pallas").apply(params, x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-2, atol=0.1)
