"""CLI tests (click CliRunner) over the end-to-end build->deploy surface."""

import json

import pytest
from click.testing import CliRunner

from lambdipy_tpu.cli import main


@pytest.fixture()
def tiny_recipe_dir(tmp_path):
    d = tmp_path / "recipes"
    d.mkdir()
    (d / "tiny-llm.toml").write_text(
        'schema = 1\nname = "tiny-llm"\nversion = "0.1"\ndevice = "any"\n'
        'base_layer = "jax-tpu"\nrequires = []\n'
        "[payload]\n"
        'model = "llama-tiny"\n'
        'handler = "lambdipy_tpu.runtime.handlers:generate_handler"\n'
        'params = "init"\ndtype = "float32"\n')
    return d


def test_recipes_listing(tiny_recipe_dir):
    result = CliRunner().invoke(main, ["recipes", "--recipe-dir", str(tiny_recipe_dir)])
    assert result.exit_code == 0, result.output
    assert "jax-resnet50" in result.output and "tiny-llm" in result.output


def test_show_recipe():
    result = CliRunner().invoke(main, ["show", "jax-llama3-8b"])
    assert result.exit_code == 0
    doc = json.loads(result.output)
    assert doc["payload"]["quant"] == "int8"


def test_show_unknown_recipe_fails_cleanly():
    result = CliRunner().invoke(main, ["show", "nope"])
    assert result.exit_code != 0
    assert "no recipe named" in str(result.exception)


def test_build_publish_cache_hit_and_artifacts(tiny_recipe_dir, tmp_path):
    runner = CliRunner()
    reg = str(tmp_path / "registry")
    args = ["build", "tiny-llm", "--recipe-dir", str(tiny_recipe_dir),
            "--registry", reg]
    r1 = runner.invoke(main, args)
    assert r1.exit_code == 0, r1.output
    assert "built + published" in r1.output
    r2 = runner.invoke(main, args)
    assert "cache hit" in r2.output
    r3 = runner.invoke(main, ["artifacts", "--registry", reg])
    assert "tiny-llm-0.1" in r3.output


def test_build_to_out_dir(tiny_recipe_dir, tmp_path):
    out = tmp_path / "bundle"
    r = CliRunner().invoke(main, [
        "build", "tiny-llm", "--recipe-dir", str(tiny_recipe_dir),
        "--out", str(out)])
    assert r.exit_code == 0, r.output
    assert (out / "manifest.json").exists()
    assert (out / "params" / "orbax").exists()
    assert (out / "handler.py").exists()


def test_package_command(tmp_path):
    req = tmp_path / "requirements.txt"
    req.write_text("einops\n")
    out = tmp_path / "build"
    r = CliRunner().invoke(main, ["package", str(req), "--out", str(out)])
    assert r.exit_code == 0, r.output
    assert (out / "site" / "einops").is_dir()


def test_deploy_rejects_unknown_target(tmp_path):
    r = CliRunner().invoke(main, ["deploy", "definitely-missing",
                                  "--registry", str(tmp_path / "reg")])
    assert r.exit_code != 0
    assert "neither a bundle dir" in r.output
