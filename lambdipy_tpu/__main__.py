from lambdipy_tpu.cli import main

main()
