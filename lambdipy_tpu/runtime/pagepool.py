"""Paged KV memory manager: a page allocator over one preallocated arena.

The serve path's HBM story before this module: every engine slot owns a
full ``cache_len`` KV window whether the row holds 40 tokens or 4000,
and a prefix-cache hit pays a ``concat_cache_blocks`` assembly copy (plus
the peak-HBM spike of holding source blocks and the assembled window at
once) before it can dispatch. This module is the vLLM-style
PagedAttention step (Kwon et al., SOSP 2023), specialized to this repo's
functional-cache serving stack:

- ONE preallocated arena per layer, shaped ``[n_pages, page, kv_heads,
  head_dim]`` (``models/llama.py init_page_arena`` builds it in the KV
  store layout, int8 + scales included). ``page`` equals the prefix
  store's block width, so a radix block IS a page and a block-aligned
  prefix hit needs no re-slicing.
- :class:`PagePool` is the HOST-side allocator: free-list reuse,
  per-page REFCOUNTS (a prefix page shared by the radix store and N live
  rows has refcount N+1), and exact-bytes accounting. Batch admission
  charges ``ceil(tokens / page)`` pages — capacity is bounded by *actual*
  tokens, not windows, which is directly more concurrent rows per chip
  for mixed-length traffic.
- Page 0 is the reserved NULL page: block tables pad with it, retired
  slots point every entry at it, and over-decode writes land in it.
  Nothing ever reads the null page unmasked (attention masks positions
  past a row's length to exact zeros), so its garbage is harmless by the
  same argument the dense engine uses for stale slot rows.
- Running out of pages is BACKPRESSURE, not a bug: :class:`PagesExhausted`
  carries a ``retry_after_s`` estimate and ``runtime/server.py`` maps it
  to a priced 503 + Retry-After shed (reason ``kv_pages``), exactly like
  the scheduler's queue-depth sheds.
- The arena itself is a FUNCTIONAL jax value that every mutating program
  (decode segment, pack, prefix continuation, block insert) consumes and
  replaces. ``arena_lock`` serializes that chain: a mutation dispatched
  against arena vN must publish vN+1 before the next mutation reads it,
  or one side's writes would silently vanish from the other's copy.
  Dispatches are async (the lock holds for enqueue time, not compute
  time), and readers of frozen prefix pages may snapshot the reference
  without the lock — those pages never change value.

Fault injection: ``page_alloc`` is a first-class ``runtime/faults.py``
site — an injected allocation failure surfaces as a priced shed for that
row only, never an engine failure.

Pages are also the KV-SHIP unit for disaggregated prefill/decode
serving (runtime/kvwire.py + fleet/router.py): an export reads a page
out host-side (``models/llama.py arena_page_slices``, under a held pool
ref so a concurrent release cannot recycle it mid-read), and an import
writes each shipped block into its own strictly-allocated page — the
prefix store allocs the whole ship up front so a full arena surfaces as
:class:`PagesExhausted` backpressure (the router's fallback-to-mixed
path) instead of a silently partial cache.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from lambdipy_tpu.runtime.metrics import PagePoolStats
from lambdipy_tpu.utils.logs import get_logger

log = get_logger("lambdipy.pagepool")

NULL_PAGE = 0


class PagesExhausted(RuntimeError):
    """The arena has fewer free pages than an admission needs. Mapped by
    the HTTP layer to a 503 + Retry-After shed (reason ``kv_pages``) —
    explicit backpressure, not an internal error."""

    def __init__(self, needed: int, free: int, retry_after_s: float = 1.0):
        self.needed = int(needed)
        self.free = int(free)
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"KV page pool exhausted: need {needed} pages, {free} free "
            f"(retry in ~{self.retry_after_s:.1f}s)")


def page_width(max_len: int, block: int) -> int:
    """Normalize a requested page/block width exactly like the prefix
    store does: the largest power of two <= the pow-2 bucket of
    ``block`` that divides ``max_len`` — every page write then lands at
    a page-aligned offset inside the context window."""
    b = 1
    while b < max(1, int(block)):
        b *= 2
    while b > 1 and max_len % b:
        b //= 2
    return min(b, max_len)


class PagePool:
    """Host-side page allocator + the owner of the device KV arena.

    ``make_arena`` builds the device arena lazily on first use (boot
    order: the pool is constructed while the bundle loads, the arena
    allocates when the first paged program needs it). ``page_bytes`` is
    the exact stored bytes of ONE page across all layers/leaves — the
    unit of every byte gauge this pool reports.
    """

    def __init__(self, *, n_pages: int, page: int, page_bytes: int,
                 make_arena: Callable[[], Any] | None = None,
                 window_pages: int | None = None, faults: Any = None):
        if n_pages < 2:
            raise ValueError("n_pages must be >= 2 (page 0 is reserved)")
        self.n_pages = int(n_pages)
        self.page = int(page)
        self.page_bytes = int(page_bytes)
        # pages one full decode window costs — the denominator of the
        # capacity_rows comparison (set by the engine from its cache_len)
        self.window_pages = max(1, int(window_pages or 1))
        self._make_arena = make_arena
        self.faults = faults  # FaultPlan | None; site "page_alloc"
        # optional last-resort reclaimer (the prefix store's
        # reclaim_pages): called OUTSIDE the pool lock when an alloc
        # comes up short, so store-owned cold pages yield to admission
        # instead of starving it (lock order stays store -> pool)
        self.reclaim_fn: Callable[[int], int] | None = None
        # optional session-pin gauge provider (the prefix store's
        # _pool_pin_gauges): merged into stats() OUTSIDE the pool lock
        # (the provider takes the store lock; store -> pool is the one
        # sanctioned order) so operators see pinned pages squeezing
        # arena headroom next to the refcount gauges
        self.pinned_fn: Callable[[], dict] | None = None
        # optional host-offload tier (runtime/offload.py): ``offload``
        # is the OffloadArena whose report() merges into stats() as the
        # ``kv_offload`` block; ``temperature`` is the shared page-LRU
        # tracker spill-victim selection reads. Both attach AFTER
        # construction (attach_offload) so a pool without the long-
        # context tier pays nothing.
        self.offload: Any = None
        self.temperature: Any = None
        self.stats_counters = PagePoolStats()
        self._lock = threading.RLock()
        # serializes the functional-arena chain (see module docstring);
        # RLock so a holder may call helpers that re-enter
        self.arena_lock = threading.RLock()
        self._arena = None
        # bumped by reset_arena (engine failure): stale-content guard
        # for consumers caching page ids against arena values
        self.arena_generation = 0
        # LIFO free list: hot pages reuse warm HBM lines
        self._free: list[int] = list(range(self.n_pages - 1, 0, -1))
        # page id -> refcount; the null page is permanently pinned
        self._refs: dict[int, int] = {NULL_PAGE: 1}
        # page id -> tokens actually stored in it (internal-fragmentation
        # gauge: a row's last page is usually part-full)
        self._tokens: dict[int, int] = {}
        # EWMA of seconds between page releases — the Retry-After price
        self._last_release_t: float | None = None
        self._release_gap_s = 0.25

    # -- arena ---------------------------------------------------------------

    @property
    def arena(self):
        return self._arena

    @arena.setter
    def arena(self, new) -> None:
        self._arena = new

    def ensure_arena(self):
        """Build the device arena on first use (idempotent)."""
        with self.arena_lock:
            if self._arena is None:
                if self._make_arena is None:
                    raise RuntimeError("pool has no arena factory")
                self._arena = self._make_arena()
            return self._arena

    def reset_arena(self) -> None:
        """Discard the device arena (rebuilt zeroed on next use) and
        bump the GENERATION. The engine calls this on failure: on an
        async backend the published arena may be the output of the very
        computation that failed, and every program consuming it would
        re-raise — the paged twin of the dense engine discarding its
        whole carry. Consumers holding page CONTENT expectations (the
        prefix store's radix tree) watch ``arena_generation`` and drop
        their state when it moves; page *accounting* (refcounts, free
        list) is host-side truth and survives untouched."""
        with self.arena_lock:
            self._arena = None
            self.arena_generation += 1

    # -- allocation ----------------------------------------------------------

    @property
    def capacity_pages(self) -> int:
        """Allocatable pages (the null page excluded)."""
        return self.n_pages - 1

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def alloc(self, n: int, *, tokens: int = 0,
              record_shed: bool = True) -> list[int]:
        """Take ``n`` pages (refcount 1 each). ``tokens`` is how many KV
        positions the caller will actually store across them (the
        internal-fragmentation gauge). A short free list first asks
        ``reclaim_fn`` (the prefix store's cold-unshared-leaf release)
        to make room — a cache must never starve admission — then
        raises :class:`PagesExhausted`; an armed ``page_alloc`` fault
        fires here, BEFORE any page leaves the free list, so an
        injected failure never leaks a partial allocation.
        ``record_shed=False`` keeps a CACHE-fill shortfall (the store
        caching less, the request unaffected) out of the ``sheds``
        counter, which meters refused ADMISSIONS only."""
        n = int(n)
        if n <= 0:
            return []
        if self.faults is not None:
            self.faults.check("page_alloc")
        if n > self.free_count() and self.reclaim_fn is not None:
            # outside the pool lock: the reclaimer takes the store lock
            # and re-enters release() (store -> pool order, never the
            # reverse)
            try:
                self.reclaim_fn(n - self.free_count())
            except Exception as e:  # noqa: BLE001 — reclaim is
                # best-effort; a broken reclaimer must not turn an
                # honest shed into an error
                log.error("page reclaim failed: %s", e)
        with self._lock:
            if n > len(self._free):
                if record_shed:
                    self.stats_counters.record_shed()
                raise PagesExhausted(n, len(self._free),
                                     self.retry_after_s(n))
            pids = [self._free.pop() for _ in range(n)]
            left = int(tokens)
            for pid in pids:
                self._refs[pid] = 1
                self._tokens[pid] = max(0, min(self.page, left))
                left -= self.page
            self.stats_counters.record_alloc(n)
            return pids

    def retain(self, pids) -> None:
        """Refcount bump — how a prefix-cache hit shares pages with zero
        copies (the radix store holds one ref, every live row another)."""
        with self._lock:
            for pid in pids:
                if pid == NULL_PAGE:
                    continue
                if self._refs.get(pid, 0) <= 0:
                    raise ValueError(f"retain of unallocated page {pid}")
                self._refs[pid] += 1
            self.stats_counters.record_share(
                sum(1 for p in pids if p != NULL_PAGE))

    def release(self, pids) -> None:
        """Drop one ref per page; pages reaching zero return to the free
        list. Double-free is a hard error — silent refcount corruption
        under a shared arena is the one bug class this allocator must
        never paper over."""
        import time as _time

        freed = 0
        with self._lock:
            for pid in pids:
                if pid == NULL_PAGE:
                    continue
                refs = self._refs.get(pid, 0)
                if refs <= 0:
                    raise ValueError(f"double free of page {pid}")
                if refs == 1:
                    del self._refs[pid]
                    self._tokens.pop(pid, None)
                    self._free.append(pid)
                    freed += 1
                else:
                    self._refs[pid] = refs - 1
            self.stats_counters.record_release(freed)
            if freed:
                now = _time.monotonic()
                if self._last_release_t is not None:
                    gap = (now - self._last_release_t) / freed
                    self._release_gap_s = (0.8 * self._release_gap_s
                                           + 0.2 * min(gap, 30.0))
                self._last_release_t = now

    def refcount(self, pid: int) -> int:
        """Current refcount of one page (0 = free/unallocated) — the
        prefix store's eviction guard: a page still shared with live
        rows must not be released by an LRU sweep."""
        with self._lock:
            return self._refs.get(pid, 0)

    def snapshot_refs(self) -> dict:
        """One-lock copy of every live refcount — the store's eviction
        sweep reads it once per pass instead of paying a pool-lock
        round-trip per candidate leaf."""
        with self._lock:
            return dict(self._refs)

    def retry_after_s(self, needed: int = 1) -> float:
        """Priced backpressure hint: pages free at roughly the recent
        release cadence, so ``needed`` pages should exist in about
        ``needed * gap`` seconds (clamped to a sane client-facing
        range)."""
        return max(0.5, min(30.0, float(needed) * self._release_gap_s))

    # -- observability / invariants ------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            live = [p for p in self._refs if p != NULL_PAGE]
            shared = [p for p in live if self._refs[p] > 1]
            hist: dict[str, int] = {}
            for p in live:
                key = str(self._refs[p])
                hist[key] = hist.get(key, 0) + 1
            used_tokens = sum(self._tokens.get(p, 0) for p in live)
            free = len(self._free)
            out = {
                "page_tokens": self.page,
                "page_bytes": self.page_bytes,
                "pages_total": self.capacity_pages,
                "pages_free": free,
                "pages_live": len(live),
                "pages_shared": len(shared),
                "bytes_total": self.capacity_pages * self.page_bytes,
                "bytes_free": free * self.page_bytes,
                "bytes_live": len(live) * self.page_bytes,
                # allocated-but-empty token slots / allocated slots: the
                # waste paging cannot remove (part-full tail pages)
                "internal_fragmentation": (
                    round(1.0 - used_tokens / (len(live) * self.page), 4)
                    if live else 0.0),
                "refcount_histogram": hist,
                "max_refcount": max((self._refs[p] for p in live),
                                    default=0),
                # full-window rows that could still be admitted RIGHT NOW
                # vs what a window-per-slot allocator could EVER hold in
                # the same bytes — the capacity margin paging buys
                "capacity_rows_now": free // self.window_pages,
                "window_bound_rows": (self.capacity_pages
                                      // self.window_pages),
                "retry_after_s": round(self.retry_after_s(), 3),
            }
        out.update(self.stats_counters.report())
        if self.pinned_fn is not None:
            try:
                out.update(self.pinned_fn())
            except Exception:  # noqa: BLE001 — gauges must never break stats
                pass
        if self.offload is not None:
            try:
                out["kv_offload"] = self.offload.report()
            except Exception:  # noqa: BLE001 — gauges must never break stats
                pass
        return out

    def attach_offload(self, offload: Any,
                       temperature: Any = None) -> None:
        """Wire the host offload tier in: the arena's ``kv.offload.*``
        counters ride this pool's stats() (one merged block per pool on
        /metrics) and the shared temperature tracker becomes the spill-
        victim oracle for every consumer of this pool."""
        self.offload = offload
        if temperature is not None:
            self.temperature = temperature
        elif self.temperature is None:
            from lambdipy_tpu.runtime.offload import PageTemperature

            self.temperature = PageTemperature()

    def check_invariants(self) -> None:
        """Test hook: every page is free XOR live exactly once, refcounts
        are positive, and free + live bytes cover the arena exactly."""
        with self._lock:
            free = set(self._free)
            live = {p for p in self._refs if p != NULL_PAGE}
            assert len(free) == len(self._free), "free list has duplicates"
            assert not (free & live), f"pages both free and live: {free & live}"
            assert free | live | {NULL_PAGE} == set(range(self.n_pages)), \
                "pages leaked out of the arena"
            assert all(r > 0 for r in self._refs.values()), \
                "non-positive refcount"
            assert (len(free) + len(live)) * self.page_bytes == \
                self.capacity_pages * self.page_bytes
