"""Device tests (SURVEY.md §5.3): the real chip, through the REAL serve
path — build the flagship bundle, deploy it, and assert the north-star
budgets (BASELINE.json: ResNet-50 < 15 ms p50, < 10 s cold start).

Marked ``tpu`` and deselected by default (pyproject addopts): the suite's
conftest pins the in-process platform to CPU, so these tests do all jax
work in subprocesses with the shell's device platform — which also guards
against the axon tunnel's observed wedge (a probe with a timeout decides
skip vs run). Run with: ``pytest -m tpu --override-ini addopts=''``.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "scripts"))

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module")
def device_ok():
    from measure_baseline import tpu_reachable

    if not tpu_reachable():
        pytest.skip("TPU device unreachable (tunnel wedge or no device)")
    return True


def test_resnet50_serve_path_meets_north_star(device_ok, tmp_path):
    """Config 3 through build -> deploy -> HTTP invoke on the chip.

    The north-star p50 is asserted NET of the environment's measured
    device->host transport floor: this image reaches its chip through a
    remote-tunnel PJRT plugin where every fetch of a fresh device result
    pays one network RTT (~66 ms measured; h2d stays sub-ms), which no
    serving stack can engineer away from inside a synchronous invoke. On
    real locally-attached hardware the floor is ~0 and the assertion
    converges to the plain end-to-end budget."""
    from measure_baseline import measure_config, publish

    rec = measure_config(3, invokes=50, work=tmp_path)
    assert rec["platform"] not in ("cpu",), rec
    p50_net = rec.get("serve_overhead_p50_ms", rec["invoke_p50_ms"])
    assert p50_net < 15.0, rec                # BASELINE.json north star
    assert rec["cold_start_s"] < 10.0, rec    # cold-start budget
    publish({"config3": rec})


def test_bert_serve_path_on_device(device_ok, tmp_path):
    """Config 4 (jax BERT) boots and serves on the chip; latency recorded."""
    from measure_baseline import measure_config, publish

    rec = measure_config(4, invokes=30, work=tmp_path)
    assert rec["platform"] not in ("cpu",), rec
    p50_net = rec.get("serve_overhead_p50_ms", rec["invoke_p50_ms"])
    assert p50_net < 100.0, rec  # sanity bound, not the star
    publish({"config4": rec})


def test_llama_int8_generate_serve_path(device_ok, tmp_path):
    """Config 5's serve path (int8 weights + compile-once decode) on the
    chip, at the single-chip exemplar scale; the full 8B recipe's v5e-4
    sharding is proven by the CPU-mesh dryrun, whose evidence rides in
    the published record."""
    import subprocess
    import sys as _sys
    from pathlib import Path as _Path

    from measure_baseline import measure_config, publish

    rec = measure_config(5, invokes=20, work=tmp_path)
    assert rec["platform"] not in ("cpu",), rec
    assert rec.get("decode_tok_s", 0) > 50, rec  # sanity: real decode speed
    dry = subprocess.run(
        [_sys.executable, str(_Path(__file__).parents[1] / "__graft_entry__.py")],
        capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ, "GRAFT_DRYRUN_DEVICES": "8"})
    assert dry.returncode == 0, (dry.stdout + dry.stderr)[-500:]
    lines = dry.stdout.strip().splitlines()
    rec["multichip_dryrun"] = "pass: " + (lines[-1] if lines else "(no output)")
    publish({"config5": rec})
