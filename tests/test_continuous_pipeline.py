"""Pipelined continuous engine: >= 2 segments in flight so device
compute overlaps the host fetch + bookkeeping window. The contract under
test is BITWISE parity with the synchronous depth-1 loop — rows that
finish mid-pipeline have their over-decoded tails discarded host-side,
joiners force a bounded drain, and none of it may change a single
token."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from lambdipy_tpu.runtime.continuous import ContinuousBatcher

# tiny_server: the session-scoped shared LlamaServer from conftest.py
# (one compiled-program cache across the continuous-engine modules)


def test_depth_parity_greedy_and_sampled(tiny_server):
    """The same concurrent mix (greedy + seeded-sampled rows) produces
    bitwise identical outputs at pipeline depth 1 (the synchronous
    loop), 2 and 3 — and all of them equal solo."""
    reqs = [
        dict(prompt=[1, 2, 3], kw={}),
        dict(prompt=[9, 8, 7, 6], kw=dict(temperature=0.9, seed=7)),
        dict(prompt=[4, 4], kw=dict(temperature=1.2, top_k=3, seed=11)),
    ]
    solo = [tiny_server.generate(r["prompt"], max_new_tokens=12,
                                 **r["kw"]) for r in reqs]
    for depth in (1, 2, 3):
        cb = ContinuousBatcher(tiny_server, slots=4, segment=4,
                               pipeline_depth=depth)
        with ThreadPoolExecutor(max_workers=3) as ex:
            futs = [ex.submit(cb.generate, r["prompt"], max_new_tokens=12,
                              **r["kw"]) for r in reqs]
            for i, f in enumerate(futs):
                np.testing.assert_array_equal(
                    f.result(), solo[i],
                    err_msg=f"depth {depth} request {i} diverged")
        stats = cb.stats()
        assert stats["pipeline_depth"] == depth
        assert stats["requests_served"] == 3, stats


def test_eos_overdecode_truncated_exactly(tiny_server):
    """A row hitting eos mid-pipeline keeps decoding on the device until
    the next drain barrier; the over-decoded tail is discarded host-side
    and the output (eos latch + filler tail) is bitwise the solo
    path's. The discarded tokens show up in the wasted counter."""
    free = tiny_server.generate([5, 6, 7, 8], max_new_tokens=16)[0]
    eos = int(free[2])  # a token the row actually emits early
    solo = tiny_server.generate([5, 6, 7, 8], max_new_tokens=16,
                                eos_id=eos)
    cb = ContinuousBatcher(tiny_server, slots=2, segment=4,
                           pipeline_depth=3)
    out = cb.generate([5, 6, 7, 8], max_new_tokens=16, eos_id=eos)
    np.testing.assert_array_equal(out, solo)
    # generate() returns the moment the row's finish is observed; the
    # over-decoded blocks behind the frontier are still draining — wait
    # for the collector to catch up before reading its counters
    deadline = time.monotonic() + 10
    pipe = cb.stats()["pipeline"]
    while time.monotonic() < deadline \
            and pipe["segments"] < pipe["dispatches"]:
        time.sleep(0.01)
        pipe = cb.stats()["pipeline"]
    # eos landed in the first segment while later segments were already
    # dispatched: those blocks were fetched and thrown away
    assert pipe["wasted_overdecode_tokens"] > 0, pipe
    assert pipe["drains"].get("complete", 0) >= 1, pipe


def test_midstream_joiner_forces_bounded_drain(tiny_server):
    """A joiner arriving while segments are in flight drains the
    pipeline (at most depth-1 segments), packs at the barrier, and both
    rows still match solo. The in-flight histogram proves the frontier
    never exceeded the configured depth."""
    depth = 3
    cb = ContinuousBatcher(tiny_server, slots=4, segment=4,
                           pipeline_depth=depth)
    long_prompt, late_prompt = [1, 2, 3, 4, 5], [9, 8, 7]
    solo_long = tiny_server.generate(long_prompt, max_new_tokens=64)
    solo_late = tiny_server.generate(late_prompt, max_new_tokens=8)

    out = {}

    def late():
        # join once the long row is demonstrably mid-decode (not a wall
        # clock guess): 2 of its 16 segments collected, 14 to go
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and cb.stats()["segments_run"] < 2:
            time.sleep(0.002)
        out["late"] = cb.generate(late_prompt, max_new_tokens=8)

    t = threading.Thread(target=late)
    t.start()
    out["long"] = cb.generate(long_prompt, max_new_tokens=64)
    t.join()
    np.testing.assert_array_equal(out["long"], solo_long)
    np.testing.assert_array_equal(out["late"], solo_late)
    pipe = cb.stats()["pipeline"]
    assert pipe["in_flight"], pipe
    assert max(int(d) for d in pipe["in_flight"]) <= depth, pipe
    # the joiner interrupted an in-flight frontier at least once (its
    # arrival is gated on the long row being mid-decode, so an engine
    # that never drained for it would mean joins no longer work
    # mid-flight)
    assert pipe["drains"].get("joiner", 0) >= 1, pipe


def test_prefix_join_and_stream_pipelined(tiny_server):
    """prefix= rows (cached-KV continuation carries) and streamed
    requests ride the pipelined engine with fused-path parity — the
    SAME shared scenarios test_continuous.py runs at the default depth,
    here at depth 3 (deeper frontier = more over-decode to discard)."""
    from tests.test_continuous import (assert_prefix_join_parity,
                                       assert_stream_eos_latch)

    cb = ContinuousBatcher(tiny_server, slots=4, segment=4,
                           pipeline_depth=3)
    assert_prefix_join_parity(tiny_server, cb)
    assert_stream_eos_latch(tiny_server, cb)


def test_depth1_keeps_synchronous_frontier(tiny_server):
    """pipeline_depth=1 is today's behavior: every segment is collected
    before the next dispatch, so the in-flight depth never exceeds 1 and
    no drain barriers fire."""
    cb = ContinuousBatcher(tiny_server, slots=2, segment=4,
                           pipeline_depth=1)
    out = cb.generate([1, 2, 3], max_new_tokens=12)
    np.testing.assert_array_equal(
        out, tiny_server.generate([1, 2, 3], max_new_tokens=12))
    pipe = cb.stats()["pipeline"]
    assert set(pipe["in_flight"]) == {"1"}, pipe
    assert pipe["drains"] == {}, pipe
    assert pipe["segments"] == pipe["dispatches"], pipe


def test_synthetic_rtt_keeps_parity(tiny_server):
    """The bench's synthetic-fetch-RTT hook only delays the collector —
    tokens stay bitwise identical (this is what lets bench.py --pipeline
    claim parity while measuring the overlap win)."""
    solo = tiny_server.generate([2, 4, 6], max_new_tokens=8)
    cb = ContinuousBatcher(tiny_server, slots=2, segment=4,
                           pipeline_depth=2, synthetic_fetch_rtt_ms=5.0)
    np.testing.assert_array_equal(
        cb.generate([2, 4, 6], max_new_tokens=8), solo)
    pipe = cb.stats()["pipeline"]
    assert pipe["fetch_block_s"] > 0, pipe
