"""EWMA cost model of per-request service time.

Deadline feasibility needs an answer to "how long will THIS request
take?" before it runs. Service time on the decode path is close to
affine in the token counts — a fixed overhead, a per-prefill-token cost
and a per-decode-token cost (decode re-reads all weights every step, so
the decode term dominates at scale) — so the estimator fits

    ms  ≈  overhead + prefill_rate * prefill_tokens + decode_rate * decode_tokens

online with a normalized-LMS update (a per-sample gradient step scaled
by the feature norm: the exponential forgetting makes it the
multi-feature generalization of an EWMA, and it degrades gracefully to a
plain EWMA of request latency when token counts are unknown). Weights
are clamped non-negative — a transient can't drive a negative cost and
a nonsense (negative) estimate.

Fed by the server after every completed invoke with the same latencies
``LatencyStats`` records; consumed by admission (deadline shedding) and
the scheduler's queue-wait estimate.
"""

from __future__ import annotations

import threading


class CostEstimator:
    # token features enter the fit divided by this: raw counts (10^2-10^4)
    # against a unit bias feature make normalized-LMS converge on the
    # token weights orders of magnitude slower than on the bias (the
    # norm term is dominated by the largest feature) — scaling to
    # "64-token blocks" puts all features at comparable magnitude
    TOKEN_SCALE = 64.0

    def __init__(self, *, alpha: float = 0.2, default_ms: float = 50.0):
        self.alpha = alpha
        self.default_ms = default_ms
        self._lock = threading.Lock()
        self.samples = 0
        self._ewma_ms = None           # plain EWMA over all requests
        # affine weights over (1, prefill/SCALE, decode/SCALE)
        self._w = [default_ms, 0.0, 0.0]

    def _features(self, prefill_tokens: int,
                  decode_tokens: int) -> tuple[float, float, float]:
        return (1.0, max(0, prefill_tokens) / self.TOKEN_SCALE,
                max(0, decode_tokens) / self.TOKEN_SCALE)

    def observe(self, ms: float, prefill_tokens: int = 0,
                decode_tokens: int = 0) -> None:
        ms = max(0.0, float(ms))
        with self._lock:
            self.samples += 1
            self._ewma_ms = (ms if self._ewma_ms is None else
                             (1 - self.alpha) * self._ewma_ms + self.alpha * ms)
            x = self._features(prefill_tokens, decode_tokens)
            pred = sum(w * xi for w, xi in zip(self._w, x))
            err = ms - pred
            norm = sum(xi * xi for xi in x)
            step = self.alpha * err / norm
            self._w = [max(0.0, w + step * xi)
                       for w, xi in zip(self._w, x)]

    def estimate(self, prefill_tokens: int = 0,
                 decode_tokens: int = 0) -> float:
        """Predicted service ms for a request of this shape."""
        with self._lock:
            if self.samples == 0:
                return self.default_ms
            x = self._features(prefill_tokens, decode_tokens)
            affine = sum(w * xi for w, xi in zip(self._w, x))
            # never below half the observed mean: a cold affine fit can
            # underestimate wildly before the rates converge
            return max(affine, 0.5 * self._ewma_ms)

    def mean_ms(self) -> float:
        """EWMA of request latency regardless of shape (queue-wait math)."""
        with self._lock:
            return self.default_ms if self._ewma_ms is None else self._ewma_ms

    def report(self) -> dict:
        with self._lock:
            return {
                "samples": self.samples,
                "ewma_ms": (None if self._ewma_ms is None
                            else round(self._ewma_ms, 3)),
                "overhead_ms": round(self._w[0], 3),
                "ms_per_prefill_token": round(self._w[1] / self.TOKEN_SCALE,
                                              5),
                "ms_per_decode_token": round(self._w[2] / self.TOKEN_SCALE,
                                             5),
            }
