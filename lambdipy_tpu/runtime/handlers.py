"""Handler builders for the builtin model recipes.

A handler is what a bundle's generated ``handler.py`` delegates to: a
builder ``(spec, ctx) -> state`` where the returned state exposes
``invoke(request: dict) -> dict``. Requests/responses are JSON dicts (the
Lambda handler shape the reference's users write by hand — SURVEY.md §4 B
"user zips build/ + handler.py"; here handlers are generated and TPU-aware).

Every JAX handler jits once at init (cold start), accepts
``{"warmup": true}``, and supports ``{"random": true}`` for benchmarking
without a real payload.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class HandlerState:
    invoke_fn: Callable[[dict], dict]
    meta: dict
    # optional live-stats provider merged into /metrics (e.g. the decode
    # server's bucket/compile counters); must be cheap and non-blocking
    stats_fn: Callable[[], dict] | None = None
    # optional streaming invoke: request -> iterator of chunk dicts,
    # last one carrying {"done": true}. None = handler can't stream.
    invoke_stream_fn: Callable[[dict], Any] | None = None
    # optional host-only probe: prompt token ids -> tokens the automatic
    # prefix cache would reuse. The HTTP scheduler prices admission on
    # the SUFFIX a request will actually prefill (runtime/server.py) —
    # without this, deadline shedding over-rejects cache-hit requests.
    prefix_probe: Callable[[Any], int] | None = None
    # optional O(1) readiness probe: True while a background warm
    # (bucket / group-prefill compiles) is in flight. /healthz reads
    # THIS — not the full stats() document — once per fleet probe
    # interval, so it must stay a bare flag read, no locks or
    # serialization.
    warming_fn: Callable[[], bool] | None = None
    # optional O(1) engine-fault probe: {"wedged", "restarting",
    # "degrade_level"} from the continuous engine's fault-isolation
    # layer. /healthz flips ready:false (and reports wedged:true) on it
    # so the fleet router ejects a wedged replica at probe speed, and
    # server admission 503s instead of queueing requests into a dead
    # engine. Same cost contract as warming_fn: bare attribute reads.
    engine_fault_fn: Callable[[], dict] | None = None
    # optional disaggregated-serving KV ship surface (runtime/kvwire.py
    # framing over the prefix store): kv_export_fn serves a request's
    # whole-block head as a wire frame (prefilling missing blocks — on
    # a prefill-class replica this IS the request's prefill phase);
    # kv_import_fn registers a shipped frame in the radix tree. None =
    # no prefix store, /v1/kv/* answers 404.
    kv_export_fn: Callable[[dict], Any] | None = None
    kv_import_fn: Callable[[bytes], dict] | None = None
    # CHUNKED (pipelined-ship) twins: kv_export_stream_fn returns a
    # generator of wire frames (LKVS header first, then LKVC chunks —
    # each flushed as soon as the prefix-store walk produces its block
    # group, so wire transfer overlaps the remaining prefill);
    # kv_import_stream_fn consumes an iterator of raw byte chunks off
    # a chunked-transfer request body, staging each chunk as it lands
    # and attaching to the radix tree only on a complete stream (a
    # truncated/garbage stream rolls back, touching nothing).
    kv_export_stream_fn: Callable[[dict], Any] | None = None
    kv_import_stream_fn: Callable[[Any], dict] | None = None
    # optional host-only KV presence probe ({"tokens": [...]} ->
    # {"matched": n}): the router's import-miss PULL checks it before
    # trusting a ship-dedup entry (an arena reset may have flushed the
    # blocks the dedup cache still claims are there). O(depth) tree
    # walk, no device work.
    kv_probe_fn: Callable[[dict], dict] | None = None
    # optional session close (DELETE /v1/sessions/{id} -> release the
    # session's prefix-store pins now instead of waiting out the lease)
    session_end_fn: Callable[[str], dict] | None = None
    # optional host-only invariant sweep (GET /v1/debug/invariants):
    # pagepool conservation + prefix-store pin/content accounting as
    # {"ok", "checks"} — the chaos checker's quiesce probe. Cheap and
    # lock-bounded; never device work.
    debug_invariants_fn: Callable[[], dict] | None = None
    # optional host-only fault control (POST /v1/debug/faults): arm a
    # runtime/faults.py spec on the replica's live plan or clear it —
    # the chaos soak's nemesis arms composed faults on a timeline
    # through this instead of restarting the process per spec.
    faults_admin_fn: Callable[[dict], dict] | None = None
    # optional host-only live-knob control (POST /v1/debug/knobs): the
    # elastic fleet controller retunes a serving replica's
    # pipeline_depth / spec_k from its own published signals. Both
    # knobs are read per-dispatch by the continuous engine, so a live
    # write is race-free; the handler clamps/buckets and refuses what
    # the boot config never enabled.
    knobs_admin_fn: Callable[[dict], dict] | None = None

    def invoke(self, request: dict) -> dict:
        t0 = time.monotonic()
        out = self.invoke_fn(dict(request or {}))
        out.setdefault("latency_ms", round((time.monotonic() - t0) * 1e3, 3))
        return out

    def invoke_stream(self, request: dict):
        if self.invoke_stream_fn is None:
            raise ValueError("handler does not support streaming")
        return self.invoke_stream_fn(dict(request or {}))

    def stats(self) -> dict:
        if self.stats_fn is None:
            return {}
        try:
            return self.stats_fn()
        except Exception:  # stats must never break the metrics endpoint
            return {}


# --------------------------------------------------------------------------


def hello_handler(spec: dict, ctx) -> HandlerState:
    """Config 1: numpy+scipy hello world — a small deterministic linalg op
    proving the vendored native stack works inside the bundle."""
    import numpy as np
    from scipy import linalg

    def invoke(req: dict) -> dict:
        n = int(req.get("n", 64))
        rng = np.random.default_rng(int(req.get("seed", 0)))
        a = rng.normal(size=(n, n))
        sign, logdet = np.linalg.slogdet(a @ a.T + n * np.eye(n))
        lu = linalg.lu_factor(a + n * np.eye(n))[0]
        return {
            "ok": True,
            "n": n,
            "logdet": float(logdet * sign),
            "lu_trace": float(np.trace(lu)),
            "numpy": np.__version__,
        }

    return HandlerState(invoke_fn=invoke, meta={"model": "hello"})


def tabular_handler(spec: dict, ctx) -> HandlerState:
    """Config 2: sklearn (+xgboost when vendored) tabular inference."""
    import numpy as np

    from lambdipy_tpu.models import registry

    clf = registry.load_params("tabular", ctx.params_dir)
    n_features = getattr(clf, "n_features_in_", 16)
    degraded = ctx.degraded()

    def invoke(req: dict) -> dict:
        if req.get("warmup") or req.get("random"):
            x = np.zeros((1, n_features))
        else:
            x = np.asarray(req["instances"], dtype=float)
            if x.ndim == 1:
                x = x[None, :]
        proba = clf.predict_proba(x)
        return {
            "ok": True,
            "predictions": proba.argmax(1).tolist(),
            "probabilities": proba.tolist(),
            "degraded": degraded,  # e.g. ["xgboost"] in this offline env
        }

    return HandlerState(invoke_fn=invoke,
                        meta={"model": "tabular", "n_features": n_features})


# --------------------------------------------------------------------------


def _jax_adapter_and_params(spec: dict, ctx):
    from lambdipy_tpu.models import registry

    extra = dict(spec.get("extra") or {})
    # HF-imported bundles record the converted architecture in the
    # manifest; it overrides the builder defaults so the module matches
    # the checkpoint exactly (models/convert.py save_hf_params)
    info = (getattr(ctx, "manifest", None) or {}).get("payload", {}) or {}
    extra.update((info.get("params_info") or {}).get("config") or {})
    adapter = registry.get(spec["model"]).build(
        dtype=spec.get("dtype", "bfloat16"), quant=spec.get("quant"),
        extra=extra)
    if ctx.params_dir is not None:
        # single-device payloads take the bulk-transfer device load; a
        # mesh payload loads host-side so the sharder can place it
        single = not any(v > 1 for v in (spec.get("mesh") or {}).values())
        params = registry.load_params(spec["model"], ctx.params_dir,
                                      device=single)
    else:
        params = adapter.init_params(seed=0)
    return adapter, params


def _aot_or_jit(ctx, fn, example_args, mesh):
    """Boot from the bundle's AOT store (runtime/aot.py). Single-chip
    payloads get both tiers; meshed payloads get the StableHLO tier keyed
    by (topology, mesh shape), so a multi-device boot stops re-tracing
    once any boot on the same topology has saved it.

    AOT artifacts are shape-specialized to the spec's example batch, so a
    hit is wrapped with a shape dispatch: example-shaped requests (the hot
    serving path) run the AOT program, anything else re-traces through a
    plain jit fallback exactly as before AOT existed.
    """
    import jax

    from lambdipy_tpu.runtime.aot import cached_jit

    cached, src = cached_jit(ctx, "forward", fn, example_args, mesh=mesh)
    if src == "jit":
        return cached, src
    fallback = jax.jit(fn)
    shapes = tuple(getattr(a, "shape", None) for a in example_args[1:])

    def dispatch(params, *args):
        if tuple(getattr(a, "shape", None) for a in args) == shapes:
            return cached(params, *args)
        return fallback(params, *args)

    return dispatch, src


def _maybe_shard(adapter, params, spec: dict):
    """Place params on the payload mesh when it needs more than one device;
    single-chip serving device-puts them once instead.

    The single-chip device_put is load-bearing, not cosmetic: checkpoint
    restore yields HOST arrays, and jit re-transfers host arrays on EVERY
    call (measured through the axon tunnel: ~3 s/invoke for ResNet-50's
    51 MB vs 0.2 ms once the params live on device)."""
    import jax

    mesh_shape = {k: v for k, v in (spec.get("mesh") or {}).items() if v > 1}
    if not mesh_shape:
        return jax.device_put(params), None
    from lambdipy_tpu.parallel.mesh import make_mesh
    from lambdipy_tpu.parallel.sharding import shard_params

    needed = 1
    for v in mesh_shape.values():
        needed *= v
    if len(jax.devices()) < needed:
        # degrade to single-device — LOUDLY: the operator declared a
        # mesh and is getting replicated serving instead (the usual
        # cause on CPU: XLA_FLAGS host-device forcing not set)
        from lambdipy_tpu.utils.logs import get_logger

        get_logger("lambdipy.handlers").warning(
            "mesh %s needs %d devices but only %d are visible: "
            "degrading to SINGLE-DEVICE serving (meta.sharded=false)",
            mesh_shape, needed, len(jax.devices()))
        return jax.device_put(params), None
    # the first `needed` devices, not all of them: a host with more
    # chips than the declared mesh (or a CPU with forced host devices)
    # must still honor the bundle's shape instead of erroring on the
    # device-count mismatch
    mesh = make_mesh(mesh_shape, devices=jax.devices()[:needed])
    return shard_params(params, mesh, adapter.tp_rules), mesh


def image_classify_handler(spec: dict, ctx) -> HandlerState:
    """Config 3 / north star: ResNet-50 image classification on v5e."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    adapter, params = _jax_adapter_and_params(spec, ctx)
    params, mesh = _maybe_shard(adapter, params, spec)
    batch = int(spec.get("batch_size", 1))
    example = adapter.example_batch(batch)[0]
    fwd, aot_src = _aot_or_jit(ctx, adapter.forward, (params, example), mesh)

    def run(x):
        if mesh is not None:
            with mesh:
                return fwd(params, x)
        return fwd(params, x)

    def invoke(req: dict) -> dict:
        if req.get("warmup") or req.get("random"):
            x = example
        else:
            x = jnp.asarray(np.asarray(req["image"], dtype=np.float32),
                            example.dtype)
            if x.ndim == 3:
                x = x[None, ...]
        logits = np.asarray(jax.device_get(run(x)), dtype=np.float32)
        top = np.argsort(-logits, axis=-1)[:, :5]
        return {
            "ok": True,
            "top5": top.tolist(),
            "top1": top[:, 0].tolist(),
            "logit_max": float(logits.max()),
        }

    return HandlerState(invoke_fn=invoke, meta={
        "model": spec["model"], "batch": batch,
        "sharded": mesh is not None, "aot": aot_src,
        "platform": jax.devices()[0].platform,
    })


def text_classify_handler(spec: dict, ctx) -> HandlerState:
    """Config 4 (jax path): BERT text classification."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    adapter, params = _jax_adapter_and_params(spec, ctx)
    params, mesh = _maybe_shard(adapter, params, spec)
    cfg = adapter.config
    example_ids, example_mask = adapter.example_batch(int(spec.get("batch_size", 1)))
    fwd, aot_src = _aot_or_jit(
        ctx, adapter.forward, (params, example_ids, example_mask), mesh)

    def run(ids, mask):
        if mesh is not None:
            with mesh:
                return fwd(params, ids, mask)
        return fwd(params, ids, mask)

    def invoke(req: dict) -> dict:
        if req.get("warmup") or req.get("random"):
            ids, mask = example_ids, example_mask
        else:
            raw = np.asarray(req["input_ids"], dtype=np.int32)
            if raw.ndim == 1:
                raw = raw[None, :]
            ids = np.zeros((raw.shape[0], cfg.max_len), np.int32)
            mask = np.zeros((raw.shape[0], cfg.max_len), np.int32)
            n = min(cfg.max_len, raw.shape[1])
            ids[:, :n] = raw[:, :n]
            mask[:, :n] = 1
            ids, mask = jnp.asarray(ids), jnp.asarray(mask)
        logits = np.asarray(jax.device_get(run(ids, mask)), dtype=np.float32)
        return {
            "ok": True,
            "labels": logits.argmax(-1).tolist(),
            "logits": logits.tolist(),
        }

    return HandlerState(invoke_fn=invoke, meta={
        "model": spec["model"], "max_len": cfg.max_len,
        "sharded": mesh is not None, "aot": aot_src,
    })


def generate_handler(spec: dict, ctx) -> HandlerState:
    """Config 5: Llama TP int8 generation (greedy by default; requests may
    set temperature / top_k / top_p / seed / eos_id for sampled decode)."""
    import threading as _threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    extra = spec.get("extra") or {}
    # tensor-parallel sharded serving (ROADMAP direction 3): the `mesh`
    # bundle extra ("tp=2", "2x2", "tp=2,sp=1"...) — or LAMBDIPY_MESH,
    # the `lambdipy serve --mesh` bridge; an explicit extra wins over
    # the env like every other knob — resolves into the spec-level mesh
    # shape `_maybe_shard` places params by. The whole serve stack then
    # runs SPMD over the mesh: attention heads / MLP hidden shard over
    # tp, the KV cache over kv_heads, host-side engine logic unchanged.
    # CPU testing: XLA_FLAGS=--xla_force_host_platform_device_count=N.
    import os as _os_env

    raw_mesh = extra.get("mesh", _os_env.environ.get("LAMBDIPY_MESH"))
    if raw_mesh is not None:
        from lambdipy_tpu.parallel.mesh import parse_mesh_spec

        # an explicit knob REPLACES any spec-level [payload.mesh] —
        # including replacing it with nothing: `--mesh off` (parse ->
        # {}) must actually serve single-device, not silently keep the
        # bundle's declared mesh
        spec = {**spec, "mesh": parse_mesh_spec(str(raw_mesh))}
    # Cold-start overlap (VERDICT r5 #5): AOT executable deserialization
    # + remote program loads need no weights, and the bulk weight upload
    # needs no programs — run them CONCURRENTLY instead of serially (at
    # 8B through the tunnel the serial order was 54.6 s weights THEN
    # ~220 s programs). The store is created before the params load and
    # its preload thread joins right after; LlamaServer then consumes
    # the preloaded executables with only the probe left to pay.
    serve_aot_store = None
    preload_state: dict = {}
    preload_thread = None
    single_spec = not any(v > 1 for v in (spec.get("mesh") or {}).values())
    if single_spec and getattr(ctx, "bundle_dir", None) is not None \
            and str(extra.get("serve_aot", "1")) != "0":
        from lambdipy_tpu.runtime.aot import AotStore

        # gate sized for decode programs (an honest 8B 64-token decode
        # call is ~700 ms — the default 500 ms forward-program gate
        # would reject it as "slow")
        serve_aot_store = AotStore(
            ctx.bundle_dir,
            gate_ms=float(extra.get("serve_aot_gate_ms", 30000)))
        # preload only the CURRENT generation's artifacts: an upgraded
        # bundle's aot/ dir keeps the previous generation's orphans,
        # and device-loading those would pay the very remote program
        # loads this overlap hides, for executables load() never reads
        from lambdipy_tpu.models.llama import LlamaServer as _LS

        preload_thread = _threading.Thread(
            target=lambda: preload_state.update(
                serve_aot_store.preload(prefix=_LS.aot_prefix())),
            daemon=True, name="aot-preload")
        preload_thread.start()

    adapter, params = _jax_adapter_and_params(spec, ctx)
    params, mesh = _maybe_shard(adapter, params, spec)
    if preload_thread is not None:
        preload_thread.join()
    default_new = int(extra.get("max_new_tokens", 16))
    # compile-once serving: prompt-length bucketing + runtime sampling
    # knobs, one compiled program per shape bucket (llama.LlamaServer)
    server = None
    batcher = None
    continuous = None  # set when batcher is the ContinuousBatcher
    if adapter.make_server is not None:
        cap = extra.get("decode_cap")  # None = full context window
        server_caps = {"decode_cap": int(cap) if cap else None}
        if extra.get("prefix_cache_max") is not None:
            # operators serving many (or deliberately few) prefixes; an
            # explicit 0 means "smallest" (the server clamps to 1)
            server_caps["prefix_cache_max"] = int(extra["prefix_cache_max"])
        if extra.get("program_cache_max") is not None:
            # LRU bound on compiled programs; size to the workload's
            # bucket diversity (rising program_evictions in /metrics
            # means it is too small)
            server_caps["program_cache_max"] = int(extra["program_cache_max"])
        if extra.get("prefill_chunk") is not None:
            # long prefixes prefill in fixed-width chunks: dense-attention
            # memory O(chunk x s) instead of O(s^2), O(1) programs
            server_caps["prefill_chunk"] = int(extra["prefill_chunk"])
        if extra.get("min_bucket") is not None:
            # smallest prompt/decode bucket. The default 16 makes a
            # max_new_tokens=1 request run a 16-step scan — ~16 wasted
            # weight reads (~165 ms at 8B): scoring/logprob workloads
            # dominated by tiny decodes should set 1, trading a few
            # more compiled program variants per distinct length
            server_caps["min_bucket"] = int(extra["min_bucket"])
        if mesh is None and getattr(ctx, "bundle_dir", None) is not None \
                and str(extra.get("serve_aot", "1")) != "0":
            # serving programs ride the bundle's AOT exec tier: at real
            # scale each is a ~70 s remote compile, and a loaded
            # executable boots in seconds. Normally the store was built
            # above (its preload overlapped the weight upload); the
            # degraded case (spec asked for a mesh this host can't
            # provide) builds it here without preload.
            if serve_aot_store is None:
                from lambdipy_tpu.runtime.aot import AotStore

                serve_aot_store = AotStore(
                    ctx.bundle_dir,
                    gate_ms=float(extra.get("serve_aot_gate_ms", 30000)))
            server_caps["aot"] = serve_aot_store
        server = adapter.make_server(params, mesh=mesh, **server_caps)
        window_ms = float(extra.get("batch_window_ms", 0) or 0)
        batch_mode = str(extra.get("batch_mode", "") or "").lower()
        # batch formation dequeues by the bundle's scheduling policy
        # (the same [payload.extra] sched_policy the HTTP scheduler
        # uses), so request class survives INTO the batchers.
        # LAMBDIPY_SCHED_POLICY is the serve-process override (set by
        # `lambdipy serve --sched-policy`): the handler is built inside
        # load_bundle, before the server's scheduler exists, so the CLI
        # choice reaches batch formation through the environment.
        import os as _os

        # default matches the HTTP scheduler's default ("fair"), so batch
        # formation honors class fairness even when nothing is configured
        # — /metrics reporting policy "fair" while batches board FIFO
        # would be a lie
        pol_name = (_os.environ.get("LAMBDIPY_SCHED_POLICY")
                    or extra.get("sched_policy") or "fair")
        from lambdipy_tpu.sched.policy import make_policy

        sched_policy = make_policy(str(pol_name))
        # ONE resolution of the prefix block width, shared by the page
        # pool (page width) and the prefix store (radix block) below —
        # they must agree by construction, not by parallel parsing
        raw_block = _os.environ.get("LAMBDIPY_PREFIX_BLOCK")
        if raw_block in (None, ""):
            raw_block = extra.get("prefix_block")
        prefix_block = (int(raw_block) if raw_block not in (None, "")
                        else 32)
        # one deterministic fault plan shared by the engine's sites AND
        # the prefix store's prefix_walk site (chaos specs arm a
        # replica's whole serve path through one LAMBDIPY_FAULT)
        engine_faults = None
        if batch_mode == "continuous":
            from lambdipy_tpu.runtime.continuous import ContinuousBatcher

            # requests join an in-flight decode at segment boundaries.
            # batch_cache_len bounds the B-slot KV allocation (B full-
            # window caches otherwise — at 8B dims that is HBM that the
            # operator must be able to cap per bundle)
            bcl = extra.get("batch_cache_len")
            # length-aware window bucketing (on by default): pow-2
            # window program variants are compiled AT FIRST USE per
            # bucket (deliberately un-AOT-able), so a latency-critical
            # bundle on a slow-compile transport can opt out via
            # `batch_window_bucketing = "0"` (or the
            # LAMBDIPY_WINDOW_BUCKETING env default) and keep the
            # single AOT-warmed full-window segment program. Same
            # precedence as LAMBDIPY_ATTN_BACKEND: an explicit bundle
            # extra wins over the environment.
            wb = extra.get(
                "batch_window_bucketing",
                _os.environ.get("LAMBDIPY_WINDOW_BUCKETING", "1"))
            # pipelined dispatch/collect: segments kept in flight on the
            # device before the host fetches the oldest. 1 restores the
            # synchronous loop; the default 2 overlaps device compute
            # with the per-segment fetch RTT + host bookkeeping. Same
            # precedence as the window-bucketing knob: an explicit
            # bundle extra wins over the environment (set by
            # `lambdipy serve --pipeline-depth`).
            pd = extra.get(
                "pipeline_depth",
                _os.environ.get("LAMBDIPY_PIPELINE_DEPTH", "2"))
            # fault isolation knobs (runtime/faults.py): the watchdog
            # bounds device-side waits (0 = off — size it to the
            # transport: a first dispatch legitimately includes a
            # remote compile), max_replays caps transparent replays of
            # rows that delivered no bytes, and a fault spec arms the
            # deterministic injection sites for chaos tests. Extra wins
            # over env, like the pipeline-depth knob (the env vars are
            # the CLI bridge: `lambdipy serve --engine-watchdog`).
            wd = extra.get(
                "engine_watchdog_s",
                _os.environ.get("LAMBDIPY_ENGINE_WATCHDOG_S", "0"))
            mr = extra.get(
                "max_replays",
                _os.environ.get("LAMBDIPY_MAX_REPLAYS", "1"))
            fspec = extra.get("fault_spec",
                              _os.environ.get("LAMBDIPY_FAULT", ""))
            # engine-level speculative decoding (DEFAULT OFF this
            # release): spec_k >= 2 turns every engine segment into
            # draft -> batched verify -> accept/rollback with bitwise
            # outputs (continuous.py docstring). `spec_k` extra wins
            # over the LAMBDIPY_SPEC_K env (the `lambdipy serve
            # --spec-k` bridge), like the knobs above. Distinct from
            # the per-REQUEST `"speculative": k` field, which still
            # serves solo through generate_speculative.
            sk = extra.get("spec_k",
                           _os.environ.get("LAMBDIPY_SPEC_K", "0"))
            # draft tier for the engine's spec path (ROADMAP direction
            # 4): draft_mode picks the provider rows start on — lookup
            # (PR 9 behavior, default), model (self-drafting
            # shallow-exit head, per-row adaptive k + fallback), off.
            # draft_exit sets how many layers the shallow-exit draft
            # runs (clamped to the model's depth). Extra wins over env
            # (`lambdipy serve --draft-mode/--draft-exit` bridge).
            dmode = extra.get("draft_mode",
                              _os.environ.get("LAMBDIPY_DRAFT_MODE",
                                              "lookup"))
            dexit = extra.get("draft_exit",
                              _os.environ.get("LAMBDIPY_DRAFT_EXIT", "1"))
            # long-context tier (runtime/longctx.py, DEFAULT OFF):
            # max_logical_ctx > cache_len serves prompts past the
            # compiled window through a sliding logical window whose
            # evicted pages spill to a host offload arena (needs
            # --kv-paged); long_prefill opts the tier's prefill side
            # into the ring-attention path on sp meshes. Extra wins
            # over env (`lambdipy serve --max-logical-ctx` bridge).
            mlc = extra.get("max_logical_ctx",
                            _os.environ.get("LAMBDIPY_MAX_LOGICAL_CTX",
                                            "0"))
            lpf = extra.get("long_prefill",
                            _os.environ.get("LAMBDIPY_LONG_PREFILL", "0"))
            # whole-prompt sequence-parallel prefill (models/llama.py
            # sp_prefill family, DEFAULT "chunked"): "sp" runs every
            # cold prefill as ONE sharded program per round over the
            # mesh's sp axis — long-context rounds, the engine's group
            # prefill, and the prefix store's cold walk all route
            # through it. Requesting it without an sp mesh axis stands
            # down counted. Extra wins over env (`lambdipy serve
            # --prefill-mode` bridge).
            pfm = extra.get("prefill_mode",
                            _os.environ.get("LAMBDIPY_PREFILL_MODE",
                                            "chunked"))
            from lambdipy_tpu.runtime.faults import FaultPlan

            # paged KV memory (runtime/pagepool.py, DEFAULT OFF): one
            # refcounted page arena replaces the engine's B full-window
            # caches — admission charges actual tokens, prefix hits
            # share pages zero-copy, capacity rows scale with the
            # workload's real lengths. `kv_paged` extra wins over the
            # LAMBDIPY_KV_PAGED env (the `lambdipy serve --kv-paged`
            # bridge); `kv_pages` sizes the arena (default: the same
            # HBM the dense engine would allocate, slots x window).
            page_pool = None
            kvp = extra.get("kv_paged",
                            _os.environ.get("LAMBDIPY_KV_PAGED", "0"))
            if str(kvp).lower() not in ("", "0", "false", "off"):
                from lambdipy_tpu.models.llama import (init_page_arena,
                                                       page_kv_bytes)
                from lambdipy_tpu.runtime.pagepool import (PagePool,
                                                           page_width)

                cfg_m = server.model.cfg
                eng_len = min(int(bcl) if bcl else cfg_m.max_len,
                              cfg_m.max_len)
                page = page_width(eng_len, prefix_block)
                window_pages = eng_len // page
                raw_np = extra.get(
                    "kv_pages", _os.environ.get("LAMBDIPY_KV_PAGES"))
                n_pages = max(2, (int(raw_np)
                                  if raw_np not in (None, "") else
                                  int(extra.get("batch_max", 8))
                                  * window_pages + 1))
                page_pool = PagePool(
                    n_pages=n_pages, page=page,
                    page_bytes=page_kv_bytes(cfg_m, page),
                    # a meshed payload's arena is born kv-head-sharded
                    # (per-device arena HBM ~1/tp); page_bytes stays the
                    # LOGICAL page size — the pool's capacity accounting
                    # is mesh-agnostic by design
                    make_arena=(lambda n=n_pages, p=page, m=mesh:
                                init_page_arena(cfg_m, n, p, mesh=m)),
                    window_pages=window_pages)
            engine_faults = (FaultPlan.from_spec(str(fspec))
                             if str(fspec).strip() else None)
            batcher = continuous = ContinuousBatcher(
                server, slots=int(extra.get("batch_max", 8)),
                segment=int(extra.get("batch_segment", 16)),
                cache_len=int(bcl) if bcl else None,
                policy=sched_policy,
                window_bucketing=str(wb).lower() not in ("0", "false",
                                                         "off"),
                pipeline_depth=int(pd),
                watchdog_s=float(wd or 0),
                max_replays=int(mr),
                faults=engine_faults,
                page_pool=page_pool,
                spec_k=int(sk or 0),
                draft_mode=str(dmode or "lookup"),
                draft_exit=int(dexit or 1),
                max_logical_ctx=int(mlc or 0),
                long_prefill=str(lpf).lower() not in ("", "0", "false",
                                                      "off"),
                prefill_mode=str(pfm or "chunked").lower())
        elif window_ms > 0:
            from lambdipy_tpu.runtime.batching import MicroBatcher

            # concurrent same-knob requests share one ragged device call
            batcher = MicroBatcher(server, window_ms=window_ms,
                                   max_batch=int(extra.get("batch_max", 8)),
                                   policy=sched_policy)

    # automatic cross-request prefix KV cache (runtime/prefixstore.py):
    # the DEFAULT path for all single-row generate requests — the prompt
    # is longest-prefix-matched against a radix tree of cached KV blocks
    # and only the suffix prefills. `prefix_cache_mb` (bundle extra, or
    # `lambdipy serve --prefix-cache-mb` via the env bridge) budgets the
    # store's HBM; 0 disables. kv_quant bundles keep it OPT-IN: the
    # cached prefix reads back quantized, so on/off parity drops from
    # bitwise to quantization tolerance — the operator must choose that.
    prefix_store = None
    # configurations where routing permanently stands down must not
    # build (or advertise) a store at all: meta would claim the cache is
    # on, /metrics would export counters that can never move, and every
    # admission would probe a permanently empty tree
    routable = (batcher is None
                or (continuous is not None
                    and server is not None
                    and continuous.cache_len == server.model.cfg.max_len))
    if server is not None and routable:
        import os as _os_px

        raw_mb = _os_px.environ.get("LAMBDIPY_PREFIX_CACHE_MB")
        if raw_mb in (None, ""):
            raw_mb = extra.get("prefix_cache_mb")
        explicit_mb = raw_mb not in (None, "")
        mb = float(raw_mb) if explicit_mb else 512.0
        if mb > 0 and (server.model.cfg.kv_quant is None or explicit_mb):
            from lambdipy_tpu.runtime.prefixstore import PrefixStore

            # a paged engine's store shares the engine's page arena:
            # blocks live as refcounted pages and a hit is a refcount
            # bump through acquire_pages (zero-copy). `prefix_block`
            # is the ONE resolved block width the page pool sized by.
            paged_pool = (continuous.pool if continuous is not None
                          else None)
            # session-pin knobs (multi-turn chat): the pin budget caps
            # total bytes open sessions may hold out of eviction's
            # reach; ttl/idle are the lease (renewed every turn). Env
            # first like the cache-mb knob — `lambdipy serve
            # --session-pin-budget/--session-ttl` bridge through it.
            raw_pb = _os_px.environ.get("LAMBDIPY_SESSION_PIN_BUDGET_MB")
            if raw_pb in (None, ""):
                raw_pb = extra.get("session_pin_budget_mb")
            raw_ttl = _os_px.environ.get("LAMBDIPY_SESSION_TTL_S")
            if raw_ttl in (None, ""):
                raw_ttl = extra.get("session_ttl_s")
            raw_idle = _os_px.environ.get("LAMBDIPY_SESSION_IDLE_S")
            if raw_idle in (None, ""):
                raw_idle = extra.get("session_idle_s")
            prefix_store = PrefixStore(
                server, block=prefix_block, budget_mb=mb,
                pool=paged_pool,
                faults=(continuous.faults if continuous is not None
                        else None),
                pin_budget_mb=(float(raw_pb)
                               if raw_pb not in (None, "") else None),
                session_ttl_s=(float(raw_ttl)
                               if raw_ttl not in (None, "") else 3600.0),
                session_idle_s=(float(raw_idle)
                                if raw_idle not in (None, "") else 600.0),
                # the store's cold walk shares the engine's prefill
                # schedule + the ONE batching.prefill stats block
                prefill_mode=(continuous.prefill_mode
                              if continuous is not None else "chunked"),
                prefill_stats=(continuous.prefill_stats
                               if continuous is not None else None))
            if paged_pool is not None:
                continuous.prefix_pages_fn = prefix_store.acquire_pages
                # host KV offload tier (runtime/offload.py, DEFAULT
                # OFF): swept-cold store pages spill their kvwire bytes
                # to host RAM and re-online on demand instead of
                # re-prefilling. kv_offload.* gauges ride
                # batching.page_pool into /metrics via
                # pool.attach_offload; kv_offload_mb budgets the host
                # arena. Extra wins over env (`lambdipy serve
                # --kv-offload` bridge).
                kvo = extra.get(
                    "kv_offload",
                    _os_px.environ.get("LAMBDIPY_KV_OFFLOAD", "0"))
                if str(kvo).lower() not in ("", "0", "false", "off"):
                    from lambdipy_tpu.runtime.offload import OffloadArena

                    raw_omb = _os_px.environ.get("LAMBDIPY_KV_OFFLOAD_MB")
                    if raw_omb in (None, ""):
                        raw_omb = extra.get("kv_offload_mb")
                    prefix_store.attach_offload(OffloadArena(
                        page=paged_pool.page,
                        layers=server.model.cfg.layers,
                        budget_mb=(float(raw_omb)
                                   if raw_omb not in (None, "")
                                   else 256.0),
                        faults=continuous.faults))

    # disaggregated-serving KV ship surface (ROADMAP direction 4): a
    # prefill-class replica exports a prompt head's KV blocks as a wire
    # frame (runtime/kvwire.py), the router ships it, and the decode
    # replica's import is a radix insert — zero-copy into arena pages
    # under --kv-paged. Rides the prefix store, so it exists exactly
    # when automatic prefix caching does.
    kv_ship_stats = None
    kv_export = kv_import = kv_probe = None
    kv_export_stream = kv_import_stream = None
    if prefix_store is not None:
        from lambdipy_tpu.runtime.kvwire import (
            StreamDecoder,
            decode_frame,
            encode_chunk,
            encode_frame,
            encode_stream_header,
        )
        from lambdipy_tpu.runtime.metrics import KvShipStats
        from lambdipy_tpu.runtime.pagepool import PagesExhausted

        kv_ship_stats = KvShipStats()

        def kv_export(req: dict):
            """{"tokens": [...]} -> wire frame bytes, or an error dict
            (the server maps dicts to 400s)."""
            raw = req.get("tokens")
            if not isinstance(raw, (list, tuple)) or not raw or \
                    not all(isinstance(t, int) for t in raw):
                return {"ok": False,
                        "error": "kv export wants a flat token id list"}
            out = prefix_store.export_blocks(list(raw))
            if out is None:
                return {"ok": False,
                        "error": "no whole-block prefix to export"}
            head, blocks = out
            frame = encode_frame(head, prefix_store.block, blocks)
            kv_ship_stats.record_export(tokens=len(head),
                                        nbytes=len(frame))
            return frame

        def kv_import(data: bytes) -> dict:
            """Wire frame -> radix insert; ValueError on garbage frames
            (server maps to 400), PagesExhausted on a full arena
            (server maps to the priced-shed 503)."""
            try:
                tokens, block, blocks = decode_frame(data)
                if block != prefix_store.block:
                    raise ValueError(
                        f"frame block width {block} != this replica's "
                        f"prefix block {prefix_store.block}")
                res = prefix_store.import_blocks(tokens, blocks)
            except PagesExhausted:
                kv_ship_stats.record_backpressure()
                raise
            except ValueError:
                kv_ship_stats.record_rejected()
                raise
            kv_ship_stats.record_import(
                tokens=len(tokens), nbytes=len(data),
                inserted=res["inserted"], present=res["present"],
                mode=res["mode"])
            return {"ok": True, **res}

        def kv_probe(req: dict) -> dict:
            """{"tokens": [...]} -> how many head tokens are actually
            PRESENT in the radix tree (host-only; no device work). The
            router's import-miss pull calls this before trusting its
            ship-dedup cache."""
            raw = req.get("tokens")
            if not isinstance(raw, (list, tuple)) or not raw or \
                    not all(isinstance(t, int) for t in raw):
                return {"ok": False,
                        "error": "kv probe wants a flat token id list"}
            return {"ok": True,
                    "matched": prefix_store.present_len(list(raw)),
                    "block": prefix_store.block}

        def kv_export_stream(req: dict):
            """Chunked export twin: {"tokens": [...], "stream": true}
            -> generator of wire frames (LKVS header, then one LKVC
            per block group, flushed as the walk produces it). Returns
            an error dict (the server maps dicts to 400s) when the
            prompt has no whole block."""
            raw = req.get("tokens")
            if not isinstance(raw, (list, tuple)) or not raw or \
                    not all(isinstance(t, int) for t in raw):
                return {"ok": False,
                        "error": "kv export wants a flat token id list"}
            out = prefix_store.export_stream(list(raw))
            if out is None:
                return {"ok": False,
                        "error": "no whole-block prefix to export"}
            head, groups = out
            cfg = prefix_store.server.model.cfg
            leaves = [[name, dt.name, list(shape)]
                      for name, (shape, dt)
                      in sorted(prefix_store._leaf_template().items())]

            def gen():
                nbytes = sent = 0
                header = encode_stream_header(head, prefix_store.block,
                                              cfg.layers, leaves)
                nbytes += len(header)
                yield header
                chunks = 0
                for group in groups:
                    frame = encode_chunk(sent, group)
                    sent += len(group)
                    nbytes += len(frame)
                    chunks += 1
                    yield frame
                # recorded only on a COMPLETE stream: a truncated
                # export is the relay's mid-stream-failure signal, not
                # a served export
                kv_ship_stats.record_export(tokens=len(head),
                                            nbytes=nbytes,
                                            chunks=chunks)

            return gen()

        def kv_import_stream(chunks_iter, commit_gate=None) -> dict:
            """Chunked import twin: raw byte chunks off the wire ->
            strict per-chunk validation (kvwire.StreamDecoder) ->
            per-chunk staging -> one atomic radix attach at stream end.
            ValueError on garbage/out-of-order/truncated streams and
            PagesExhausted on a full arena propagate AFTER the staged
            pages are rolled back — a failed stream touches nothing.

            ``commit_gate`` (a context manager) brackets ONLY the
            commit: the stream's staging must not hold a run slot,
            because the body arrives over the lifetime of the exporting
            replica's prefill — a slot held across that wait would
            serialize the decode batch behind every in-flight ship,
            the very stall the phase split removes. Anything the gate
            raises aborts the staged pages like any other failure."""
            dec = StreamDecoder()
            imp = None
            nbytes = chunks = 0
            try:
                for data in chunks_iter:
                    nbytes += len(data)
                    for kind, payload in dec.feed(data):
                        if kind == "header":
                            if payload["block"] != prefix_store.block:
                                raise ValueError(
                                    f"stream block width "
                                    f"{payload['block']} != this "
                                    f"replica's prefix block "
                                    f"{prefix_store.block}")
                            imp = prefix_store.import_begin(
                                payload["tokens"])
                        elif imp is not None:
                            chunks += 1
                            imp.add_blocks(payload[1])
                if imp is None:
                    raise ValueError("empty KV stream (no header)")
                if not dec.complete:
                    raise ValueError(
                        f"truncated KV stream: "
                        f"{dec.blocks_received} block(s) arrived")
                if commit_gate is not None:
                    with commit_gate:
                        res = imp.commit()
                else:
                    res = imp.commit()
            except PagesExhausted:
                kv_ship_stats.record_backpressure()
                kv_ship_stats.record_stream_abort()
                if imp is not None:
                    imp.abort()
                raise
            except ValueError:
                kv_ship_stats.record_rejected()
                kv_ship_stats.record_stream_abort()
                if imp is not None:
                    imp.abort()
                raise
            except BaseException:
                kv_ship_stats.record_stream_abort()
                if imp is not None:
                    imp.abort()
                raise
            kv_ship_stats.record_import(
                tokens=len(imp.row), nbytes=nbytes,
                inserted=res["inserted"], present=res["present"],
                mode=res["mode"], chunks=chunks)
            return {"ok": True, **res, "streamed": True}

    # -- chaos/debug surfaces (runtime/faults.py + the invariant sweep) ------
    # ONE live fault plan serves the whole replica (engine sites, the
    # store's prefix_walk/session_pin, the pool's page_alloc): the
    # continuous engine always owns a plan (empty when nothing is
    # armed), so the soak's nemesis can arm/clear it at runtime over
    # POST /v1/debug/faults and /metrics can report what is armed.
    live_faults = None
    if continuous is not None:
        live_faults = continuous.faults
    elif prefix_store is not None:
        live_faults = prefix_store.faults

    def debug_invariants() -> dict:
        """Cheap host-side invariant sweep (GET /v1/debug/invariants —
        the chaos checker's quiesce probe, also a live debugging aid):
        page-pool conservation, prefix-store pin/content accounting,
        plus the engine fault state as context. ``ok`` covers the
        ACCOUNTING checks; transient serving state (wedged, degrade
        level) is reported but judged by /healthz, not here."""
        ok, checks = True, {}
        if continuous is not None and continuous.pool is not None:
            try:
                continuous.pool.check_invariants()
                checks["page_pool"] = {"ok": True}
            except AssertionError as e:
                checks["page_pool"] = {"ok": False, "error": str(e)}
            checks["page_pool"]["stats"] = continuous.pool.stats()
            ok = ok and checks["page_pool"]["ok"]
        if prefix_store is not None:
            checks["prefix_store"] = prefix_store.check_invariants()
            ok = ok and checks["prefix_store"]["ok"]
        if continuous is not None:
            checks["engine"] = continuous.fault_state()
        return {"ok": ok, "checks": checks}

    def faults_admin(req: dict) -> dict:
        """POST /v1/debug/faults (host-only): arm a fault spec on the
        live plan or clear it — the chaos soak's nemesis control
        surface, so composed faults can start and stop on a timeline
        without restarting the replica."""
        if live_faults is None:
            return {"ok": False,
                    "error": "no fault plan on this handler (neither a "
                             "continuous engine nor a prefix store)"}
        if req.get("clear"):
            return {"ok": True, "cleared": live_faults.clear(),
                    "armed": live_faults.armed()}
        spec = req.get("spec")
        if not spec:
            return {"ok": False,
                    "error": "want {\"spec\": \"site:kind@...\"} or "
                             "{\"clear\": true}"}
        try:
            added = live_faults.arm(str(spec))
        except ValueError as e:
            return {"ok": False, "error": str(e)}
        return {"ok": True, "added": added,
                "armed": live_faults.armed()}

    # whether speculative decode was ENABLED at boot (post any sp-mesh
    # stand-down): the knobs endpoint only RESIZES live speculation —
    # turning it on where the boot config (or a stand-down) left it off
    # would recreate the exact hazard the stand-down existed to avoid
    spec_boot_on = continuous is not None and continuous.spec_k >= 2

    def knobs_admin(req: dict) -> dict:
        """POST /v1/debug/knobs (host-only): live-retune the continuous
        engine's per-dispatch knobs. The elastic fleet controller's
        actuator for pipeline_depth (from overlap_ratio/fetch stall)
        and spec_k (from the live acceptance EWMA). Values are clamped
        and pow-2-bucketed here so a controller bug can never push the
        engine outside its compiled program shapes."""
        if continuous is None:
            return {"ok": False,
                    "error": "no continuous engine on this handler "
                             "(pipeline_depth/spec_k are engine knobs)"}
        known = {"pipeline_depth", "spec_k", "draft_mode",
                 "max_logical_ctx", "prefill_mode"}
        unknown = sorted(set(req) - known)
        if unknown or not (set(req) & known):
            return {"ok": False,
                    "error": f"want a subset of {sorted(known)}, got "
                             f"{sorted(req) or 'nothing'}"}
        if "pipeline_depth" in req:
            try:
                d = int(req["pipeline_depth"])
            except (TypeError, ValueError):
                return {"ok": False, "error": "pipeline_depth wants an int"}
            if not 1 <= d <= 8:
                return {"ok": False,
                        "error": f"pipeline_depth {d} out of range [1, 8]"}
            continuous.pipeline_depth = d
            continuous.pipeline_stats.depth = d
        if "spec_k" in req:
            try:
                k = int(req["spec_k"])
            except (TypeError, ValueError):
                return {"ok": False, "error": "spec_k wants an int"}
            if k != 0 and not spec_boot_on:
                return {"ok": False,
                        "error": "spec_k was off at boot (config, or an "
                                 "sp-mesh stand-down): live retune only "
                                 "resizes speculation, never enables it"}
            if k != 0:
                from lambdipy_tpu.models.llama import _next_bucket
                k = min(8, max(2, _next_bucket(k, 2)))
            continuous.spec_k = k
        if "draft_mode" in req:
            dm = str(req["draft_mode"] or "").lower()
            if dm == "auto":
                dm = "model"
            if dm not in ("model", "lookup", "aux", "off"):
                return {"ok": False,
                        "error": "draft_mode wants one of "
                                 "model|lookup|aux|off"}
            if dm in ("model", "aux") and not spec_boot_on:
                # same enablement rule as spec_k: retune only steers a
                # tier that booted on — it never turns speculation on
                # where boot config (or a stand-down) left it off
                return {"ok": False,
                        "error": "spec was off at boot: draft_mode "
                                 "retune steers a live draft tier, "
                                 "never enables one"}
            if dm == "aux" and continuous.draft_provider is None:
                return {"ok": False,
                        "error": "draft_mode=aux needs a draft_provider "
                                 "wired at boot"}
            # applies to rows admitted from here on; in-flight rows
            # keep their adapted per-row provider (the fallback chain
            # still demotes them individually)
            continuous.draft_mode = dm
        if "prefill_mode" in req:
            pm = str(req["prefill_mode"] or "").lower()
            if pm not in ("chunked", "sp"):
                return {"ok": False,
                        "error": "prefill_mode wants chunked|sp"}
            # unlike spec_k this is always retunable: "sp" without a
            # usable mesh stands down counted inside set_prefill_mode,
            # so a controller can never push prefill off a cliff
            continuous.set_prefill_mode(pm)
            if prefix_store is not None:
                prefix_store.prefill_mode = continuous.prefill_mode
            if continuous._longctx is not None:
                continuous._longctx.prefill_mode = continuous.prefill_mode
        if "max_logical_ctx" in req:
            try:
                m = int(req["max_logical_ctx"])
            except (TypeError, ValueError):
                return {"ok": False,
                        "error": "max_logical_ctx wants an int"}
            if m != 0 and continuous.pool is None:
                return {"ok": False,
                        "error": "max_logical_ctx needs paged KV "
                                 "(--kv-paged) at boot"}
            m = max(0, m)
            continuous.max_logical_ctx = m
            if continuous._longctx is not None and m:
                # a live runner re-reads its admission cap; 0 just
                # stops routing (the runner idles, already-admitted
                # runs finish)
                continuous._longctx.max_logical_ctx = m
        return {"ok": True,
                "pipeline_depth": continuous.pipeline_depth,
                "spec_k": continuous.spec_k,
                "draft_mode": continuous.draft_mode,
                "max_logical_ctx": continuous.max_logical_ctx,
                "prefill_mode": continuous.prefill_mode}

    # background bucket pre-warm: the boot warmup compiles only the
    # smallest prompt bucket; a first request in a bigger bucket pays a
    # multi-second compile at request time (measured ~14 s for a
    # 256-token bucket through the remote-compile transport). An
    # operator-listed `warm_buckets = "64,256"` compiles those buckets on
    # a daemon thread — started AFTER the first invoke (the boot warmup)
    # completes, never at init: a background compile racing the
    # foreground warmup serialized the cold start to 73 s measured.
    # Progress rides /metrics (handler.warm_buckets).
    import threading

    # "in_flight" is the readiness signal /healthz exposes: True from the
    # moment the warm thread is committed until it finishes, so a fleet
    # router can hold traffic off a still-compiling replica
    warm_state = {"requested": [], "done": [], "errors": [],
                  "in_flight": False}
    raw_buckets = extra.get("warm_buckets")
    if server is not None and raw_buckets:
        warm_state["requested"] = sorted(
            {int(tok) for tok in str(raw_buckets).split(",") if tok.strip()})
    _warm_lock = threading.Lock()
    _warm_started = False

    # the continuous engine's ragged group-prefill programs are another
    # first-burst compile cliff (measured: ~30 s of remote compiles when
    # 8 joiners arrive at once) — warm them on the same daemon
    warm_group = (continuous is not None
                  and str(extra.get("warm_group_prefill", "1")) != "0")

    def _maybe_start_bucket_warm():
        nonlocal _warm_started
        if not warm_state["requested"] and not warm_group:
            return
        with _warm_lock:  # atomic test-and-set: exactly one warm thread
            if _warm_started:
                return
            _warm_started = True
            # flipped before the thread exists: no window where warm is
            # committed but a /healthz probe still reads ready
            warm_state["in_flight"] = True

        def _warm_buckets():
            # warm traffic time-shares the one device with foreground
            # requests right after boot: early requests can see inflated
            # latency until the listed buckets finish compiling — the
            # operator opted into that trade by listing warm_buckets.
            for size in warm_state["requested"]:
                try:
                    server.generate([list(range(1, size + 1))],
                                    max_new_tokens=default_new)
                    with _warm_lock:
                        warm_state["done"].append(size)
                except Exception as e:  # background QoS, never fatal —
                    # and one bad bucket must not abandon the rest
                    with _warm_lock:
                        warm_state["errors"].append(f"bucket {size}: {e}")
            if warm_group:
                try:
                    n = continuous.warm_group_prefill()
                    with _warm_lock:
                        warm_state["done"].append(f"group_prefill:{n}")
                except Exception as e:
                    with _warm_lock:
                        warm_state["errors"].append(f"group_prefill: {e}")
            # the programs this thread just compiled should boot from
            # the AOT tier next time too
            try:
                server.aot_save_all()
            except Exception:  # noqa: BLE001 — AOT is best-effort
                pass
            with _warm_lock:
                warm_state["in_flight"] = False

        threading.Thread(target=_warm_buckets, daemon=True,
                         name="bucket-warm").start()

    tokenizer, tok_err = None, None
    tok_path = (spec.get("extra") or {}).get("tokenizer_path")
    if tok_path:
        # text-in/text-out: an HF tokenizer shipped INSIDE the bundle
        # (package.py copies it and rewrites the path bundle-relative);
        # absence degrades to the token-ids API, not an error
        from pathlib import Path as _Path

        resolved = _Path(tok_path)
        if not resolved.is_absolute():
            resolved = _Path(ctx.bundle_dir) / resolved
        try:
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(
                str(resolved), local_files_only=True)
        except Exception as e:  # noqa: BLE001 - degrade, recorded in meta
            tok_err = str(e)

    def _route_prefix(prompt, prefix, sess=None):
        """Transparent radix reuse: split a single-row prompt into
        (suffix prompt, cached-prefix tokens) when the prefix store can
        match or extend a block-aligned prefix. Requests carrying an
        EXPLICIT ``prefix`` keep the client's split; multi-row and
        sub-block prompts pass through. Fail-open by construction —
        ``route`` returns 0 on any store failure.

        ``sess`` = (session_id, ttl_s | None): after routing, the
        conversation's whole-block head is PINNED in the store under
        the session's lease, so turn-2+ requests keep hitting even
        under LRU pressure. A pin-budget refusal raises
        :class:`SessionPinsExceeded` (the server maps it to the priced
        ``session_pins`` 503); any routing stand-down just renews the
        lease without pinning — sessions degrade with the cache, never
        ahead of it."""
        if prefix_store is None or prefix is not None or len(prompt) != 1:
            return prompt, prefix
        standdown = False
        if continuous is not None and continuous.degrade_level >= 3:
            # degradation ladder level 3: a repeatedly-failing engine
            # bypasses the prefix cache — full-prompt prefill through
            # the plainest path until a clean interval restores it.
            # Session pins SURVIVE the bypass untouched (only the lease
            # renews): when the ladder restores, the next turn hits the
            # still-pinned head.
            standdown = True
        if continuous is not None and \
                continuous.cache_len != server.model.cfg.max_len:
            # a capped engine can't pack full-window prefix carries
            # (continuous._admit falls back solo): auto-routing would
            # silently trade away continuous batching for KV reuse —
            # keep the engine's pre-cache behavior and skip routing
            standdown = True
        if batcher is not None and continuous is None:
            # MicroBatcher mode: prefix requests bypass the window
            # batcher entirely (it has no prefix path), so routing would
            # serialize exactly the concurrent traffic the batcher
            # fuses — same silent-trade regression, same stand-down
            standdown = True
        if standdown:
            if sess is not None and sess[0]:
                prefix_store.touch_session(str(sess[0]))
            return prompt, prefix
        row = [int(t) for t in np.asarray(prompt[0]).reshape(-1)]
        m = prefix_store.route(row)
        if sess is not None and sess[0]:
            # pin AFTER route: the head's blocks exist now (the
            # request's own prefill inserted them). SessionPinsExceeded
            # propagates — the HTTP layer sheds the session, priced.
            prefix_store.pin_session(str(sess[0]), row, ttl_s=sess[1])
        if m <= 0:
            return prompt, prefix
        return ([np.asarray(row[m:], np.int32)],
                np.asarray(row[:m], np.int32))

    def run(prompt, max_new, sample_kwargs, want_lp=False):
        # prompt stays a host numpy array until the chosen path needs it:
        # the server/batcher convert internally, only the legacy
        # adapter.generate path pays a device transfer here. logprob
        # requests ride the batchers like any other (the fused program
        # computes logprobs anyway; want_lp only adds a fetch).
        if batcher is not None and len(prompt) == 1:
            return batcher.generate(prompt[0], max_new_tokens=max_new,
                                    return_logprobs=want_lp, **sample_kwargs)
        if server is not None:
            return server.generate(prompt, max_new_tokens=max_new,
                                   return_logprobs=want_lp, **sample_kwargs)
        device_prompt = jnp.asarray(prompt)
        if mesh is not None:
            with mesh:
                return adapter.generate(params, device_prompt,
                                        max_new_tokens=max_new, **sample_kwargs)
        return adapter.generate(params, device_prompt, max_new_tokens=max_new,
                                **sample_kwargs)

    def _parse(req: dict):
        """Request -> (prompt, max_new, sample_kwargs, from_text, prefix,
        want_logprobs), or an error dict (the shared front half of
        invoke and invoke_stream)."""
        from_text = False
        if req.get("warmup") or req.get("random"):
            if req.get("warmup") and server is not None and batcher is not None:
                from lambdipy_tpu.models.llama import _next_bucket
                from lambdipy_tpu.runtime.continuous import ContinuousBatcher

                if isinstance(batcher, ContinuousBatcher):
                    # one engine pass compiles the row prefill, the pack
                    # program, and the B-slot segment program
                    batcher.generate([1, 2, 3, 4],
                                     max_new_tokens=default_new)
                else:
                    # pre-compile every batch-size bucket the micro-batcher
                    # can produce — including the bucket max_batch rounds UP
                    # to — so the first concurrent burst hits warm programs,
                    # not an inline XLA compile
                    bb, top = 2, _next_bucket(batcher.max_batch, 1)
                    while bb <= top:
                        server.generate([[1, 2, 3, 4]] * bb,
                                        max_new_tokens=default_new)
                        bb *= 2
            if req.get("warmup") and server is not None:
                # pre-compile the streaming (prefill, segment) pair for
                # the default segment size too: on remote-compile
                # transports a first streamed request otherwise pays the
                # whole compile at time-to-first-token
                for _ in server.generate_stream([1, 2, 3, 4],
                                                max_new_tokens=default_new):
                    pass
            prompt = np.asarray([[1, 2, 3, 4]], np.int32)
        elif req.get("text") is not None:
            if tokenizer is None:
                return {"ok": False,
                        "error": "bundle has no tokenizer; send 'tokens'"}
            ids = tokenizer(req["text"])["input_ids"]
            if not ids:
                return {"ok": False,
                        "error": "prompt tokenized to zero tokens"}
            prompt = np.asarray([ids], np.int32)
            from_text = True
        else:
            raw = req["tokens"]
            if isinstance(raw, (list, tuple)) and raw and \
                    isinstance(raw[0], (list, tuple, np.ndarray)):
                # list-of-rows: may be RAGGED (different prompt lengths);
                # np.asarray would crash on inhomogeneous shape, and the
                # compile-once server decodes ragged batches natively
                rows = [np.asarray(r, dtype=np.int32).reshape(-1)
                        for r in raw]
                if any(r.size == 0 for r in rows):
                    return {"ok": False, "error": "empty prompt row"}
                if len({len(r) for r in rows}) == 1:
                    prompt = np.stack(rows)
                elif server is not None:
                    prompt = rows
                else:
                    return {"ok": False, "error":
                            "ragged prompt rows need the compile-once "
                            "server (model exposes no make_server)"}
            else:
                arr = np.asarray(raw, dtype=np.int32)
                if arr.size == 0:
                    return {"ok": False, "error": "empty prompt"}
                prompt = arr[None, :] if arr.ndim == 1 else arr
        # tolerate JSON null (= "use the default"); explicit 0 is honored
        raw_new = req.get("max_new_tokens")
        max_new = default_new if raw_new is None else int(raw_new)
        # every knob tolerates JSON null (= "use the default")
        sample_kwargs = {
            "temperature": float(req.get("temperature") or 0.0),
            "top_k": int(req["top_k"]) if req.get("top_k") is not None else None,
            "top_p": float(req["top_p"]) if req.get("top_p") is not None else None,
            "seed": int(req.get("seed") or 0),
            "eos_id": int(req["eos_id"]) if req.get("eos_id") is not None else None,
        }
        if sample_kwargs["eos_id"] is None and from_text and \
                tokenizer.eos_token_id is not None:
            sample_kwargs["eos_id"] = int(tokenizer.eos_token_id)
        prefix = req.get("prefix")
        if prefix is not None:
            prefix = np.asarray(prefix, np.int32).reshape(-1)
            if prefix.size == 0:
                return {"ok": False, "error": "empty prefix"}
            if server is None:
                return {"ok": False, "error":
                        "prefix caching needs the compile-once server"}
            if len(prompt) != 1:
                return {"ok": False,
                        "error": "prefix caching is single-row"}
        spec_k = req.get("speculative")
        if spec_k is not None:
            try:
                spec_k = int(spec_k)
            except (TypeError, ValueError):
                return {"ok": False,
                        "error": "speculative must be an integer draft "
                                 "length"}
            if server is None:
                return {"ok": False, "error":
                        "speculative decoding needs the compile-once "
                        "server"}
            if len(prompt) != 1:
                return {"ok": False, "error":
                        "speculative decoding is single-row"}
        # multi-turn session surface: `session_id` (string/number) pins
        # the conversation's prefix KV under a lease; `session_ttl_s`
        # optionally tightens this session's idle lease (clamped)
        sess = None
        sid = req.get("session_id")
        if sid is not None and str(sid):
            try:
                ttl = (float(req["session_ttl_s"])
                       if req.get("session_ttl_s") is not None else None)
            except (TypeError, ValueError):
                return {"ok": False,
                        "error": "session_ttl_s must be a number"}
            sess = (str(sid), ttl)
        return (prompt, max_new, sample_kwargs, from_text, prefix,
                bool(req.get("logprobs")), spec_k, sess)

    def invoke(req: dict) -> dict:
        parsed = _parse(req)
        if isinstance(parsed, dict):
            return parsed
        try:
            return _invoke_parsed(parsed)
        finally:
            if req.get("warmup") and server is not None:
                # the warmup invoke itself compiled the fused decode
                # program — snapshot everything compiled so far into the
                # bundle's AOT exec tier so the NEXT boot loads
                # executables instead of recompiling (no-op for programs
                # that were themselves AOT-loaded)
                try:
                    server.aot_save_all()
                except Exception:  # noqa: BLE001 — AOT is best-effort
                    pass
            # first completed invoke (the boot warmup) releases the
            # background bucket warm
            _maybe_start_bucket_warm()

    def _invoke_parsed(parsed) -> dict:
        (prompt, max_new, sample_kwargs, from_text, prefix, want_lp,
         spec_k, sess) = parsed
        prompt, prefix = _route_prefix(prompt, prefix, sess)
        lps = None
        if want_lp and server is None:
            return {"ok": False,
                    "error": "logprobs need the compile-once server"}
        spec_stats = None
        if spec_k is not None:
            # speculative decoding: prompt-lookup drafts verified in
            # chunks — plain greedy output at temperature 0, exact
            # rejection-sampled output (seed-deterministic) above it —
            # fewer weight reads either way (models/llama.py
            # generate_speculative). Stats come back with the call:
            # instance state would race under the threaded server and
            # go stale on the fallback path.
            out_, spec_stats = server.generate_speculative(
                prompt, max_new_tokens=max_new, k=spec_k, prefix=prefix,
                return_logprobs=want_lp, return_stats=True,
                **sample_kwargs)
            toks, lps = out_ if want_lp else (out_, None)
        elif prefix is not None:
            # shared-prefix KV reuse: only the suffix prefills per
            # request — and under continuous batching the prefix row
            # joins the shared engine batch (VERDICT r5 #3c; the
            # batcher falls back solo when its cache can't hold a
            # full-window prefix carry)
            if continuous is not None and len(prompt) == 1:
                out_ = continuous.generate(
                    prompt[0], max_new_tokens=max_new, prefix=prefix,
                    return_logprobs=want_lp, **sample_kwargs)
            else:
                out_ = server.generate(prompt, max_new_tokens=max_new,
                                       prefix=prefix,
                                       return_logprobs=want_lp,
                                       **sample_kwargs)
            toks, lps = out_ if want_lp else (out_, None)
        else:
            out_ = run(prompt, max_new, sample_kwargs, want_lp)
            toks, lps = out_ if want_lp else (out_, None)
            toks = np.asarray(jax.device_get(toks))
        toks = np.asarray(toks)
        out = {"ok": True, "tokens": toks.tolist(), "n_new": int(toks.shape[-1]),
               # effective request metadata for API shims (/v1/completions):
               # the real prompt token count and the eos actually in force
               # (a text prompt inherits the tokenizer's)
               "n_prompt": int(sum(len(r) for r in prompt)
                               + (len(prefix) if prefix is not None else 0))}
        if lps is not None:
            out["logprobs"] = [[round(float(x), 5) for x in row]
                               for row in np.asarray(lps)]
        if sample_kwargs["eos_id"] is not None:
            out["eos_id"] = sample_kwargs["eos_id"]
        if prefix is not None:
            out["prefix_cached"] = True
        if spec_stats is not None:
            out["speculative"] = spec_stats
        if from_text:
            row = toks[0].tolist()
            eos = sample_kwargs["eos_id"]
            if eos is not None and eos in row:
                row = row[:row.index(eos)]
            out["completion"] = tokenizer.decode(row)
        return out

    def invoke_stream(req: dict):
        """Streaming invoke: yields chunk dicts as the decode emits them
        (LlamaServer.generate_stream), ending with a summary record.
        Concatenated chunk tokens equal the non-streamed response."""
        parsed = _parse(req)
        if isinstance(parsed, dict):
            yield parsed
            return
        (prompt, max_new, sample_kwargs, from_text, prefix, want_lp,
         spec_k, sess) = parsed
        prompt, prefix = _route_prefix(prompt, prefix, sess)
        # clamp the client's segment size to a pow-2 in [4, 64]: it is
        # part of the compiled-program key, and an arbitrary per-request
        # value would grow the program cache (and pay a compile) without
        # bound on a public endpoint
        from lambdipy_tpu.models.llama import _next_bucket

        segment = min(64, _next_bucket(max(4, int(req.get("segment") or 16)), 4))
        spec_stats = None
        if spec_k is not None:
            # speculative + stream (VERDICT r5 weak #2): each verify
            # step's accepted chunk is a stream segment — TTFT is one
            # prefill + one verify step, where speculation pays most
            spec_stats = {}
            chunks_iter = server.generate_speculative_stream(
                prompt[0], max_new_tokens=max_new, k=spec_k,
                prefix=prefix, return_logprobs=want_lp,
                stats_out=spec_stats, **sample_kwargs)
        elif continuous is not None and len(prompt) == 1:
            # under continuous batching a streamed single-row request
            # joins the shared engine batch and receives its slice per
            # engine segment (VERDICT r5 #3b)
            chunks_iter = continuous.generate_stream(
                prompt[0], max_new_tokens=max_new, segment=segment,
                prefix=prefix, return_logprobs=want_lp, **sample_kwargs)
        else:
            chunks_iter = server.generate_stream(
                prompt, max_new_tokens=max_new, segment=segment,
                prefix=prefix, return_logprobs=want_lp, **sample_kwargs)
        all_rows = None
        text_emitted = ""
        for chunk in chunks_iter:
            chunk, lp_chunk = chunk if want_lp else (chunk, None)
            all_rows = (chunk if all_rows is None
                        else np.concatenate([all_rows, chunk], axis=1))
            rec = {"ok": True, "tokens": chunk.tolist()}
            if lp_chunk is not None:
                rec["logprobs"] = [[round(float(x), 5) for x in row]
                                   for row in lp_chunk]
            if from_text:
                # incremental text per segment so OpenAI-style clients
                # render as the stream arrives (each chunk carries the
                # DELTA since the previous one). Decode the whole row each
                # time — subword merges can only be resolved with the full
                # context — and hold back trailing replacement chars from
                # an incomplete UTF-8 sequence until the next segment
                # completes it. If a later token retroactively changes
                # ALREADY-SENT text (a non-prefix-stable tokenizer), emit
                # nothing and let the summary's tail field close the gap
                # with at most the diverged span duplicated — never the
                # whole completion.
                row = all_rows[0].tolist()
                eos = sample_kwargs["eos_id"]
                if eos is not None and eos in row:
                    row = row[:row.index(eos)]
                full = tokenizer.decode(row).rstrip("�")
                if full.startswith(text_emitted):
                    rec["text"] = full[len(text_emitted):]
                    text_emitted = full
                else:
                    rec["text"] = ""
            yield rec
        n_new = 0 if all_rows is None else int(all_rows.shape[1])
        out = {"ok": True, "done": True, "n_new": n_new,
               "n_prompt": int(sum(len(r) for r in prompt)
                               + (len(prefix) if prefix is not None else 0))}
        if spec_stats is not None:
            out["speculative"] = spec_stats
        if sample_kwargs["eos_id"] is not None:
            out["eos_id"] = sample_kwargs["eos_id"]
        if prefix is not None:
            # streamed from the cached prefix KV: TTFT and KV reuse
            # together (VERDICT r3 missing #4)
            out["prefix_cached"] = True
        if from_text and all_rows is not None:
            import os as _os

            row = all_rows[0].tolist()
            eos = sample_kwargs["eos_id"]
            if eos is not None and eos in row:
                row = row[:row.index(eos)]
            completion = tokenizer.decode(row)
            out["completion"] = completion
            # `text`: the tail a delta-concatenating client still needs.
            # Normally completion minus what was streamed; if decode
            # diverged from already-sent text, fall back to the common
            # prefix so at most the diverged span repeats — never the
            # whole completion (the handler owns this because only it
            # knows what was actually sent).
            if completion.startswith(text_emitted):
                out["text"] = completion[len(text_emitted):]
            else:
                common = _os.path.commonprefix([completion, text_emitted])
                out["text"] = completion[len(common):]
        yield out
        # a streaming-only workload must release the bucket warm too
        _maybe_start_bucket_warm()

    def stats() -> dict:
        if server is None:
            return {}
        out = {"decode_buckets": [list(b) for b in server.buckets],
               "compile_count": server.compile_count,
               "program_evictions": server.program_evictions,
               "aot_hits": getattr(server, "aot_hits", 0)}
        if preload_state:
            # programs deserialized concurrently with the weight upload
            # (cold-start overlap): count + seconds the preload took
            out["aot_preload"] = {
                "programs": len(preload_state.get("names", ())),
                "seconds": preload_state.get("seconds")}
        if batcher is not None:
            out["batching"] = batcher.stats()
        if getattr(server, "spec_metrics", None) is not None:
            # the solo `"speculative": k` path's cumulative acceptance
            # counters (the engine's batching.spec block shares this
            # same object when spec_k is on — one source of truth)
            out["spec"] = server.spec_metrics.report()
        if prefix_store is not None:
            # prefix_cache_{hits,misses,hit_tokens,evictions,bytes} +
            # hit_rate — the automatic radix reuse surface
            out["prefix_cache"] = prefix_store.stats()
        if kv_ship_stats is not None:
            # disaggregated-serving export/import counters; nested under
            # batching like the engine's other serve-path blocks (a
            # batcher-less server still reports them — the ship surface
            # rides the prefix store, not the engine)
            out.setdefault("batching", {})["disagg"] = \
                kv_ship_stats.report()
        if live_faults is not None:
            # faults.armed: the LIVE injection plan (sites, kinds,
            # remaining fire counts) — a soak run, or a stray
            # LAMBDIPY_FAULT left set in prod, is visible at the front
            # door instead of only in the process's environment
            out["faults"] = {"armed": live_faults.armed()}
        if warm_state["requested"] or warm_group:
            # gate on what was ASKED (listed buckets or the engine's
            # group-prefill warm), not on what finished: an in-flight
            # warm with empty done/errors lists must still be visible
            # in /metrics, or operators can't tell "running" from "not
            # started" (ADVICE r5). Snapshot under the lock: the warm
            # daemon appends while we serialize.
            with _warm_lock:
                out["warm_buckets"] = {
                    k: list(v) if isinstance(v, list) else v
                    for k, v in warm_state.items()}
        return out

    return HandlerState(
        invoke_fn=invoke, stats_fn=stats,
        invoke_stream_fn=invoke_stream if server is not None else None,
        prefix_probe=(prefix_store.match_len
                      if prefix_store is not None else None),
        # bare dict read — GIL-atomic, no lock: exactly what a
        # once-per-probe-interval health check may cost
        warming_fn=lambda: bool(warm_state["in_flight"]),
        engine_fault_fn=(continuous.fault_state
                         if continuous is not None else None),
        kv_export_fn=kv_export,
        kv_import_fn=kv_import,
        kv_export_stream_fn=kv_export_stream,
        kv_import_stream_fn=kv_import_stream,
        kv_probe_fn=kv_probe,
        session_end_fn=(prefix_store.end_session
                        if prefix_store is not None else None),
        debug_invariants_fn=debug_invariants,
        faults_admin_fn=faults_admin,
        knobs_admin_fn=knobs_admin,
        meta={
            "model": spec["model"], "quant": spec.get("quant"),
            "sharded": mesh is not None,
            "mesh": ({a: int(n) for a, n in dict(mesh.shape).items()}
                     if mesh is not None else None),
            "tokenizer": tokenizer is not None,
            "compile_once": server is not None,
            "streaming": server is not None,
            "prefix_cache": prefix_store is not None,
            "sessions": prefix_store is not None,
            "kv_ship": prefix_store is not None,
            "kv_paged": (continuous is not None
                         and continuous.pool is not None),
            **({"tokenizer_error": tok_err} if tok_err else {}),
        })


def torch_text_classify_handler(spec: dict, ctx) -> HandlerState:
    """Config 4 (torch path): torch-xla when available, CPU-torch smoke
    otherwise (SURVEY.md §9.7) — the degradation is reported per-invoke."""
    import numpy as np
    import torch

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.models.torch_bert import TorchBertClassifier, xla_device_or_cpu

    extra = spec.get("extra") or {}
    model = TorchBertClassifier(
        vocab_size=int(extra.get("vocab_size", 30522)),
        hidden=int(extra.get("hidden", 768)),
        layers=int(extra.get("layers", 12)),
        heads=int(extra.get("heads", 12)),
        max_len=int(extra.get("max_len", 128)),
        num_classes=int(extra.get("num_classes", 2)),
    )
    if ctx.params_dir is not None:
        model.load_state_dict(registry.load_params("bert-base-torch", ctx.params_dir))
    model.eval()
    device, device_kind = xla_device_or_cpu()
    model = model.to(device)
    max_len = model.max_len

    def invoke(req: dict) -> dict:
        if req.get("warmup") or req.get("random"):
            ids = torch.zeros(1, max_len, dtype=torch.long)
            mask = torch.ones(1, max_len, dtype=torch.long)
        else:
            raw = np.asarray(req["input_ids"], dtype=np.int64)
            if raw.ndim == 1:
                raw = raw[None, :]
            ids = torch.zeros(raw.shape[0], max_len, dtype=torch.long)
            mask = torch.zeros(raw.shape[0], max_len, dtype=torch.long)
            n = min(max_len, raw.shape[1])
            ids[:, :n] = torch.from_numpy(raw[:, :n])
            mask[:, :n] = 1
        with torch.no_grad():
            logits = model(ids.to(device), mask.to(device)).cpu().numpy()
        return {
            "ok": True,
            "labels": logits.argmax(-1).tolist(),
            "device": device_kind,  # "cpu" = the documented degraded path
        }

    meta = {"model": spec["model"], "device": device_kind}
    if device_kind == "cpu":
        # say it LOUDLY in /healthz meta, not just per-invoke: any number
        # measured against this deployment is the documented CPU-torch
        # degradation (torch-xla unavailable), not a TPU number
        meta["degraded"] = ("torch-xla unavailable: serving on CPU torch; "
                            "measured latencies are NOT TPU numbers "
                            "(SURVEY.md §9.7)")
    return HandlerState(invoke_fn=invoke, meta=meta)
