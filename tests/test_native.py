"""Native extension (xxh64) tests; skipped when not built."""

import pytest

native = pytest.importorskip("lambdipy_tpu._native")


def test_official_vectors():
    assert native.xxh64_bytes(b"") == 0xEF46DB3751D8E999
    assert native.xxh64_bytes(b"a") == 0xD24EC4F1A98C6E5B
    assert native.xxh64_bytes(b"abc") == 0x44BC2CF5AD770999
    # seeded vector
    assert native.xxh64_bytes(b"abc", 1) != native.xxh64_bytes(b"abc")


def test_file_vs_bytes_consistency(tmp_path):
    data = bytes(range(256)) * 1000 + b"tail"
    p = tmp_path / "blob"
    p.write_bytes(data)
    assert native.xxh64_file(str(p)) == native.xxh64_bytes(data)


def test_missing_file_raises(tmp_path):
    with pytest.raises(OSError):
        native.xxh64_file(str(tmp_path / "nope"))


def test_hash_file_integration(tmp_path):
    from lambdipy_tpu.utils.fsutil import hash_file

    p = tmp_path / "f"
    p.write_bytes(b"hello")
    h = hash_file(p)
    assert h.startswith("xxh64:")
    assert hash_file(p, algo="sha256").startswith("sha256:")
    # pinned algo reproduces the manifest hash exactly
    assert hash_file(p, algo="xxh64") == h
