"""Deploy layer: local-process stand-in for the TPU serverless runtime.

The reference's publish layer uploads artifacts to GitHub Releases and
leaves deployment to the user (SURVEY.md §2 publish row); the rebuild gains
a real deploy target (SURVEY.md §9.9). ``LocalRuntime`` spawns a bundle
server subprocess, waits for the readiness line, health-checks it, and
records the deployment — the same control-plane contract a Cloud-Run-on-TPU
target would implement (deploy/list/invoke/stop against a URL).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request
from dataclasses import dataclass
from pathlib import Path

from lambdipy_tpu.utils.fsutil import atomic_write_text
from lambdipy_tpu.utils.logs import get_logger, log_event

log = get_logger("lambdipy.deploy")

DEFAULT_STATE = Path.home() / ".lambdipy-tpu" / "deployments.json"


class DeployError(RuntimeError):
    pass


@dataclass
class Deployment:
    name: str
    bundle_dir: str
    pid: int
    port: int
    cold_start: dict

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"


def _http_json(url: str, payload: dict | None = None, timeout: float = 30.0) -> dict:
    if payload is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


class LocalRuntime:
    """Process-per-function local runtime with a persisted deployment table."""

    def __init__(self, state_path: Path | None = None):
        self.state_path = Path(state_path) if state_path else DEFAULT_STATE
        self.state_path.parent.mkdir(parents=True, exist_ok=True)

    def _load(self) -> dict:
        if self.state_path.exists():
            return json.loads(self.state_path.read_text())
        return {}

    def _save(self, state: dict) -> None:
        atomic_write_text(self.state_path, json.dumps(state, indent=1))

    def deploy(self, name: str, bundle_dir: Path, *, port: int = 0,
               ready_timeout: float = 300.0, env: dict | None = None,
               watchdog: bool = True) -> Deployment:
        """Spawn a server for the bundle and wait until it reports ready.

        ``watchdog`` (default) runs the server under the restart supervisor
        (SURVEY.md §6 failure-detection row): a crashed server is respawned
        on the same port with backoff, so the deployment URL self-heals.
        ``ready_timeout`` is generous because cold start includes PJRT init
        + first compile on a cold compile cache (BASELINE.md ~10 s floor).
        """
        bundle_dir = Path(bundle_dir).resolve()
        state = self._load()
        if name in state:
            raise DeployError(f"deployment {name!r} already exists; stop it first")
        # surface a failed build-time warm before paying for it: this boot
        # will trace+compile from scratch instead of hitting the cache
        try:
            from lambdipy_tpu.bundle.format import load_manifest

            warm_info = load_manifest(bundle_dir).get("warm")
            if isinstance(warm_info, dict) and not warm_info.get("ok"):
                log_event(log, "bundle warm step failed at build time; expect "
                               "a cold first compile", name=name,
                          warm_error=warm_info.get("error", ""))
        except Exception:
            pass  # advisory only — never blocks a deploy
        module = ("lambdipy_tpu.runtime.supervisor" if watchdog
                  else "lambdipy_tpu.runtime.server")
        cmd = [sys.executable, "-m", module, str(bundle_dir), str(port)]
        full_env = dict(os.environ)
        full_env.update(env or {})
        # the framework itself must be importable in the server process
        repo_root = str(Path(__file__).resolve().parents[2])
        full_env["PYTHONPATH"] = os.pathsep.join(
            [repo_root] + [p for p in full_env.get("PYTHONPATH", "").split(os.pathsep) if p])
        # server stderr goes to a per-deployment log so a boot failure is
        # diagnosable (`serve.log` beside the state file)
        log_path = self.state_path.parent / f"{name}.serve.log"
        log_path.parent.mkdir(parents=True, exist_ok=True)
        stderr_f = open(log_path, "w")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=stderr_f,
                                text=True, env=full_env, start_new_session=True)
        stderr_f.close()

        def _log_tail() -> str:
            try:
                return log_path.read_text(errors="replace")[-800:]
            except OSError:
                return ""

        deadline = time.monotonic() + ready_timeout
        ready_line = None
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                if proc.poll() is not None:
                    raise DeployError(
                        f"server for {name!r} exited rc={proc.returncode} before "
                        f"ready; log tail ({log_path}):\n{_log_tail()}")
                time.sleep(0.05)
                continue
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if parsed.get("ready"):
                ready_line = parsed
                break
        if ready_line is None:
            # group-kill: with the watchdog a supervisor fronts the server,
            # and killing only the supervisor would orphan the booting child
            _signal_group(proc.pid, signal.SIGKILL)
            raise DeployError(
                f"deployment {name!r} not ready within {ready_timeout}s; "
                f"log tail ({log_path}):\n{_log_tail()}")
        dep = Deployment(name=name, bundle_dir=str(bundle_dir), pid=proc.pid,
                         port=ready_line["port"],
                         cold_start=ready_line.get("cold_start", {}))
        state[name] = dep.__dict__
        self._save(state)
        log_event(log, "deployed", name=name, port=dep.port,
                  cold_start=dep.cold_start)
        return dep

    def list(self) -> list[Deployment]:
        return [Deployment(**v) for v in self._load().values()]

    def get(self, name: str) -> Deployment:
        state = self._load()
        if name not in state:
            raise DeployError(f"no deployment named {name!r}")
        return Deployment(**state[name])

    def invoke(self, name: str, request: dict, timeout: float = 60.0) -> dict:
        dep = self.get(name)
        return _http_json(f"{dep.url}/invoke", request, timeout=timeout)

    def invoke_stream(self, name: str, request: dict, timeout: float = 60.0):
        """Streaming invoke: sets ``stream: true`` and yields one dict per
        ndjson line as the server emits decode segments."""
        dep = self.get(name)
        req = urllib.request.Request(
            f"{dep.url}/invoke",
            data=json.dumps({**request, "stream": True}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            for line in resp:  # urllib de-chunks; one JSON object per line
                line = line.strip()
                if line:
                    yield json.loads(line)

    def health(self, name: str) -> dict:
        return _http_json(f"{self.get(name).url}/healthz")

    def metrics(self, name: str) -> dict:
        return _http_json(f"{self.get(name).url}/metrics")

    def restart(self, name: str, *, ready_timeout: float = 300.0,
                env: dict | None = None, watchdog: bool = True,
                grace: float = 5.0) -> Deployment:
        """Drain + stop, then redeploy the same bundle pinned to the SAME
        port, so anything holding the deployment's URL (the fleet
        router's replica table) stays valid across the restart. This is
        the rolling-restart primitive ``ReplicaPool.rolling_restart``
        drains the fleet with."""
        dep = self.get(name)
        self.stop(name, grace=grace)
        return self.deploy(name, Path(dep.bundle_dir), port=dep.port,
                           ready_timeout=ready_timeout, env=env,
                           watchdog=watchdog)

    def stop(self, name: str, *, grace: float = 5.0) -> None:
        """Drain via /shutdown, escalate to SIGTERM, then SIGKILL the whole
        process group (deploys start a new session, so this reaps the
        supervisor AND its server child — a bare SIGKILL on the supervisor
        would orphan the serving process)."""
        dep = self.get(name)
        try:
            _http_json(f"{dep.url}/shutdown", {})
        except Exception:
            pass
        if not _wait_dead(dep.pid, grace):
            _signal_group(dep.pid, signal.SIGTERM)
            if not _wait_dead(dep.pid, grace):
                _signal_group(dep.pid, signal.SIGKILL)
        state = self._load()
        state.pop(name, None)
        self._save(state)
        # the per-deployment serve.log dies with its deployment entry —
        # otherwise one orphan file per deployment name accumulates forever
        try:
            (self.state_path.parent / f"{name}.serve.log").unlink(missing_ok=True)
        except OSError:
            pass
        log_event(log, "stopped", name=name)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def _wait_dead(pid: int, grace: float) -> bool:
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if not _pid_alive(pid):
            return True
        time.sleep(0.1)
    return not _pid_alive(pid)


def _signal_group(pid: int, sig: int) -> None:
    """Signal the deployment's process group, falling back to the single
    pid if the group is gone."""
    try:
        os.killpg(pid, sig)
    except (ProcessLookupError, PermissionError):
        try:
            os.kill(pid, sig)
        except (ProcessLookupError, PermissionError):
            pass
