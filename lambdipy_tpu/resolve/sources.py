"""Source store: resolves sdist-recipe sources from local archives.

The driver image ships ``/source.tar.gz`` containing exemplar source
archives (certifi, numpy, the jax-stable-stack TPU image scripts — SURVEY.md
§0). The store extracts it lazily into a cache dir and resolves a recipe's
``build.source`` key (e.g. ``"certifi"``) to an unpacked source tree.
"""

from __future__ import annotations

import tarfile
import tempfile
from pathlib import Path

DEFAULT_ARCHIVE = Path("/source.tar.gz")
DEFAULT_CACHE = Path.home() / ".lambdipy-tpu" / "sources"


class SourceError(RuntimeError):
    pass


def _safe_extract(tar: tarfile.TarFile, dest: Path) -> None:
    # the stdlib "data" filter rejects path traversal, absolute names,
    # devices, and chmod/chown escalation (PEP 706)
    tar.extractall(dest, filter="data")


class SourceStore:
    def __init__(self, archive: Path | None = None, cache: Path | None = None):
        self.archive = Path(archive) if archive else DEFAULT_ARCHIVE
        self.cache = Path(cache) if cache else DEFAULT_CACHE

    def _ensure_extracted(self) -> Path:
        outer = self.cache / "outer"
        if not outer.is_dir():
            if not self.archive.exists():
                raise SourceError(f"source archive {self.archive} not found")
            self.cache.mkdir(parents=True, exist_ok=True)
            tmp = Path(tempfile.mkdtemp(dir=self.cache))
            with tarfile.open(self.archive) as tar:
                _safe_extract(tar, tmp)
            tmp.replace(outer)
        return outer

    def available(self) -> list[str]:
        try:
            outer = self._ensure_extracted()
        except SourceError:
            return []
        return sorted(p.name.split("@")[0].removeprefix("Python_").lower()
                      for p in outer.glob("*.tar.gz"))

    def resolve(self, source: str) -> Path:
        """Return the unpacked source tree for a named source (the directory
        containing pyproject.toml/setup.py)."""
        outer = self._ensure_extracted()
        matches = [p for p in outer.glob("*.tar.gz")
                   if p.name.lower().removeprefix("python_").startswith(source.lower())]
        if not matches:
            raise SourceError(
                f"source {source!r} not found in {self.archive}; available: {self.available()}")
        inner = matches[0]
        unpack_dir = self.cache / "trees" / inner.name.removesuffix(".tar.gz")
        if not unpack_dir.is_dir():
            unpack_dir.parent.mkdir(parents=True, exist_ok=True)
            with tarfile.open(inner) as tar:
                _safe_extract(tar, unpack_dir)
        # the project root is the dir holding pyproject.toml/setup.py — either
        # the unpack dir itself or its single top-level directory
        for candidate in [unpack_dir, *sorted(unpack_dir.iterdir())]:
            if candidate.is_dir() and any((candidate / f).exists() for f in ("pyproject.toml", "setup.py")):
                return candidate
        raise SourceError(f"no pyproject.toml/setup.py found under {unpack_dir}")
