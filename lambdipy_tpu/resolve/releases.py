"""Release store + fetcher: prebuilt-artifact distribution.

The reference's defining UX is that ordinary users never compile: a
maintainer publishes per-package prebuilt artifacts as GitHub Release
assets keyed ``<pkg>-<ver>-python<N>`` and `build` downloads a matching
asset instead of running the docker build (SURVEY.md §3.1 #4/#8/#9, §4
call stacks A and C). This module is that channel, TPU-rebuild shape:

- :func:`pack_bundle` / :func:`unpack_archive` — deterministic tar.gz of a
  bundle tree (fixed mtimes/owners, sorted entries) so the same bundle
  always produces the same asset hash, and hardened extraction (no
  absolute paths, no ``..`` escapes, no symlinks pointing outside) since
  release assets are remote content.
- :class:`ReleaseStore` — the release index. File-backed here (no network
  exists — SURVEY.md §8), but the layout and API mirror the GitHub
  Releases shape: releases keyed by tag, assets keyed by name with
  size/hash/recipe/version/python/device metadata, and a write token
  (``LAMBDIPY_RELEASE_TOKEN``, the ``GITHUB_TOKEN`` analogue) required
  for uploads when the store is protected. A GCS-backed store would
  implement the same surface.
- :class:`ReleaseFetcher` — the user-side download path: hash-verified
  fetch into a content-addressed local asset cache
  (``~/.lambdipy-tpu/cache/assets``), then unpack into the local
  :class:`~lambdipy_tpu.resolve.registry.ArtifactRegistry` so
  deploy/serve work exactly as for a locally built artifact.
"""

from __future__ import annotations

import gzip
import json
import os
import re
import shutil
import tarfile
import time
from dataclasses import asdict, dataclass
from pathlib import Path

from lambdipy_tpu.utils.fsutil import atomic_write_text, hash_file, walk_files

TOKEN_ENV = "LAMBDIPY_RELEASE_TOKEN"
STORE_ENV = "LAMBDIPY_RELEASE_STORE"
DEFAULT_CACHE = Path.home() / ".lambdipy-tpu" / "cache" / "assets"
_EPOCH = 315532800  # fixed mtime (1980-01-01) for deterministic archives


class ReleaseError(RuntimeError):
    pass


# -- archive format ----------------------------------------------------------


def _pack_entries(root: Path):
    """Deterministic walk for packing: regular files, symlinks (including
    symlinks to directories, which ``walk_files`` would drop — os.walk
    files them under dirnames), and empty directories, so a fetched bundle
    unpacks to exactly the tree that was published."""
    for dirpath, dirnames, filenames in os.walk(root, followlinks=False):
        dirnames.sort()
        if not dirnames and not filenames and Path(dirpath) != Path(root):
            yield Path(dirpath)
        for name in sorted(dirnames):
            p = Path(dirpath) / name
            if p.is_symlink():
                yield p
        for name in sorted(filenames):
            yield Path(dirpath) / name


def pack_bundle(bundle_dir: Path, archive_path: Path) -> Path:
    """Pack a bundle tree into a deterministic ``.tar.gz``.

    Determinism matters because the asset hash doubles as the integrity
    check and the cache key: entries are sorted, mtime/uid/gid/uname are
    normalized, and the gzip header carries no timestamp.
    """
    bundle_dir = Path(bundle_dir)
    archive_path = Path(archive_path)
    archive_path.parent.mkdir(parents=True, exist_ok=True)
    tmp = archive_path.with_suffix(archive_path.suffix + ".tmp")
    with open(tmp, "wb") as out:
        # filename="" keeps the output path out of the gzip header (FNAME),
        # which would otherwise break byte-determinism; streaming the tar
        # through keeps memory O(chunk) for multi-GB model bundles
        with gzip.GzipFile(filename="", fileobj=out, mode="wb", mtime=0) as gz:
            with tarfile.open(fileobj=gz, mode="w", format=tarfile.PAX_FORMAT) as tar:
                for path in _pack_entries(bundle_dir):
                    info = tar.gettarinfo(
                        path, arcname=path.relative_to(bundle_dir).as_posix())
                    info.mtime = _EPOCH
                    info.uid = info.gid = 0
                    info.uname = info.gname = ""
                    if info.issym() or info.isdir():
                        tar.addfile(info)
                    else:
                        with open(path, "rb") as f:
                            tar.addfile(info, f)
    os.replace(tmp, archive_path)
    return archive_path


def unpack_archive(archive_path: Path, dest: Path) -> Path:
    """Extract a release asset, refusing path-escape entries.

    Release assets are downloaded content: absolute member names, ``..``
    components, and symlinks targeting outside the extraction root are
    all rejected before anything is written.
    """
    dest = Path(dest)
    dest.mkdir(parents=True, exist_ok=True)
    root = dest.resolve()
    with tarfile.open(archive_path, mode="r:gz") as tar:
        for member in tar.getmembers():
            name = Path(member.name)
            if name.is_absolute() or ".." in name.parts:
                raise ReleaseError(f"unsafe archive member {member.name!r}")
            if member.issym() or member.islnk():
                target = (root / name).parent / member.linkname
                if not target.resolve().is_relative_to(root):
                    raise ReleaseError(
                        f"unsafe link {member.name!r} -> {member.linkname!r}")
            elif not (member.isfile() or member.isdir()):
                raise ReleaseError(f"unsupported member type in {member.name!r}")
        tar.extractall(dest, filter="data")
    return dest


# -- release store -----------------------------------------------------------


_SAFE_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


@dataclass(frozen=True)
class Asset:
    """One release asset: a packed bundle plus its index metadata.

    Name/id fields are validated on construction (which covers every index
    load): release.json is remote content, and these fields flow into
    filesystem paths on the fetch side — a tampered index must not be able
    to direct writes outside the cache/registry."""

    name: str  # "<recipe>-<version>-py<N>-<device>.tar.gz"
    tag: str  # release tag it belongs to
    size: int
    hash: str  # content hash of the archive ("xxh64:..." / "sha256:...")
    artifact_id: str
    recipe: str
    version: str
    python: str  # "3.12"
    device: str
    uploaded: float

    def __post_init__(self):
        for field_name in ("name", "tag", "artifact_id"):
            value = getattr(self, field_name)
            if not _SAFE_NAME_RE.match(value) or ".." in value:
                raise ReleaseError(
                    f"unsafe asset {field_name} {value!r} in release index")


class ReleaseStore:
    """File-backed release index with the GitHub-Releases access shape.

    Layout::

        <root>/store.json                      # {"protected": bool}
        <root>/releases/<tag>/release.json     # tag metadata + asset index
        <root>/releases/<tag>/assets/<name>    # the packed bundles

    ``protected`` stores require the ``LAMBDIPY_RELEASE_TOKEN`` env (or an
    explicit ``token=``) for uploads — the offline stand-in for GitHub's
    authenticated asset upload (SURVEY.md §3.1 #4: ``GITHUB_TOKEN``).
    Reads never need a token, matching public releases.
    """

    def __init__(self, root: Path, *, token: str | None = None):
        self.root = Path(root)
        self.releases_dir = self.root / "releases"
        self.token = token if token is not None else os.environ.get(TOKEN_ENV)

    # - store admin -

    @classmethod
    def create(cls, root: Path, *, protected: bool = False) -> "ReleaseStore":
        root = Path(root)
        (root / "releases").mkdir(parents=True, exist_ok=True)
        atomic_write_text(root / "store.json",
                          json.dumps({"protected": protected}))
        return cls(root)

    @property
    def protected(self) -> bool:
        cfg = self.root / "store.json"
        return bool(json.loads(cfg.read_text()).get("protected")) if cfg.exists() else False

    def _check_write(self) -> None:
        if self.protected and not self.token:
            raise ReleaseError(
                f"release store {self.root} is protected; set {TOKEN_ENV} to upload")

    # - releases -

    def _release_path(self, tag: str) -> Path:
        if not tag or "/" in tag or tag.startswith("."):
            raise ReleaseError(f"invalid release tag {tag!r}")
        return self.releases_dir / tag

    def _load_release(self, tag: str) -> dict:
        path = self._release_path(tag) / "release.json"
        if not path.exists():
            raise ReleaseError(f"no release tagged {tag!r} in {self.root}")
        return json.loads(path.read_text())

    def _save_release(self, tag: str, doc: dict) -> None:
        atomic_write_text(self._release_path(tag) / "release.json",
                          json.dumps(doc, indent=1, sort_keys=True))

    def create_release(self, tag: str, *, notes: str = "") -> dict:
        """Idempotent: returns the existing release if the tag exists."""
        path = self._release_path(tag)
        if (path / "release.json").exists():
            return self._load_release(tag)
        self._check_write()
        (path / "assets").mkdir(parents=True, exist_ok=True)
        doc = {"tag": tag, "notes": notes, "created": time.time(), "assets": {}}
        self._save_release(tag, doc)
        return doc

    def list_releases(self) -> list[str]:
        if not self.releases_dir.is_dir():
            return []
        return sorted(p.name for p in self.releases_dir.iterdir()
                      if (p / "release.json").exists())

    # - assets -

    def upload_asset(self, tag: str, archive_path: Path, *, artifact_id: str,
                     recipe: str, version: str, python: str, device: str) -> Asset:
        """Upload a packed bundle as a release asset (call stack C's
        'create/append release, upload asset' step)."""
        self._check_write()
        archive_path = Path(archive_path)
        doc = self.create_release(tag)
        name = f"{artifact_id}.tar.gz"
        dst = self._release_path(tag) / "assets" / name
        dst.parent.mkdir(parents=True, exist_ok=True)
        tmp = dst.with_suffix(".tmp")
        shutil.copyfile(archive_path, tmp)
        os.replace(tmp, dst)
        asset = Asset(
            name=name, tag=tag, size=dst.stat().st_size,
            # sha256 always: asset hashes must verify on machines without
            # the optional native xxh64 extension (the fetch side of the
            # "users never compile" channel)
            hash=hash_file(dst, algo="sha256"), artifact_id=artifact_id,
            recipe=recipe, version=version, python=python, device=device,
            uploaded=time.time(),
        )
        doc["assets"][name] = asdict(asset)
        self._save_release(tag, doc)
        return asset

    def list_assets(self, tag: str | None = None) -> list[Asset]:
        tags = [tag] if tag else self.list_releases()
        out: list[Asset] = []
        for t in tags:
            doc = self._load_release(t)
            out.extend(Asset(**a) for a in doc["assets"].values())
        return out

    def find_asset(self, *, recipe: str, python: str,
                   device: str | None = None,
                   version: str | None = None) -> Asset | None:
        """Newest asset matching recipe × python (× device/version), the
        release-index lookup of call stack A. ``device=None`` accepts any;
        a concrete device also accepts ``any``-device assets."""
        matches = [
            a for a in self.list_assets()
            if a.recipe == recipe and a.python == python
            and (version is None or a.version == version)
            and (device is None or a.device in (device, "any"))
        ]
        return max(matches, key=lambda a: a.uploaded) if matches else None

    def asset_path(self, asset: Asset) -> Path:
        path = self._release_path(asset.tag) / "assets" / asset.name
        if not path.exists():
            raise ReleaseError(f"asset {asset.name!r} missing from release {asset.tag!r}")
        return path


# -- user-side fetch path ----------------------------------------------------


class ReleaseFetcher:
    """Download + verify + cache release assets (call stack A's hit branch:
    'download artifact; unpack into build dir; cache')."""

    def __init__(self, store: ReleaseStore, cache_dir: Path | None = None):
        self.store = store
        self.cache_dir = Path(
            cache_dir or os.environ.get("LAMBDIPY_CACHE_DIR") or DEFAULT_CACHE)
        self.cache_dir.mkdir(parents=True, exist_ok=True)

    def _cache_path(self, asset: Asset) -> Path:
        # content-addressed: a re-published asset with new bytes gets a new
        # cache entry instead of silently shadowing the old one
        return self.cache_dir / f"{asset.hash.replace(':', '-')}-{asset.name}"

    def fetch(self, asset: Asset) -> Path:
        """Return a verified local archive for the asset (cache hit = no
        store access beyond metadata)."""
        cached = self._cache_path(asset)
        if cached.exists() and hash_file(cached, algo=asset.hash.split(":", 1)[0]) == asset.hash:
            return cached
        src = self.store.asset_path(asset)
        tmp = cached.with_suffix(".tmp")
        shutil.copyfile(src, tmp)
        got = hash_file(tmp, algo=asset.hash.split(":", 1)[0])
        if got != asset.hash:
            tmp.unlink()
            raise ReleaseError(
                f"asset {asset.name!r} failed verification: index says "
                f"{asset.hash}, downloaded {got}")
        os.replace(tmp, cached)
        return cached

    def fetch_into_registry(self, asset: Asset, registry) -> Path:
        """Fetch + unpack an asset into the local artifact registry; returns
        the bundle path. After this, deploy/serve behave exactly as if the
        artifact had been built locally."""
        import tempfile

        archive = self.fetch(asset)
        with tempfile.TemporaryDirectory(prefix="lambdipy-fetch-") as td:
            bundle = unpack_archive(archive, Path(td) / "bundle")
            manifest = None
            mpath = bundle / "manifest.json"
            if mpath.exists():
                manifest = json.loads(mpath.read_text())
            return registry.publish(
                asset.artifact_id, bundle, recipe=asset.recipe,
                version=asset.version, device=asset.device, manifest=manifest)


def default_store(path: str | os.PathLike | None = None) -> ReleaseStore | None:
    """Resolve the release store from an explicit path or the
    ``LAMBDIPY_RELEASE_STORE`` env var; None when neither is set."""
    root = path or os.environ.get(STORE_ENV)
    return ReleaseStore(Path(root)) if root else None
