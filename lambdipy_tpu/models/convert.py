"""HuggingFace weight import: transformers checkpoints -> framework params.

The migration path for users arriving with real weights: a local HF Llama
checkpoint (or in-memory ``LlamaForCausalLM``) converts into the exact
pytree models/llama.py expects, verified to logits parity in
tests/test_convert.py. Conversion happens on host numpy (no device memory
spike); quantization (llama.quantize_params) and sharding happen after, on
the target mesh.

Mapping notes (HF ``modeling_llama`` naming):
- torch ``nn.Linear`` stores ``weight`` as [out, in] -> transposed into
  our [in, out] kernels;
- HF rotary embeddings use the rotate-half convention, same as llama.rope
  (split halves, not interleaved pairs) — weights port without permutation;
- ``tie_word_embeddings``: the lm_head kernel falls back to the transposed
  embedding matrix.

Offline rule (SURVEY.md §8, no network): sources are local paths or
already-constructed models only; nothing here downloads.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from lambdipy_tpu.utils.logs import get_logger, log_event

log = get_logger("lambdipy.convert")


def _to_numpy(t) -> np.ndarray:
    """Torch/array -> numpy, preserving the checkpoint dtype: an 8B bf16
    checkpoint must not silently double into fp32 orbax params. The fp32
    hop is exact for bf16/f16 (strict supersets), so round-tripping back
    to the source dtype loses nothing."""
    if hasattr(t, "detach"):  # torch tensor
        orig = str(t.dtype).replace("torch.", "")
        arr = t.detach().to("cpu").float().numpy()
        if orig == "bfloat16":
            import ml_dtypes

            return arr.astype(ml_dtypes.bfloat16)
        if orig == "float16":
            return arr.astype(np.float16)
        return arr
    return np.asarray(t)


def _state_dict_of(source) -> tuple[dict, dict | None]:
    """(state_dict, hf_config_dict|None) from a model / path / mapping."""
    if hasattr(source, "state_dict") and hasattr(source, "config"):
        return dict(source.state_dict()), source.config.to_dict()
    if isinstance(source, (str, Path)):
        from transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(
            str(source), local_files_only=True)
        return dict(model.state_dict()), model.config.to_dict()
    return dict(source), None


def _rope_scaling_from_hf(hf_cfg: dict) -> tuple | None:
    """HF ``rope_scaling`` dict -> our hashable tuple form; raises for
    schemes the model does not implement (yarn, dynamic, longrope)."""
    rs = hf_cfg.get("rope_scaling")
    if not rs:
        return None
    kind = rs.get("rope_type", rs.get("type", "default"))
    if kind in (None, "default"):
        return None
    if kind == "linear":
        return ("linear", float(rs["factor"]))
    if kind == "llama3":
        return ("llama3", float(rs["factor"]),
                float(rs["low_freq_factor"]), float(rs["high_freq_factor"]),
                float(rs["original_max_position_embeddings"]))
    raise ValueError(
        f"unsupported HF config field: rope_scaling type {kind!r} "
        "(supported: default, linear, llama3)")


def _check_supported_hf_config(hf_cfg: dict) -> None:
    """Reject HF config fields that would silently change numerics if
    dropped (VERDICT r2 missing #6): wrong logits with no error is the
    worst failure mode on the advertised migration path."""
    if hf_cfg.get("attention_bias"):
        raise ValueError(
            "unsupported HF config field: attention_bias=True "
            "(q/k/v/o projection biases are not implemented)")
    if hf_cfg.get("mlp_bias"):
        raise ValueError(
            "unsupported HF config field: mlp_bias=True "
            "(gate/up/down projection biases are not implemented)")
    head_dim = hf_cfg.get("head_dim")
    derived = int(hf_cfg["hidden_size"]) // int(hf_cfg["num_attention_heads"])
    if head_dim is not None and int(head_dim) != derived:
        raise ValueError(
            f"unsupported HF config field: head_dim={head_dim} differs from "
            f"hidden_size/num_attention_heads={derived}")


def llama_config_from_hf(hf_cfg: dict, **overrides):
    """Map an HF LlamaConfig dict onto our LlamaConfig; raises a clear
    error for unsupported fields instead of silently dropping them."""
    from lambdipy_tpu.models.llama import LlamaConfig

    import jax.numpy as jnp

    _check_supported_hf_config(hf_cfg)
    cfg = LlamaConfig(
        vocab_size=int(hf_cfg["vocab_size"]),
        hidden=int(hf_cfg["hidden_size"]),
        layers=int(hf_cfg["num_hidden_layers"]),
        heads=int(hf_cfg["num_attention_heads"]),
        kv_heads=int(hf_cfg.get("num_key_value_heads",
                                hf_cfg["num_attention_heads"])),
        mlp=int(hf_cfg["intermediate_size"]),
        max_len=int(hf_cfg.get("max_position_embeddings", 8192)),
        rope_theta=float(hf_cfg.get("rope_theta", 10000.0)),
        rope_scaling=_rope_scaling_from_hf(hf_cfg),
        norm_eps=float(hf_cfg.get("rms_norm_eps", 1e-5)),
        dtype=jnp.bfloat16,
    )
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def import_hf_llama(source, *, config_overrides: dict | None = None):
    """Convert an HF Llama checkpoint into (LlamaConfig, params).

    ``source``: a ``transformers`` model instance, a local checkpoint path,
    or a raw ``state_dict`` mapping (then pass the architecture via
    ``config_overrides`` on a LlamaConfig-complete dict).
    """
    sd, hf_cfg = _state_dict_of(source)
    sd = {k: _to_numpy(v) for k, v in sd.items()}
    if hf_cfg is None:
        raise ValueError(
            "raw state_dict needs an HF config; pass a model or path instead")
    cfg = llama_config_from_hf(hf_cfg, **(config_overrides or {}))

    def lin(name):  # torch Linear [out, in] -> kernel [in, out]
        return {"kernel": np.ascontiguousarray(sd[f"{name}.weight"].T)}

    def norm(name):
        return {"scale": sd[f"{name}.weight"]}

    params: dict = {
        "embed": {"embedding": sd["model.embed_tokens.weight"]},
        "final_norm": norm("model.norm"),
    }
    for i in range(cfg.layers):
        hf = f"model.layers.{i}"
        params[f"layer_{i}"] = {
            "attn_norm": norm(f"{hf}.input_layernorm"),
            "q_proj": lin(f"{hf}.self_attn.q_proj"),
            "k_proj": lin(f"{hf}.self_attn.k_proj"),
            "v_proj": lin(f"{hf}.self_attn.v_proj"),
            "o_proj": lin(f"{hf}.self_attn.o_proj"),
            "mlp_norm": norm(f"{hf}.post_attention_layernorm"),
            "gate_proj": lin(f"{hf}.mlp.gate_proj"),
            "up_proj": lin(f"{hf}.mlp.up_proj"),
            "down_proj": lin(f"{hf}.mlp.down_proj"),
        }
    if "lm_head.weight" in sd:
        params["lm_head"] = {"kernel": np.ascontiguousarray(sd["lm_head.weight"].T)}
    else:  # tie_word_embeddings
        params["lm_head"] = {
            "kernel": np.ascontiguousarray(sd["model.embed_tokens.weight"].T)}
    n = sum(v.size for v in jax_tree_leaves(params))
    log_event(log, "hf llama imported", layers=cfg.layers, n_params=int(n))
    return cfg, {"params": params}


def jax_tree_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def bert_config_from_hf(hf_cfg: dict, num_classes: int, **overrides):
    from lambdipy_tpu.models.bert import BertConfig

    import jax.numpy as jnp

    cfg = BertConfig(
        vocab_size=int(hf_cfg["vocab_size"]),
        hidden=int(hf_cfg["hidden_size"]),
        layers=int(hf_cfg["num_hidden_layers"]),
        heads=int(hf_cfg["num_attention_heads"]),
        mlp=int(hf_cfg["intermediate_size"]),
        max_len=int(hf_cfg.get("max_position_embeddings", 512)),
        type_vocab=int(hf_cfg.get("type_vocab_size", 2)),
        num_classes=num_classes,
        dtype=jnp.bfloat16,
    )
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def import_hf_bert(source, *, config_overrides: dict | None = None):
    """Convert an HF ``BertForSequenceClassification`` checkpoint (or local
    path) into (BertConfig, params) for models/bert.py BertClassifier.

    Mapping notes: torch Linear [out, in] -> [in, out] kernels; the q/k/v
    projections reshape into DenseGeneral's [hidden, heads, head_dim], the
    output projection into [heads, head_dim, hidden]; LayerNorm
    weight/bias -> scale/bias. Parity verified in tests/test_convert.py.
    """
    if isinstance(source, (str, Path)):
        from transformers import AutoModelForSequenceClassification

        source = AutoModelForSequenceClassification.from_pretrained(
            str(source), local_files_only=True)
    sd = {k: _to_numpy(v) for k, v in source.state_dict().items()}
    hf_cfg = source.config.to_dict()
    num_classes = sd["classifier.weight"].shape[0]
    cfg = bert_config_from_hf(hf_cfg, num_classes, **(config_overrides or {}))
    h, heads, hd = cfg.hidden, cfg.heads, cfg.hidden // cfg.heads

    def lin(name):
        return {"kernel": np.ascontiguousarray(sd[f"{name}.weight"].T),
                "bias": sd[f"{name}.bias"]}

    def qkv(name):  # [h_out, h_in] -> kernel [h_in, heads, head_dim]
        return {"kernel": np.ascontiguousarray(
                    sd[f"{name}.weight"].T.reshape(h, heads, hd)),
                "bias": sd[f"{name}.bias"].reshape(heads, hd)}

    def ln(name):
        return {"scale": sd[f"{name}.weight"], "bias": sd[f"{name}.bias"]}

    enc: dict = {
        "tok_emb": {"embedding": sd["bert.embeddings.word_embeddings.weight"]},
        "pos_emb": {"embedding": sd["bert.embeddings.position_embeddings.weight"]},
        "type_emb": {"embedding": sd["bert.embeddings.token_type_embeddings.weight"]},
        "emb_ln": ln("bert.embeddings.LayerNorm"),
    }
    for i in range(cfg.layers):
        hf = f"bert.encoder.layer.{i}"
        enc[f"layer_{i}"] = {
            "attn": {
                "query": qkv(f"{hf}.attention.self.query"),
                "key": qkv(f"{hf}.attention.self.key"),
                "value": qkv(f"{hf}.attention.self.value"),
                # output projection: [h_out, h_in] -> [heads, head_dim, h]
                "out": {"kernel": np.ascontiguousarray(
                            sd[f"{hf}.attention.output.dense.weight"].T
                            .reshape(heads, hd, h)),
                        "bias": sd[f"{hf}.attention.output.dense.bias"]},
            },
            "ln_attn": ln(f"{hf}.attention.output.LayerNorm"),
            "mlp_in": lin(f"{hf}.intermediate.dense"),
            "mlp_out": lin(f"{hf}.output.dense"),
            "ln_mlp": ln(f"{hf}.output.LayerNorm"),
        }
    params = {
        "encoder": enc,
        "pooler": lin("bert.pooler.dense"),
        "classifier": lin("classifier"),
    }
    n = sum(v.size for v in jax_tree_leaves(params))
    log_event(log, "hf bert imported", layers=cfg.layers, n_params=int(n))
    return cfg, {"params": params}


def save_hf_params(hf_path: str | Path, params_dir: Path, *,
                   quant: str | None = None,
                   params_format: str = "both") -> dict:
    """Bundle-build hook: convert a local HF Llama checkpoint and persist
    it as the bundle's orbax params (bundle/package.py params="hf")."""
    from lambdipy_tpu.utils.platform import prefer_cpu_backend

    prefer_cpu_backend()  # host-side conversion; leave the TPU to the warmer
    import jax

    from lambdipy_tpu.models.llama import quantize_params

    cfg, params = import_hf_llama(hf_path)
    if quant == "int8":
        params = jax.device_get(quantize_params(params))
    params_dir = Path(params_dir)
    params_dir.mkdir(parents=True, exist_ok=True)
    from lambdipy_tpu.bundle.flatpack import save_checkpoint_files

    fmt = save_checkpoint_files(params_dir, params, params_format)
    n = sum(v.size for v in jax_tree_leaves(params))
    info = {"format": fmt, "n_params": int(n), "source": "hf",
            "hf_path": str(hf_path), "quant": quant,
            # the COMPLETE architecture: the serve side rebuilds the module
            # from exactly this dict, so every field that changes numerics
            # or limits (norm_eps! max_len!) must be here, not defaulted
            "config": {"vocab_size": cfg.vocab_size, "hidden": cfg.hidden,
                       "layers": cfg.layers, "heads": cfg.heads,
                       "kv_heads": cfg.kv_heads, "mlp": cfg.mlp,
                       "rope_theta": cfg.rope_theta,
                       "rope_scaling": (list(cfg.rope_scaling)
                                        if cfg.rope_scaling else None),
                       "norm_eps": cfg.norm_eps, "max_len": cfg.max_len}}
    return info
