"""Watchdog supervisor: crash detection + restart for a deployed bundle.

SURVEY.md §6 (failure detection / elastic recovery row): the rebuild's
serve loop gets a health endpoint, watchdog restart, and request draining.
The deploy controller spawns THIS process, which in turn runs the bundle
server (`lambdipy_tpu.runtime.server`) as a child:

- first readiness line is forwarded to stdout (the controller parses it),
  with the server's chosen port pinned so restarts keep the same URL;
- an abnormal child exit (non-zero rc / killed) triggers a restart with
  exponential backoff, up to ``LAMBDIPY_MAX_RESTARTS`` consecutive
  failures (the counter resets after a stable run);
- a clean child exit (rc 0 — drain via ``POST /shutdown`` or SIGTERM)
  ends the supervisor too;
- SIGTERM/SIGINT are forwarded to the child for a graceful drain.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

from lambdipy_tpu.utils.logs import get_logger, log_event

log = get_logger("lambdipy.supervisor")

# A run this long resets the consecutive-failure count; env-tunable so
# fleet fault-injection tests (and operators with fast-booting bundles)
# can shrink the window without patching the module.
STABLE_UPTIME_S = float(os.environ.get("LAMBDIPY_STABLE_UPTIME_S", "60"))
MAX_BACKOFF_S = float(os.environ.get("LAMBDIPY_MAX_BACKOFF_S", "10"))


def _spawn(bundle: str, port: int) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "lambdipy_tpu.runtime.server", bundle, str(port)],
        stdout=subprocess.PIPE, text=True)


def _read_ready(child: subprocess.Popen) -> dict | None:
    """Read child stdout until the readiness line (or EOF = boot failure),
    then keep draining the pipe in the background so the child can never
    block on a full stdout buffer."""
    ready = None
    assert child.stdout is not None
    for line in child.stdout:
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if parsed.get("ready"):
            ready = parsed
            break
    if ready is not None:
        threading.Thread(target=lambda: [None for _ in child.stdout],
                         daemon=True).start()
    return ready


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: supervisor <bundle_dir> [port]", file=sys.stderr)
        return 2
    bundle = str(Path(argv[0]))
    port = int(argv[1]) if len(argv) > 1 else 0
    max_restarts = int(os.environ.get("LAMBDIPY_MAX_RESTARTS", "5"))

    state = {"child": None, "stopping": False}

    def _forward_term(signum, frame):
        state["stopping"] = True
        child = state["child"]
        if child is not None and child.poll() is None:
            child.send_signal(signal.SIGTERM)

    signal.signal(signal.SIGTERM, _forward_term)
    signal.signal(signal.SIGINT, _forward_term)

    failures = 0
    announced = False
    while True:
        # a SIGTERM that landed between children (during backoff/respawn)
        # must stop the loop, not be swallowed
        if state["stopping"]:
            log_event(log, "supervisor exit", rc=0, clean=True)
            return 0
        started = time.monotonic()
        child = _spawn(bundle, port)
        state["child"] = child
        if state["stopping"] and child.poll() is None:
            child.send_signal(signal.SIGTERM)  # raced the spawn itself
        ready = _read_ready(child)
        if ready is not None:
            if port == 0:
                port = int(ready["port"])  # pin: restarts keep the URL stable
            if not announced:
                ready["supervisor_pid"] = os.getpid()
                print(json.dumps(ready), flush=True)
                announced = True
            else:
                log_event(log, "restarted", port=port, pid=child.pid,
                          consecutive_failures=failures)
        rc = child.wait()
        uptime = time.monotonic() - started
        if state["stopping"] or rc == 0:
            log_event(log, "supervisor exit", rc=rc, clean=True)
            return 0
        if uptime >= STABLE_UPTIME_S:
            failures = 0
        failures += 1
        if failures > max_restarts:
            log_event(log, "giving up", rc=rc, consecutive_failures=failures,
                      max_restarts=max_restarts)
            return 1
        delay = min(0.5 * (2 ** (failures - 1)), MAX_BACKOFF_S)
        log_event(log, "child died, restarting", rc=rc, uptime_s=round(uptime, 2),
                  backoff_s=delay, attempt=failures)
        time.sleep(delay)


if __name__ == "__main__":
    raise SystemExit(main())
