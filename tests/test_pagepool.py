"""Paged KV page allocator (runtime/pagepool.py): refcounts, free-list
reuse, exact accounting, and out-of-pages backpressure.

The allocator is the trust anchor of the paged engine — a silent
refcount bug corrupts KV shared between requests — so these tests lean
on invariants (every page free XOR live exactly once, bytes conserve)
under randomized alloc/share/release interleavings, not just happy
paths. The HTTP-facing contract is exercised too: exhaustion surfaces
as a PRICED shed (503 + Retry-After through runtime/server.py), never
an unhandled exception."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from lambdipy_tpu.runtime.pagepool import (
    NULL_PAGE,
    PagePool,
    PagesExhausted,
    page_width,
)


def mkpool(n_pages=17, page=16, page_bytes=1024, **kw):
    return PagePool(n_pages=n_pages, page=page, page_bytes=page_bytes,
                    **kw)


# -- page width normalization -------------------------------------------------


def test_page_width_pow2_dividing_window():
    assert page_width(256, 32) == 32
    assert page_width(256, 48) == 64     # pow-2 bucket of 48
    assert page_width(1072, 64) == 16    # largest pow-2 dividing 1072
    assert page_width(128, 1024) == 128  # clamped to the window
    assert page_width(8, 0) == 1


# -- basic alloc/share/release ------------------------------------------------


def test_alloc_release_roundtrip_and_reuse():
    pool = mkpool(n_pages=5)
    a = pool.alloc(2, tokens=20)
    assert len(a) == 2 and NULL_PAGE not in a
    assert pool.free_count() == 2
    pool.release(a)
    assert pool.free_count() == 4
    # LIFO reuse: the pages just released come back first
    b = pool.alloc(2)
    assert set(b) & set(a)
    pool.check_invariants()


def test_share_is_refcount_not_copy():
    pool = mkpool()
    pids = pool.alloc(3)
    pool.retain(pids)
    pool.release(pids)           # first owner gone
    assert pool.free_count() == pool.capacity_pages - 3  # still live
    assert all(pool.refcount(p) == 1 for p in pids)
    pool.release(pids)           # second owner gone -> free
    assert pool.free_count() == pool.capacity_pages
    pool.check_invariants()


def test_double_free_and_bad_retain_raise():
    pool = mkpool()
    (pid,) = pool.alloc(1)
    pool.release([pid])
    with pytest.raises(ValueError, match="double free"):
        pool.release([pid])
    with pytest.raises(ValueError, match="retain"):
        pool.retain([pid])


def test_null_page_is_inert():
    pool = mkpool()
    pool.retain([NULL_PAGE])
    pool.release([NULL_PAGE])    # never frees, never double-frees
    pool.release([NULL_PAGE])
    assert pool.refcount(NULL_PAGE) == 1
    pool.check_invariants()


def test_exhaustion_is_priced_backpressure():
    pool = mkpool(n_pages=4)
    pool.alloc(3)
    with pytest.raises(PagesExhausted) as exc:
        pool.alloc(2)
    assert exc.value.needed == 2 and exc.value.free == 0
    assert exc.value.retry_after_s > 0
    assert pool.stats()["sheds"] == 1
    # a failed alloc leaks nothing
    pool.check_invariants()


def test_alloc_zero_and_negative_are_noops():
    pool = mkpool()
    assert pool.alloc(0) == []
    assert pool.alloc(-3) == []
    assert pool.free_count() == pool.capacity_pages


# -- stats / accounting -------------------------------------------------------


def test_stats_exact_bytes_and_fragmentation():
    pool = mkpool(n_pages=9, page=16, page_bytes=100)
    pool.alloc(2, tokens=20)     # second page holds 4/16 tokens
    st = pool.stats()
    assert st["bytes_total"] == 8 * 100
    assert st["bytes_live"] == 200 and st["bytes_free"] == 600
    assert st["bytes_live"] + st["bytes_free"] == st["bytes_total"]
    # 32 allocated token slots, 20 used -> 12/32 wasted
    assert st["internal_fragmentation"] == pytest.approx(12 / 32)
    assert st["pages_shared"] == 0 and st["max_refcount"] == 1
    assert st["allocs"] == 1 and st["alloc_pages"] == 2


def test_stats_refcount_histogram_and_capacity_rows():
    pool = mkpool(n_pages=9, window_pages=4)
    a = pool.alloc(2)
    pool.retain(a)
    pool.alloc(1)
    st = pool.stats()
    assert st["refcount_histogram"] == {"2": 2, "1": 1}
    assert st["max_refcount"] == 2 and st["pages_shared"] == 2
    # 5 free pages / 4-page windows -> 1 more full-window row now;
    # window-bound could only ever hold 2
    assert st["capacity_rows_now"] == 1
    assert st["window_bound_rows"] == 2


# -- randomized invariant fuzz ------------------------------------------------


def test_fuzz_alloc_share_release_invariants():
    """Random interleavings against a shadow refcount model: the pool's
    refcounts always match the model, no page is ever free and live at
    once, and free + live bytes always cover the arena exactly."""
    rng = np.random.default_rng(42)
    pool = mkpool(n_pages=33, page=8, page_bytes=64)
    shadow: dict[int, int] = {}      # pid -> model refcount
    for step in range(2000):
        op = rng.integers(0, 3)
        if op == 0:                  # alloc
            n = int(rng.integers(1, 5))
            try:
                pids = pool.alloc(n, tokens=int(rng.integers(0, n * 8 + 1)))
            except PagesExhausted:
                assert pool.free_count() < n
            else:
                for p in pids:
                    assert shadow.get(p, 0) == 0, "allocated a live page"
                    shadow[p] = 1
        elif op == 1 and shadow:     # share a random live subset
            live = [p for p, r in shadow.items() if r > 0]
            take = list(rng.choice(live,
                                   size=min(len(live),
                                            int(rng.integers(1, 4))),
                                   replace=False))
            pool.retain(take)
            for p in take:
                shadow[p] += 1
        elif op == 2 and shadow:     # release one ref on a subset
            live = [p for p, r in shadow.items() if r > 0]
            take = list(rng.choice(live,
                                   size=min(len(live),
                                            int(rng.integers(1, 4))),
                                   replace=False))
            pool.release(take)
            for p in take:
                shadow[p] -= 1
                if shadow[p] == 0:
                    del shadow[p]
        pool.check_invariants()
        for p, r in shadow.items():
            assert pool.refcount(p) == r
    st = pool.stats()
    assert st["pages_live"] == len(shadow)
    assert st["release_pages"] + st["pages_live"] == st["alloc_pages"]


def test_fuzz_pin_shadow_model_sweep_never_frees_pinned():
    """The session-pin extension of the fuzz: pages carry a PINNED flag
    (the store's session pins, modeled as pure bookkeeping) and a
    store-style sweep op releases only UNPINNED refcount-1 pages — the
    exact contract the prefix store's reclaim/eviction sweeps honor.
    Invariants hold through pin/unpin churn and the shadow model stays
    exact: a pinned page is never freed by a sweep, only by its own
    unpin + release."""
    rng = np.random.default_rng(7)
    pool = mkpool(n_pages=33, page=8, page_bytes=64)
    shadow: dict[int, int] = {}      # pid -> model refcount
    pinned: set[int] = set()         # the store's pinned leaves
    for step in range(2000):
        op = rng.integers(0, 5)
        if op == 0:                  # alloc (a cold insert)
            try:
                pids = pool.alloc(int(rng.integers(1, 4)))
            except PagesExhausted:
                pass
            else:
                for p in pids:
                    assert shadow.get(p, 0) == 0
                    shadow[p] = 1
        elif op == 1 and shadow:     # pin a live page (a session turn)
            live = [p for p, r in shadow.items() if r > 0]
            pinned.add(int(rng.choice(live)))
        elif op == 2 and pinned:     # unpin (session end / lease lapse)
            pinned.discard(int(rng.choice(sorted(pinned))))
        elif op == 3 and shadow:     # a row shares/releases a page
            live = [p for p, r in shadow.items() if r > 0]
            p = int(rng.choice(live))
            if rng.integers(0, 2) and shadow[p] > 1:
                pool.release([p])
                shadow[p] -= 1
            else:
                pool.retain([p])
                shadow[p] += 1
        else:                        # the store's cold-page sweep
            victims = [p for p, r in shadow.items()
                       if r == 1 and p not in pinned]
            take = victims[:int(rng.integers(0, 4))]
            pool.release(take)
            for p in take:
                del shadow[p]
        pool.check_invariants()
        for p in pinned:             # a pinned page is always live
            assert pool.refcount(p) == shadow[p] > 0
    # end every "session", then sweep: the pool drains to exactly the
    # still-shared pages — pins never leaked a page
    pinned.clear()
    stuck = [p for p, r in shadow.items() if r == 1]
    pool.release(stuck)
    for p in stuck:
        del shadow[p]
    pool.check_invariants()
    assert pool.stats()["pages_live"] == len(shadow)


def test_concurrent_alloc_release_conserves_pages():
    pool = mkpool(n_pages=65, page=8, page_bytes=8)
    errs: list = []

    def churn(seed):
        rng = np.random.default_rng(seed)
        held: list = []
        try:
            for _ in range(300):
                if held and rng.integers(0, 2):
                    pool.release(held.pop())
                else:
                    try:
                        held.append(pool.alloc(int(rng.integers(1, 4))))
                    except PagesExhausted:
                        pass
            for h in held:
                pool.release(h)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=churn, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert pool.free_count() == pool.capacity_pages
    pool.check_invariants()


# -- engine + HTTP backpressure ----------------------------------------------


def test_engine_sheds_priced_when_arena_full(tiny_server):
    """A transiently full arena sheds the admission with PagesExhausted
    (priced: retry_after_s rides the exception) and serves again once
    pages release — never an engine failure, never a lost in-flight
    row."""
    from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    cfg = tiny_server.model.cfg
    page = page_width(cfg.max_len, 16)
    pool = PagePool(n_pages=3, page=page,
                    page_bytes=page_kv_bytes(cfg, page),
                    make_arena=lambda: init_page_arena(cfg, 3, page))
    eng = ContinuousBatcher(tiny_server, slots=2, segment=8,
                            page_pool=pool)
    row = [1, 2, 3]
    solo = tiny_server.generate(row, max_new_tokens=8)
    held = pool.alloc(2)
    with pytest.raises(PagesExhausted) as exc:
        eng.generate(row, max_new_tokens=8)
    assert exc.value.retry_after_s > 0
    pool.release(held)
    np.testing.assert_array_equal(eng.generate(row, max_new_tokens=8),
                                  solo)
    pool.check_invariants()


def test_server_maps_pages_exhausted_to_shed_503(monkeypatch, tmp_path):
    """PagesExhausted escaping handler.invoke answers shed-style: 503 +
    integer Retry-After from the pool's own estimate, shed reason
    ``kv_pages``, no error counted — backpressure, not a fault."""
    from pathlib import Path
    from types import SimpleNamespace

    import lambdipy_tpu.runtime.server as server_mod
    from lambdipy_tpu.runtime.loader import BootReport

    def invoke(st, request):
        raise PagesExhausted(4, 1, retry_after_s=2.5)

    def stub_boot(bundle_dir, warmup=True):
        return BootReport(
            bundle_dir=Path(bundle_dir),
            handler=SimpleNamespace(invoke=invoke),
            state=SimpleNamespace(meta={"model": "stub"},
                                  stats=lambda: {"stub": True}),
            stages={"init": 0.0}, manifest={"payload": {"extra": {}}})

    monkeypatch.setattr(server_mod, "load_bundle", stub_boot)
    srv = server_mod.BundleServer(tmp_path, port=0,
                                  warmup=False).start_background()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/invoke",
            data=json.dumps({"tokens": [1, 2]}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 503
        assert int(exc.value.headers["Retry-After"]) == 3  # ceil(2.5)
        body = json.loads(exc.value.read())
        assert not body["ok"] and body["retry_after_s"] == 2.5
        shed = srv.sched.admission.shed_report()
        assert shed["by_reason"].get("kv_pages") == 1
        assert srv.stats.report()["errors"] == 0
    finally:
        threading.Thread(target=srv.stop, daemon=True).start()
