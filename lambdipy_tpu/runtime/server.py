"""HTTP serve loop for a booted bundle.

stdlib ThreadingHTTPServer (SURVEY.md §9.5: enough for v1; invokes are
device-bound so Python threading overhead is noise next to device dispatch).
Endpoints:

- ``GET  /healthz``  liveness + boot/cold-start report (watchdog surface)
- ``GET  /metrics``  latency percentiles + error counts (JSON)
- ``POST /invoke``   JSON request -> handler -> JSON response

Every invoke passes the SLO scheduler (lambdipy_tpu/sched): admission
control (per-tenant token buckets, a bounded queue, deadline-based
shedding on ``x-deadline-ms``) then a policy-ordered wait for one of
``max_concurrency`` run slots. Overload turns into explicit 429/503
responses carrying ``Retry-After`` instead of unbounded latency; request
class rides the ``x-priority`` header (interactive | batch | background),
tenant identity the ``x-api-key`` / ``x-tenant`` header.

Failure behavior (SURVEY.md §6 failure-detection row): handler exceptions
return 500 with the error type and are counted; the process stays up.
``POST /shutdown`` drains and stops (used by the deploy controller).
"""

from __future__ import annotations

import json
import math
import os
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from lambdipy_tpu.runtime.continuous import RequestCancelled
from lambdipy_tpu.runtime.loader import BootReport, load_bundle
from lambdipy_tpu.runtime.pagepool import PagesExhausted
from lambdipy_tpu.runtime.prefixstore import SessionPinsExceeded
from lambdipy_tpu.runtime.metrics import LatencyStats
from lambdipy_tpu.sched import (
    SchedConfig,
    Scheduler,
    Shed,
    clear_request_context,
    set_request_context,
)
from lambdipy_tpu.utils.logs import get_logger, log_event

log = get_logger("lambdipy.server")


def _request_token_counts(request: dict | None,
                          prefix_probe=None) -> tuple[int, int]:
    """Best-effort (prefill, decode) token counts for the cost estimator:
    wrong-shaped fields count as zero — sizing is advisory, validation
    belongs to the handler.

    ``prefix_probe`` is the handler's automatic-prefix-cache probe
    (prompt ids -> tokens the radix store would reuse): admission prices
    the SUFFIX a cache-hit request will actually prefill, not the full
    prompt — otherwise deadline shedding keeps rejecting exactly the
    requests the cache makes cheap."""
    if not isinstance(request, dict):
        return 0, 0
    prefill = 0
    toks = request.get("tokens")
    flat_row = None
    if isinstance(toks, (list, tuple)):
        if toks and isinstance(toks[0], (list, tuple)):
            prefill = sum(len(r) for r in toks
                          if isinstance(r, (list, tuple)))
        else:
            prefill = len(toks)
            flat_row = toks
    prefix = request.get("prefix")
    if isinstance(prefix, (list, tuple)):
        prefill += len(prefix)
    elif prefix_probe is not None and flat_row is not None and prefill:
        try:
            prefill = max(0, prefill - int(prefix_probe(flat_row)))
        except Exception:  # noqa: BLE001 — pricing is advisory
            pass
    decode = 0
    for key in ("max_new_tokens", "max_tokens"):
        raw = request.get(key)
        if isinstance(raw, (int, float)):
            decode = max(0, int(raw))
            break
    return prefill, decode


def _openai_to_internal(req: dict) -> tuple[dict, str | None]:
    """Translate an OpenAI /v1/completions body into the generate
    handler's request shape. ``prompt`` may be a string (bundle tokenizer
    required) or an int token array (tokenizer-free). OpenAI sampling
    defaults apply: temperature/top_p default to 1.0 (sampled) — send
    temperature 0 for greedy."""
    prompt = req.get("prompt")
    internal: dict = {}
    if isinstance(prompt, str):
        internal["text"] = prompt
    elif isinstance(prompt, list) and prompt and \
            all(isinstance(t, int) for t in prompt):
        internal["tokens"] = prompt
    else:
        return {}, "prompt must be a string or an array of token ids"
    if req.get("stop") is not None:
        return {}, "stop sequences are not supported; pass eos_id"
    if req.get("n", 1) != 1:
        return {}, "n > 1 is not supported"
    try:
        if req.get("max_tokens") is not None:
            internal["max_new_tokens"] = int(req["max_tokens"])
        internal["temperature"] = float(req.get("temperature", 1.0))
        internal["top_p"] = float(req.get("top_p", 1.0))
    except (TypeError, ValueError) as e:
        return {}, f"max_tokens/temperature/top_p must be numbers: {e}"
    for knob in ("top_k", "seed", "eos_id", "prefix", "segment",
                 "speculative", "session_id", "session_ttl_s"):
        if req.get(knob) is not None:
            internal[knob] = req[knob]
    lp = req.get("logprobs")
    if lp:
        try:
            if lp is not True and int(lp) > 1:
                return {}, ("top_logprobs > 1 is not supported "
                            "(send logprobs: 1)")
        except (TypeError, ValueError):
            return {}, "logprobs must be a boolean or small integer"
        internal["logprobs"] = True
    internal["stream"] = bool(req.get("stream"))
    return internal, None


def _internal_to_openai(internal: dict, result: dict) -> dict:
    row = list((result.get("tokens") or [[]])[0])
    # the handler reports the EFFECTIVE eos (a string prompt inherits the
    # tokenizer's) and the real prompt token count; fall back to what the
    # request carried
    eos = result.get("eos_id", internal.get("eos_id"))
    finish = "length"
    if eos is not None and eos in row:
        # eos latching pads the row to the full decode width — trim so
        # tokens and usage reflect what was actually generated
        row = row[: row.index(eos) + 1]
        finish = "stop"
    n_prompt = int(result.get("n_prompt",
                              len(internal.get("tokens") or [])))
    choice = {"index": 0, "text": result.get("completion", ""),
              "tokens": row, "finish_reason": finish,
              "logprobs": None}
    if result.get("logprobs"):
        lp_row = result["logprobs"][0][: len(row)]
        choice["logprobs"] = {"tokens": [str(t) for t in row],
                              "token_logprobs": lp_row,
                              "top_logprobs": None, "text_offset": None}
    return {
        "object": "text_completion",
        "model": "lambdipy-bundle",
        "choices": [choice],
        "usage": {"prompt_tokens": n_prompt,
                  "completion_tokens": len(row),
                  "total_tokens": n_prompt + len(row)},
    }


class BundleServer:
    def __init__(self, bundle_dir: Path, host: str = "127.0.0.1", port: int = 0,
                 *, warmup: bool = True, sched: dict | None = None):
        self.bundle_dir = Path(bundle_dir)
        self.stats = LatencyStats()
        self._profile_lock = threading.Lock()
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.draining = False
        self.started = time.time()
        # The generate handler builds its batchers INSIDE load_bundle, so
        # the effective policy must be resolved first and bridged through
        # the env var the handler reads — otherwise a programmatic
        # sched={"policy": ...} would report one policy on /metrics while
        # batch formation ordered by another. (Pre-read the manifest
        # best-effort; the authoritative extra comes from the boot below.)
        pre_extra: dict = {}
        try:
            pre_extra = (json.loads(
                (self.bundle_dir / "manifest.json").read_text())
                .get("payload") or {}).get("extra") or {}
        except (OSError, ValueError):
            pass
        pre_policy = SchedConfig.from_extra(pre_extra, **(sched or {})).policy
        prev_env = os.environ.get("LAMBDIPY_SCHED_POLICY")
        os.environ["LAMBDIPY_SCHED_POLICY"] = pre_policy
        try:
            self.boot: BootReport = load_bundle(self.bundle_dir,
                                                warmup=warmup)
        finally:
            if prev_env is None:
                os.environ.pop("LAMBDIPY_SCHED_POLICY", None)
            else:
                os.environ["LAMBDIPY_SCHED_POLICY"] = prev_env
        # SLO scheduler config layers: bundle [payload.extra] sched_* keys,
        # overridden by explicit ctor/CLI values
        extra = (self.boot.manifest.get("payload") or {}).get("extra") or {}
        cfg = SchedConfig.from_extra(extra, **(sched or {}))
        # a batching bundle sized past the default run-slot count must not
        # be silently throttled to 8 concurrent invokes: unless the
        # operator pinned sched_max_concurrency, floor the slots at the
        # batcher's own width so every batch slot can actually fill
        explicit = (extra.get("sched_max_concurrency") is not None
                    or (sched or {}).get("max_concurrency") is not None)
        batching = (str(extra.get("batch_mode", "")).lower() == "continuous"
                    or float(extra.get("batch_window_ms", 0) or 0) > 0)
        if not explicit and batching:
            cfg.max_concurrency = max(cfg.max_concurrency,
                                      int(extra.get("batch_max", 8)))
        self.sched = Scheduler(cfg)
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    # -- request handling ---------------------------------------------------

    def _make_handler(server_self):
        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route through structured logs
                log.debug(fmt % args)

            def _send(self, code: int, payload: dict,
                      headers: dict | None = None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def _require_loopback(self) -> bool:
                """Host-only endpoints (/v1/debug/*): refuse
                non-loopback clients with a 403 BEFORE touching the
                request body (the connection closes, so keep-alive
                cannot misparse unread bytes). These surfaces expose a
                fault-injection control plane and cache internals —
                operator/debugger tools on the host, never a path a
                fronting proxy should forward. /v1/kv/probe stays OPEN
                like /v1/kv/export|import: it is part of the fleet KV
                wire surface — the router's import-miss pull calls it
                cross-host, and its error path deliberately reads a
                refusal as blocks-present (plain dedup semantics), so
                gating it would silently disable the pull."""
                if self.client_address[0] in ("127.0.0.1", "::1"):
                    return True
                self.close_connection = True
                self._send(403, {"ok": False, "error":
                                 "host-only endpoint (loopback clients "
                                 "only)"})
                return False

            def do_GET(self):
                if self.path == "/v1/debug/invariants":
                    self._debug_invariants()
                    return
                if self.path == "/healthz":
                    # liveness vs readiness split: "ok" is liveness (the
                    # process answers — always 200 so watchdog tooling
                    # keeps working), "ready" says ROUTE TO ME. A
                    # replica reports ready: false while the background
                    # warmup/group-prefill is still compiling or once
                    # drain has begun, so the fleet router deprioritizes
                    # it BEFORE the 503s start. warming_fn is the
                    # handler's O(1) flag — NOT the full stats()
                    # document, which takes the serving path's locks
                    # and would be recomputed every probe interval.
                    warming_fn = getattr(server_self.boot.state,
                                         "warming_fn", None)
                    try:
                        warming = bool(warming_fn()) if warming_fn else False
                    except Exception:  # noqa: BLE001 — health never 500s
                        warming = False
                    # wedged = the engine watchdog gave up on a device
                    # wait: liveness stays 200 (the process answers) but
                    # ready flips false and the explicit wedged flag
                    # lets the fleet prober EJECT (not merely
                    # deprioritize) the replica at probe speed
                    engine = server_self._engine_fault_state()
                    wedged = bool(engine.get("wedged"))
                    self._send(200, {
                        "ok": True,
                        "ready": (not server_self.draining and not warming
                                  and not wedged),
                        "warming": warming,
                        "wedged": wedged,
                        **({"engine": engine} if engine else {}),
                        "pid": os.getpid(),
                        "draining": server_self.draining,
                        "bundle": str(server_self.bundle_dir),
                        "uptime_s": round(time.time() - server_self.started, 1),
                        "cold_start": server_self.boot.stages,
                        "skew": server_self.boot.skew,
                        "handler_meta": getattr(server_self.boot.state, "meta", {}),
                        # build-time warm outcome from the manifest: a
                        # failed warm explains a slow cold_start downstream
                        "warm": server_self.boot.manifest.get("warm"),
                        # non-empty = numerics sanitizer on (per-call sync)
                        "debug_flags": server_self.boot.debug_flags,
                        "sched": {"policy": server_self.sched.policy.name,
                                  "queued": server_self.sched.queue.depth()},
                    })
                elif self.path == "/metrics":
                    report = server_self.stats.report()
                    # admission/scheduling surface: queue depths, shed
                    # counts by reason/class, per-class queue-wait
                    # percentiles, cost-model state
                    report["sched"] = server_self.sched.report()
                    handler_stats = getattr(server_self.boot.state, "stats",
                                            lambda: {})()
                    if handler_stats:
                        report["handler"] = handler_stats
                    self._send(200, report)
                else:
                    self._send(404, {"ok": False, "error": "not found"})

            def _read_json(self) -> dict | None:
                """Parse the request body; sends a 400 and returns None on
                client errors."""
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                    return body
                except (ValueError, json.JSONDecodeError) as e:
                    self._send(400, {"ok": False, "error": f"bad request: {e}"})
                    return None

            def _send_shed(self, shed: Shed, *, openai: bool = False):
                """An explicit overload rejection: 429/503 + Retry-After
                (integer seconds per RFC 9110; the body carries the exact
                float for clients that want tighter backoff)."""
                headers = {"Retry-After":
                           str(max(1, math.ceil(shed.retry_after_s)))}
                if openai:
                    payload = {"error": {
                        "message": f"shed: {shed.reason}",
                        "type": ("rate_limit_error" if shed.code == 429
                                 else "overloaded_error"),
                        "retry_after_s": round(shed.retry_after_s, 3)}}
                else:
                    payload = shed.payload()
                self._send(shed.code, payload, headers)

            def _begin_invoke(self, request: dict | None = None, *,
                              openai: bool = False):
                """Admission gate every invoke passes: draining check +
                in-flight increment as one atomic step (stop() can then
                never observe inflight==0 while an accepted invoke is
                still on its way to dispatch), then scheduler admission
                (rate / queue-depth / deadline shedding) and a
                policy-ordered wait for a run slot. Returns a live
                ticket, or None after sending the 429/503 (with
                Retry-After) itself."""
                cls = (self.headers.get("x-priority")
                       or "interactive").strip().lower()
                tenant = (self.headers.get("x-api-key")
                          or self.headers.get("x-tenant") or "anon")
                try:
                    deadline_ms = float(self.headers["x-deadline-ms"])
                except (KeyError, TypeError, ValueError):
                    deadline_ms = None
                with server_self._inflight_lock:
                    draining = server_self.draining
                    if not draining:
                        server_self._inflight += 1
                if draining:
                    server_self.sched.admission.count_shed("draining", cls)
                    self._send_shed(Shed(503, "draining", 1.0),
                                    openai=openai)
                    return None
                # wedged-engine accept hole: while the engine is wedged
                # AND a restart is in flight (replays queued behind a
                # dead device), admitting more work would queue requests
                # into an engine that cannot serve them — shed instead.
                # A wedged engine with NO restart running still admits:
                # that request IS the recovery probe (it restarts the
                # engine; success clears the wedge, another trip re-503s
                # followers).
                engine = server_self._engine_fault_state()
                if engine.get("wedged") and engine.get("restarting"):
                    with server_self._inflight_lock:
                        server_self._inflight -= 1
                    server_self.sched.admission.count_shed("wedged", cls)
                    self._send_shed(Shed(503, "wedged", 2.0),
                                    openai=openai)
                    return None
                prefill, decode = _request_token_counts(
                    request,
                    prefix_probe=getattr(server_self.boot.state,
                                         "prefix_probe", None))
                out = server_self.sched.admit(
                    tenant=tenant, cls=cls, deadline_ms=deadline_ms,
                    prefill_tokens=prefill, decode_tokens=decode)
                if isinstance(out, Shed):
                    with server_self._inflight_lock:
                        server_self._inflight -= 1
                    self._send_shed(out, openai=openai)
                    return None
                if not server_self.sched.wait_turn(out):
                    # deadline became unmeetable while queued: shed at
                    # grant time instead of burning the slot
                    with server_self._inflight_lock:
                        server_self._inflight -= 1
                    self._send_shed(
                        Shed(503, "deadline",
                             max(0.05, out.cost_ms / 1e3)), openai=openai)
                    return None
                # the batchers read the request's class from this context
                # when forming batches (policy-ordered handoff)
                set_request_context(cls=out.cls, tenant=tenant,
                                    deadline_ms=deadline_ms)
                return out

            def _end_invoke(self, ticket, t0: float) -> None:
                clear_request_context()
                # feed the estimator with slot-occupancy time (errors
                # included — an erroring request still held the slot)
                server_self.sched.finish(
                    ticket, service_ms=(time.monotonic() - t0) * 1e3)
                with server_self._inflight_lock:
                    server_self._inflight -= 1

            def _session_header(self, request: dict | None) -> None:
                """`x-session-id` (+ optional `x-session-ttl-s`) are the
                header spelling of the body's session fields — the body
                wins when both are present (explicit beats transport)."""
                if not isinstance(request, dict):
                    return
                sid = self.headers.get("x-session-id")
                if sid and not request.get("session_id"):
                    request["session_id"] = sid
                ttl = self.headers.get("x-session-ttl-s")
                if ttl and request.get("session_ttl_s") is None:
                    request["session_ttl_s"] = ttl

            def do_DELETE(self):
                """DELETE /v1/sessions/{id}: release the session's
                prefix-store pins NOW (lease expiry would get there
                eventually; a well-behaved client closes explicitly)."""
                if not self.path.startswith("/v1/sessions/"):
                    self._send(404, {"ok": False, "error": "not found"})
                    return
                sid = self.path[len("/v1/sessions/"):]
                fn = getattr(server_self.boot.state, "session_end_fn",
                             None)
                if fn is None or not sid:
                    self._send(404, {"ok": False, "error":
                                     "no session surface (prefix cache "
                                     "off or unsupported handler)"})
                    return
                try:
                    out = fn(sid)
                except Exception as e:  # noqa: BLE001
                    server_self.stats.record_error()
                    self._send(500, {"ok": False, "error": str(e)})
                    return
                self._send(200, {"ok": True, "session": sid, **out})

            def do_POST(self):
                if self.path == "/v1/completions":
                    self._openai_completions()
                    return
                if self.path == "/v1/kv/export":
                    self._kv_export()
                    return
                if self.path == "/v1/kv/import":
                    self._kv_import()
                    return
                if self.path == "/v1/kv/probe":
                    self._kv_probe()
                    return
                if self.path == "/v1/debug/faults":
                    self._debug_faults()
                    return
                if self.path == "/v1/debug/knobs":
                    self._debug_knobs()
                    return
                if self.path == "/profile":
                    req = self._read_json()
                    if req is None:
                        return
                    try:
                        n = max(1, min(int(req.get("invokes", 3)), 100))
                    except (TypeError, ValueError):
                        self._send(400, {"ok": False,
                                         "error": "invokes must be an integer"})
                        return
                    # capture a device trace around N warmup-shaped invokes;
                    # serialized — concurrent start_trace calls would fail
                    try:
                        from lambdipy_tpu.utils.trace import (
                            latest_trace_files,
                            profile_trace,
                        )

                        out_dir = server_self.bundle_dir / "profiles" / str(int(time.time()))
                        with server_self._profile_lock:
                            with profile_trace(out_dir) as capture:
                                for _ in range(n):
                                    server_self.boot.handler.invoke(
                                        server_self.boot.state, {"warmup": True})
                        payload = {"ok": capture.started, "dir": str(out_dir),
                                   "files": latest_trace_files(out_dir)}
                        if capture.error:
                            payload["error"] = capture.error
                        self._send(200 if capture.started else 503, payload)
                    except Exception as e:
                        self._send(500, {"ok": False, "error": str(e)})
                    return
                if self.path == "/shutdown":
                    self._send(200, {"ok": True, "draining": True})
                    threading.Thread(target=server_self.stop, daemon=True).start()
                    return
                if self.path != "/invoke":
                    self._send(404, {"ok": False, "error": "not found"})
                    return
                # body must be consumed before any early reply: on a
                # keep-alive connection unread body bytes would be parsed
                # as the next request line
                request = self._read_json()
                if request is None:
                    server_self.stats.record_error()
                    return
                self._session_header(request)
                ticket = self._begin_invoke(request)
                if ticket is None:
                    return
                t0 = time.monotonic()
                # in-flight covers the response write too: drain must not
                # observe 0 (and let the process exit) between handler
                # completion and the 200 actually reaching the client
                try:
                    state = server_self.boot.state
                    if request.get("stream") and \
                            getattr(state, "invoke_stream_fn", None) is not None:
                        # the HandlerState method owns the call convention
                        # (request copy, support check)
                        self._send_stream(state.invoke_stream, request, t0)
                        return
                    try:
                        result = server_self.boot.handler.invoke(
                            server_self.boot.state, request)
                    except RequestCancelled as e:
                        # not a handler bug: the engine cancelled the row
                        # at a drain barrier (deadline expired / waiter
                        # gone). Answer shed-style — 503 + Retry-After —
                        # so clients back off and retry instead of
                        # treating it as a server fault.
                        cls = (self.headers.get("x-priority")
                               or "interactive").strip().lower()
                        server_self.sched.admission.count_shed(
                            "cancelled", cls)
                        self._send_shed(Shed(503, str(e), 1.0))
                        return
                    except PagesExhausted as e:
                        # the paged KV arena is transiently full —
                        # backpressure priced by the pool's own release
                        # cadence, exactly like a queue-depth shed
                        cls = (self.headers.get("x-priority")
                               or "interactive").strip().lower()
                        server_self.sched.admission.count_shed(
                            "kv_pages", cls)
                        self._send_shed(
                            Shed(503, "kv_pages", e.retry_after_s))
                        return
                    except SessionPinsExceeded as e:
                        # the session-pin budget is full: shed the NEW
                        # session, priced by the earliest lease-expiry
                        # horizon — pins never starve live traffic
                        cls = (self.headers.get("x-priority")
                               or "interactive").strip().lower()
                        server_self.sched.admission.count_shed(
                            "session_pins", cls)
                        self._send_shed(
                            Shed(503, "session_pins", e.retry_after_s))
                        return
                    except Exception as e:  # handler bug or bad payload shape
                        server_self.stats.record_error()
                        log_event(log, "invoke failed", error=str(e),
                                  kind=type(e).__name__)
                        self._send(500, {"ok": False, "error": str(e),
                                         "kind": type(e).__name__})
                        return
                    server_self.stats.record((time.monotonic() - t0) * 1e3)
                    self._send(200, result)
                finally:
                    self._end_invoke(ticket, t0)

            def _openai_completions(self):
                """OpenAI-compatible shim over the generate handler:
                "prompt" may be a string (needs the bundle tokenizer) or
                a token array (works without one). Shares the /invoke
                drain bracket — graceful shutdown waits for these too."""
                req = self._read_json()
                if req is None:
                    server_self.stats.record_error()
                    return
                internal, err = _openai_to_internal(req)
                if err is not None:
                    self._send(400, {"error": {"message": err,
                                               "type": "invalid_request_error"}})
                    return
                self._session_header(internal)
                # admit on the TRANSLATED request: the internal shape
                # carries "tokens"/"max_new_tokens", so the estimator
                # sees real prefill/decode counts (the raw OpenAI body
                # keys them "prompt"/"max_tokens")
                ticket = self._begin_invoke(internal, openai=True)
                if ticket is None:
                    return
                t_start = time.monotonic()
                try:
                    if internal.pop("stream", False):
                        state = server_self.boot.state
                        if getattr(state, "invoke_stream_fn", None) is None:
                            self._send(400, {"error": {
                                "message": "handler does not support streaming",
                                "type": "invalid_request_error"}})
                            return
                        self._send_sse(state.invoke_stream, internal)
                        return
                    t0 = time.monotonic()
                    try:
                        result = server_self.boot.handler.invoke(
                            server_self.boot.state, internal)
                    except RequestCancelled as e:
                        # drain-barrier cancellation, not a server fault:
                        # shed-style 503 so OpenAI clients retry/back off
                        cls = (self.headers.get("x-priority")
                               or "interactive").strip().lower()
                        server_self.sched.admission.count_shed(
                            "cancelled", cls)
                        self._send_shed(Shed(503, str(e), 1.0), openai=True)
                        return
                    except PagesExhausted as e:
                        # transiently full KV page arena: priced
                        # backpressure, not a server fault
                        cls = (self.headers.get("x-priority")
                               or "interactive").strip().lower()
                        server_self.sched.admission.count_shed(
                            "kv_pages", cls)
                        self._send_shed(
                            Shed(503, "kv_pages", e.retry_after_s),
                            openai=True)
                        return
                    except SessionPinsExceeded as e:
                        # session-pin budget full: priced shed of the
                        # NEW session, Retry-After = lease horizon
                        cls = (self.headers.get("x-priority")
                               or "interactive").strip().lower()
                        server_self.sched.admission.count_shed(
                            "session_pins", cls)
                        self._send_shed(
                            Shed(503, "session_pins", e.retry_after_s),
                            openai=True)
                        return
                    except Exception as e:
                        server_self.stats.record_error()
                        self._send(500, {"error": {"message": str(e),
                                                   "type": type(e).__name__}})
                        return
                    if not result.get("ok"):
                        server_self.stats.record_error()
                        self._send(400, {"error": {
                            "message": result.get("error", "invoke failed"),
                            "type": "invalid_request_error"}})
                        return
                    server_self.stats.record((time.monotonic() - t0) * 1e3)
                    out = _internal_to_openai(internal, result)
                    # echo the ACTUAL sched queue wait (stamped on the
                    # ticket at grant) so a client can window latency
                    # attribution per-request instead of reading the
                    # replica's cumulative percentile reservoir
                    wait_ms = getattr(ticket, "wait_ms", None)
                    if wait_ms is not None:
                        out["queue_wait_ms"] = round(wait_ms, 3)
                    self._send(200, out)
                finally:
                    self._end_invoke(ticket, t_start)

            def _kv_export(self):
                """Disaggregated-serving export: the request's whole-
                block prompt head leaves as a binary KV frame
                (runtime/kvwire.py). Missing blocks prefill here — on a
                prefill-class replica this call IS the request's
                prefill phase, so it passes the same admission gate as
                an invoke (the estimator prices the suffix via the
                prefix probe, exactly like a generate)."""
                fn = getattr(server_self.boot.state, "kv_export_fn", None)
                request = self._read_json()
                if request is None:
                    server_self.stats.record_error()
                    return
                stream_fn = getattr(server_self.boot.state,
                                    "kv_export_stream_fn", None)
                if request.get("stream") and stream_fn is not None:
                    self._kv_export_stream(stream_fn, request)
                    return
                if fn is None:
                    self._send(404, {"ok": False, "error":
                                     "no KV export surface (prefix "
                                     "cache off or unsupported handler)"})
                    return
                ticket = self._begin_invoke(request)
                if ticket is None:
                    return
                t0 = time.monotonic()
                try:
                    try:
                        out = fn(request)
                    except RequestCancelled as e:
                        cls = (self.headers.get("x-priority")
                               or "interactive").strip().lower()
                        server_self.sched.admission.count_shed(
                            "cancelled", cls)
                        self._send_shed(Shed(503, str(e), 1.0))
                        return
                    except PagesExhausted as e:
                        cls = (self.headers.get("x-priority")
                               or "interactive").strip().lower()
                        server_self.sched.admission.count_shed(
                            "kv_pages", cls)
                        self._send_shed(
                            Shed(503, "kv_pages", e.retry_after_s))
                        return
                    except Exception as e:  # noqa: BLE001
                        server_self.stats.record_error()
                        log_event(log, "kv export failed", error=str(e),
                                  kind=type(e).__name__)
                        self._send(500, {"ok": False, "error": str(e),
                                         "kind": type(e).__name__})
                        return
                    if isinstance(out, dict):  # handler-level refusal
                        self._send(400, out)
                        return
                    server_self.stats.record(
                        (time.monotonic() - t0) * 1e3)
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length", str(len(out)))
                    self.end_headers()
                    try:
                        self.wfile.write(out)
                    except OSError:
                        self.close_connection = True
                finally:
                    self._end_invoke(ticket, t0)

            def _kv_export_stream(self, stream_fn, request: dict):
                """Chunked (pipelined-ship) export: one HTTP chunk per
                wire frame, flushed as soon as the prefix-store walk
                produces its block group — the router's relay reads
                frame k while this replica prefills chunk k+1. Same
                admission bracket as the monolithic export (the export
                IS the request's prefill). A mid-walk failure after
                headers are committed TRUNCATES the stream (no terminal
                chunk): the receiver's block accounting makes
                truncation self-evident, so there is no honest 500 left
                to send and no dishonest clean EOF sent instead."""
                ticket = self._begin_invoke(request)
                if ticket is None:
                    return
                t0 = time.monotonic()
                committed = False
                try:
                    gen = stream_fn(request)
                    if isinstance(gen, dict):  # handler-level refusal
                        self._send(400, gen)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-lkv-stream")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    committed = True
                    for frame in gen:
                        if not self._write_frame(frame):
                            return  # client gone; generator closed
                    server_self.stats.record(
                        (time.monotonic() - t0) * 1e3)
                    self._end_frames()
                except Exception as e:  # noqa: BLE001
                    server_self.stats.record_error()
                    log_event(log, "kv export stream failed",
                              error=str(e), kind=type(e).__name__)
                    if not committed:
                        self._send(500, {"ok": False, "error": str(e),
                                         "kind": type(e).__name__})
                    else:
                        self.close_connection = True
                finally:
                    self._end_invoke(ticket, t0)

            def _read_chunked_body(self):
                """Generator over a chunked-transfer request body's
                chunks (stdlib BaseHTTPRequestHandler does not de-chunk
                requests). A malformed framing line raises ValueError;
                a connection dying mid-chunk raises ConnectionError —
                both roll the streaming import back."""
                from lambdipy_tpu.runtime.kvwire import _MAX_CHUNK_BODY

                while True:
                    line = self.rfile.readline(66)
                    if not line:
                        raise ConnectionError(
                            "connection closed mid-chunk-stream")
                    size = int(line.strip().split(b";")[0], 16)
                    if size > _MAX_CHUNK_BODY + 4096:
                        # the wire format already bounds what a chunk
                        # may carry (kvwire validates nbody); bound the
                        # HTTP-chunk allocation the same way, or a
                        # hostile hex length buffers arbitrary bytes
                        # BEFORE the validator ever sees one
                        raise ValueError(
                            f"chunk size {size} exceeds the KV stream "
                            f"bound")
                    if size == 0:
                        self.rfile.readline()  # trailing CRLF
                        return
                    data = self.rfile.read(size)
                    if len(data) < size:
                        raise ConnectionError(
                            "connection closed mid-chunk")
                    self.rfile.read(2)  # chunk CRLF
                    yield data

            def _kv_import_stream(self, stream_fn):
                """Chunked (pipelined-ship) import: each arriving frame
                stages immediately (device page writes overlap the rest
                of the transfer); the radix tree is only touched when
                the complete stream commits. Any failure — truncated
                body, garbage chunk, full arena — rolls the staged
                pages back and the tree reads as if the stream never
                happened.

                Admission brackets ONLY the commit (via the gate the
                handler honors): the body arrives over the exporting
                replica's prefill, and a run slot held across that wait
                would serialize this replica's decode batch behind
                every in-flight ship. Staging is backpressured by the
                page arena itself (strict up-front reservation), not by
                the scheduler."""
                t0 = time.monotonic()

                class _CommitShed(Exception):
                    pass

                handler = self

                class _Gate:
                    def __enter__(gate):
                        gate.ticket = handler._begin_invoke(None)
                        if gate.ticket is None:
                            # _begin_invoke already sent the priced 503
                            raise _CommitShed()
                        gate.t0 = time.monotonic()
                        return gate

                    def __exit__(gate, *exc):
                        handler._end_invoke(gate.ticket, gate.t0)
                        return False

                try:
                    out = stream_fn(self._read_chunked_body(),
                                    commit_gate=_Gate())
                except _CommitShed:
                    self.close_connection = True  # shed already sent
                    return
                except PagesExhausted as e:
                    cls = (self.headers.get("x-priority")
                           or "interactive").strip().lower()
                    server_self.sched.admission.count_shed(
                        "kv_import", cls)
                    self.close_connection = True
                    self._send_shed(
                        Shed(503, "kv_import", e.retry_after_s))
                    return
                except ValueError as e:
                    self.close_connection = True
                    self._send(400, {"ok": False,
                                     "error": f"bad KV stream: {e}"})
                    return
                except ConnectionError as e:
                    # the relay died mid-stream: staged pages are
                    # already rolled back; there is nobody left to
                    # answer
                    log_event(log, "kv import stream died",
                              error=str(e))
                    self.close_connection = True
                    return
                except Exception as e:  # noqa: BLE001
                    server_self.stats.record_error()
                    log_event(log, "kv import stream failed",
                              error=str(e), kind=type(e).__name__)
                    self.close_connection = True
                    self._send(500, {"ok": False, "error": str(e),
                                     "kind": type(e).__name__})
                    return
                server_self.stats.record((time.monotonic() - t0) * 1e3)
                self._send(200, out)

            def _kv_import(self):
                """Disaggregated-serving import: a shipped KV frame
                becomes a radix insert. A full page arena answers the
                priced-shed 503 (reason ``kv_import``) so the router
                falls back to mixed-mode local prefill; a malformed
                frame is a 400 and touches nothing. A CHUNKED request
                body routes to the streaming twin."""
                te = (self.headers.get("Transfer-Encoding")
                      or "").lower()
                stream_fn = getattr(server_self.boot.state,
                                    "kv_import_stream_fn", None)
                if "chunked" in te:
                    if stream_fn is None:
                        self.close_connection = True  # unread body
                        self._send(404, {"ok": False, "error":
                                         "no chunked KV import surface "
                                         "(prefix cache off or "
                                         "unsupported handler)"})
                        return
                    self._kv_import_stream(stream_fn)
                    return
                fn = getattr(server_self.boot.state, "kv_import_fn", None)
                # consume the body before any early reply: on keep-alive
                # the unread frame bytes would parse as the next request
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except (TypeError, ValueError):
                    length = 0
                data = self.rfile.read(length) if length > 0 else b""
                if fn is None:
                    self._send(404, {"ok": False, "error":
                                     "no KV import surface (prefix "
                                     "cache off or unsupported handler)"})
                    return
                ticket = self._begin_invoke(None)
                if ticket is None:
                    return
                t0 = time.monotonic()
                try:
                    try:
                        out = fn(data)
                    except PagesExhausted as e:
                        # decode-side import backpressure: same priced-
                        # shed wire shape as every other 503, distinct
                        # reason so operators can tell a full arena
                        # from a full queue
                        cls = (self.headers.get("x-priority")
                               or "interactive").strip().lower()
                        server_self.sched.admission.count_shed(
                            "kv_import", cls)
                        self._send_shed(
                            Shed(503, "kv_import", e.retry_after_s))
                        return
                    except ValueError as e:
                        self._send(400, {"ok": False,
                                         "error": f"bad KV frame: {e}"})
                        return
                    except Exception as e:  # noqa: BLE001
                        server_self.stats.record_error()
                        log_event(log, "kv import failed", error=str(e),
                                  kind=type(e).__name__)
                        self._send(500, {"ok": False, "error": str(e),
                                         "kind": type(e).__name__})
                        return
                    server_self.stats.record(
                        (time.monotonic() - t0) * 1e3)
                    self._send(200, out)
                finally:
                    self._end_invoke(ticket, t0)

            def _debug_invariants(self):
                """GET /v1/debug/invariants (host-only): the cheap
                invariant sweep — pagepool conservation, prefix-store
                pin accounting — as pass/fail + detail JSON. The chaos
                checker's quiesce probe; also a live debugging aid. No
                admission gate: host-side accounting reads only."""
                if not self._require_loopback():
                    return
                fn = getattr(server_self.boot.state,
                             "debug_invariants_fn", None)
                if fn is None:
                    self._send(404, {"ok": False, "error":
                                     "no invariants surface (handler "
                                     "has no serve-path state)"})
                    return
                try:
                    self._send(200, fn())
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"ok": False, "error": str(e)})

            def _debug_faults(self):
                """POST /v1/debug/faults (host-only): arm/clear fault
                rules on the replica's live plan — the chaos soak's
                nemesis control surface. The loopback check runs FIRST:
                a control plane must not parse non-loopback bytes, and
                the refusal closes the connection so the unread body
                cannot poison keep-alive."""
                if not self._require_loopback():
                    return
                request = self._read_json()
                if request is None:
                    return
                fn = getattr(server_self.boot.state, "faults_admin_fn",
                             None)
                if fn is None:
                    self._send(404, {"ok": False, "error":
                                     "no fault-control surface "
                                     "(unsupported handler)"})
                    return
                try:
                    out = fn(request)
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"ok": False, "error": str(e)})
                    return
                self._send(200 if out.get("ok") else 400, out)

            def _debug_knobs(self):
                """POST /v1/debug/knobs (host-only): live-retune the
                continuous engine's per-dispatch knobs (pipeline_depth,
                spec_k) — the elastic fleet controller's actuator.
                Same control-plane shape as _debug_faults: loopback
                refusal first, clamping in the handler closure."""
                if not self._require_loopback():
                    return
                request = self._read_json()
                if request is None:
                    return
                fn = getattr(server_self.boot.state, "knobs_admin_fn",
                             None)
                if fn is None:
                    self._send(404, {"ok": False, "error":
                                     "no knob-control surface "
                                     "(unsupported handler)"})
                    return
                try:
                    out = fn(request)
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"ok": False, "error": str(e)})
                    return
                self._send(200 if out.get("ok") else 400, out)

            def _kv_probe(self):
                """KV presence probe: how many head tokens the radix
                tree actually holds. No admission gate — it is an
                O(depth) dict walk with no device work, and the router
                calls it on the import-miss pull path (cross-host in a
                multi-host fleet, so no loopback refusal — see
                _require_loopback) where queueing behind a run slot
                would cost more than the re-ship it guards."""
                fn = getattr(server_self.boot.state, "kv_probe_fn", None)
                request = self._read_json()
                if request is None:
                    return
                if fn is None:
                    self._send(404, {"ok": False, "error":
                                     "no KV probe surface (prefix "
                                     "cache off or unsupported handler)"})
                    return
                try:
                    out = fn(request)
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"ok": False, "error": str(e)})
                    return
                self._send(200 if out.get("ok") else 400, out)

            def _write_frame(self, body: bytes) -> bool:
                """One chunked-transfer frame; False = client went away
                (recorded on the connection, never raised — the failure
                mode of a streaming response IS the socket)."""
                try:
                    self.wfile.write(f"{len(body):x}\r\n".encode())
                    self.wfile.write(body)
                    self.wfile.write(b"\r\n")
                    return True
                except OSError:
                    self.close_connection = True
                    return False

            def _end_frames(self) -> None:
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    self.close_connection = True

            def _send_sse(self, stream_invoke, internal: dict):
                """OpenAI-style server-sent events: one `data:` event per
                decode segment, closed by `data: [DONE]`. The final
                summary record becomes a last event carrying the decoded
                ``text`` (string prompts) and ``finish_reason``."""
                t0 = time.monotonic()
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def event(obj) -> bool:
                    body = b"data: " + (obj if isinstance(obj, bytes)
                                        else json.dumps(obj).encode()) + b"\n\n"
                    return self._write_frame(body)

                def chunk_event(tokens, text="", finish=None,
                                logprobs=None) -> bool:
                    choice = {"index": 0, "text": text, "tokens": tokens,
                              "finish_reason": finish}
                    if logprobs is not None:
                        choice["logprobs"] = {
                            "tokens": [str(t) for t in tokens],
                            "token_logprobs": logprobs,
                            "top_logprobs": None, "text_offset": None}
                    return event({"object": "text_completion.chunk",
                                  "model": "lambdipy-bundle",
                                  "choices": [choice]})

                emitted: list = []
                text_sent = ""
                final = None
                try:
                    for payload in stream_invoke(internal):
                        if not payload.get("ok"):
                            server_self.stats.record_error()
                            event({"error": {"message": payload.get("error"),
                                             "type": "invoke_error"}})
                            self._end_frames()
                            return
                        if payload.get("done"):
                            final = payload
                            continue
                        emitted.extend(payload["tokens"][0])
                        # incremental text (string prompts): each chunk
                        # carries the delta the handler decoded for it
                        delta = payload.get("text", "")
                        text_sent += delta
                        if not chunk_event(
                                payload["tokens"][0], text=delta,
                                logprobs=(payload.get("logprobs") or
                                          [None])[0]):
                            return
                except SessionPinsExceeded as e:
                    # the 200 is already committed (streams send headers
                    # first), so the shed arrives as the terminal event —
                    # shed-shaped and COUNTED as one, never an error
                    cls = (self.headers.get("x-priority")
                           or "interactive").strip().lower()
                    server_self.sched.admission.count_shed(
                        "session_pins", cls)
                    event({"error": {
                        "message": "shed: session_pins",
                        "type": "overloaded_error",
                        "retry_after_s": round(e.retry_after_s, 3)}})
                    self._end_frames()
                    return
                except Exception as e:
                    server_self.stats.record_error()
                    log_event(log, "sse invoke failed", error=str(e),
                              kind=type(e).__name__)
                    event({"error": {"message": str(e),
                                     "type": type(e).__name__}})
                    self._end_frames()
                    return
                eos = (final or {}).get("eos_id", internal.get("eos_id"))
                finish = ("stop" if eos is not None and eos in emitted
                          else "length")
                # the final event completes the text: the handler computes
                # the tail a delta-concatenating client still needs (it
                # knows exactly what the chunks carried); fall back to
                # completion-minus-sent for handlers without the field
                final_rec = final or {}
                if "text" in final_rec:
                    tail = final_rec["text"]
                else:
                    completion = final_rec.get("completion", "")
                    tail = (completion[len(text_sent):]
                            if completion.startswith(text_sent)
                            else completion)
                chunk_event([], text=tail, finish=finish)
                server_self.stats.record((time.monotonic() - t0) * 1e3)
                if event(b"[DONE]"):
                    self._end_frames()

            def _send_stream(self, stream_fn, request: dict, t0: float):
                """Chunked ndjson response: one JSON line per decode
                segment, so clients see tokens at time-to-first-segment
                instead of end-to-end latency. A mid-stream handler error
                becomes a final {"ok": false} line (headers are already
                on the wire — there is no 500 to send)."""
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def write_chunk(payload: dict) -> bool:
                    return self._write_frame(json.dumps(payload).encode() + b"\n")

                try:
                    for payload in stream_fn(request):
                        if not write_chunk(payload):
                            return
                except SessionPinsExceeded as e:
                    # headers are committed: the shed becomes the
                    # terminal line, shed-shaped and counted as a shed
                    # (not an error) like the non-streamed 503
                    cls = (self.headers.get("x-priority")
                           or "interactive").strip().lower()
                    server_self.sched.admission.count_shed(
                        "session_pins", cls)
                    write_chunk({"ok": False, "shed": True,
                                 "reason": "session_pins",
                                 "retry_after_s":
                                     round(e.retry_after_s, 3)})
                    self._end_frames()
                    return
                except Exception as e:
                    server_self.stats.record_error()
                    log_event(log, "stream invoke failed", error=str(e),
                              kind=type(e).__name__)
                    write_chunk({"ok": False, "error": str(e),
                                 "kind": type(e).__name__})
                    self._end_frames()
                    return
                server_self.stats.record((time.monotonic() - t0) * 1e3)
                self._end_frames()

        return Handler

    # -- lifecycle ----------------------------------------------------------

    def _engine_fault_state(self) -> dict:
        """O(1) snapshot of the continuous engine's fault layer (empty
        for handlers without one) — feeds /healthz and the admission
        gate, so it must never raise or take serving-path locks."""
        fn = getattr(self.boot.state, "engine_fault_fn", None)
        if fn is None:
            return {}
        try:
            return dict(fn())
        except Exception:  # noqa: BLE001 — health must never 500
            return {}

    def serve_forever(self):
        log_event(log, "serving", port=self.port, bundle=str(self.bundle_dir))
        self._httpd.serve_forever()

    def start_background(self):
        self._thread = threading.Thread(target=self.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self, *, drain_grace: float = 10.0):
        """Drain then stop: admission closes FIRST (new invokes get 503 +
        Retry-After from both the server gate and the scheduler), then
        in-flight AND already-queued invokes finish (handler threads are
        daemonic — without this wait a process exit would cut device work
        mid-dispatch)."""
        with self._inflight_lock:
            self.draining = True
        self.sched.drain()
        deadline = time.monotonic() + drain_grace
        while self._inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        self._httpd.shutdown()
        self._httpd.server_close()


def main(argv=None) -> int:
    """``python -m lambdipy_tpu.runtime.server <bundle_dir> [port]``"""
    import sys

    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("usage: server <bundle_dir> [port]", file=sys.stderr)
        return 2
    from lambdipy_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    bundle = Path(argv[0])
    port = int(argv[1]) if len(argv) > 1 else 0
    server = BundleServer(bundle, port=port)

    # SIGTERM = graceful drain (supervisor/controller stop path). stop()
    # must run off the serve_forever thread — shutdown() from inside the
    # serving thread deadlocks — so the handler hands it to a worker.
    def _term(signum, frame):
        threading.Thread(target=server.stop, daemon=True).start()

    signal.signal(signal.SIGTERM, _term)

    # readiness line on stdout: the deploy controller parses this
    print(json.dumps({"ready": True, "pid": os.getpid(), "port": server.port,
                      "cold_start": server.boot.stages}), flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
