"""Resolver + registry tests (SURVEY.md §5 rebuild test plan, items 1-2)."""

import pytest

from lambdipy_tpu.recipes import builtin_store
from lambdipy_tpu.resolve import (
    ResolutionError,
    parse_requirements_text,
    resolve_project,
    split_by_recipes,
)
from lambdipy_tpu.resolve.requirements import pin_against_local
from lambdipy_tpu.resolve.registry import ArtifactRegistry, RegistryError


def test_parse_requirements_basic():
    reqs = parse_requirements_text(
        "numpy==2.0.2\n# comment\n\nscipy>=1.0  # inline comment\nclick\n"
    )
    assert [r.name for r in reqs] == ["numpy", "scipy", "click"]
    assert reqs[0].specifier == "==2.0.2"
    assert reqs[2].specifier == ""


def test_parse_rejects_pip_options():
    with pytest.raises(ResolutionError, match="option lines"):
        parse_requirements_text("-r other.txt\n")


def test_parse_rejects_garbage():
    with pytest.raises(ResolutionError, match="invalid requirement"):
        parse_requirements_text("not a requirement!!!\n")


def test_pin_against_local_env():
    req = parse_requirements_text("numpy>=2.0\n")[0]
    pinned = pin_against_local(req)
    assert pinned.pinned is not None
    assert pinned.pin.startswith("numpy==")


def test_pin_conflict_raises():
    req = parse_requirements_text("numpy==0.0.1\n")[0]
    with pytest.raises(ResolutionError, match="cannot be satisfied"):
        pin_against_local(req)


def test_pin_missing_distribution_raises():
    req = parse_requirements_text("surely-not-installed-pkg\n")[0]
    with pytest.raises(ResolutionError, match="not available"):
        pin_against_local(req)


def test_split_by_recipes():
    store = builtin_store()
    reqs = parse_requirements_text("numpy==2.0.2\nclick>=8\n")
    res = split_by_recipes(reqs, store)
    assert [(r.name, name) for r, name in res.recipe_covered] == [("numpy", "numpy")]
    assert [r.name for r in res.plain] == ["click"]


def test_resolve_project_end_to_end(tmp_path):
    req_file = tmp_path / "requirements.txt"
    req_file.write_text("numpy>=2.0\nclick\n")
    res = resolve_project(req_file, builtin_store())
    (req, recipe_name), = res.recipe_covered
    assert recipe_name == "numpy" and req.pinned
    assert res.plain[0].pinned  # plain deps are pinned too


def test_registry_publish_fetch_roundtrip(tmp_registry, tmp_path):
    bundle = tmp_path / "bundle"
    (bundle / "site").mkdir(parents=True)
    (bundle / "site" / "mod.py").write_text("x = 1\n")
    tmp_registry.publish("pkg-1.0-py312-cpu", bundle, recipe="pkg",
                         version="1.0", device="cpu", manifest={"k": "v"})
    assert tmp_registry.has("pkg-1.0-py312-cpu")
    fetched = tmp_registry.fetch("pkg-1.0-py312-cpu")
    assert (fetched / "site" / "mod.py").read_text() == "x = 1\n"
    infos = tmp_registry.list()
    assert infos[0].recipe == "pkg" and infos[0].size_bytes > 0


def test_registry_fetch_missing_raises(tmp_registry):
    with pytest.raises(RegistryError):
        tmp_registry.fetch("nope")


def test_registry_delete(tmp_registry, tmp_path):
    bundle = tmp_path / "b"
    bundle.mkdir()
    (bundle / "f").write_text("x")
    tmp_registry.publish("a-1", bundle, recipe="a", version="1", device="cpu")
    tmp_registry.delete("a-1")
    assert not tmp_registry.has("a-1")
    assert tmp_registry.list() == []


# -- Pipfile / Pipfile.lock / pyproject manifests ----------------------------


def test_parse_pipfile():
    from lambdipy_tpu.resolve.requirements import parse_pipfile_text

    text = (
        '[[source]]\nurl = "https://pypi.org/simple"\n\n'
        "[packages]\n"
        'numpy = "==2.0.2"\n'
        'click = "*"\n'
        'requests = {version = ">=2.0", extras = ["socks"]}\n\n'
        "[dev-packages]\n"
        'pytest = "*"\n'
    )
    reqs = parse_pipfile_text(text)
    assert [r.name for r in reqs] == ["numpy", "click", "requests"]
    assert reqs[0].specifier == "==2.0.2" and reqs[1].specifier == ""
    dev = parse_pipfile_text(text, dev=True)
    assert [r.name for r in dev] == ["numpy", "click", "requests", "pytest"]


def test_parse_pipfile_rejects_vcs_entry():
    from lambdipy_tpu.resolve.requirements import parse_pipfile_text

    with pytest.raises(ResolutionError, match="git"):
        parse_pipfile_text('[packages]\nfoo = {git = "https://x/y.git"}\n')


def test_parse_pipfile_lock():
    import json

    from lambdipy_tpu.resolve.requirements import parse_pipfile_lock_text

    doc = {
        "default": {"numpy": {"version": "==2.0.2", "hashes": []},
                    "click": {"version": "==8.4.2"}},
        "develop": {"pytest": {"version": "==8.0.0"}},
    }
    reqs = parse_pipfile_lock_text(json.dumps(doc))
    assert {(r.name, r.specifier) for r in reqs} == {
        ("numpy", "==2.0.2"), ("click", "==8.4.2")}
    dev = parse_pipfile_lock_text(json.dumps(doc), dev=True)
    assert any(r.name == "pytest" for r in dev)
    with pytest.raises(ResolutionError, match="missing pinned version"):
        parse_pipfile_lock_text(json.dumps({"default": {"x": {}}}))


def test_resolve_project_pipfile_and_pyproject(tmp_path):
    pipfile = tmp_path / "Pipfile"
    pipfile.write_text('[packages]\nnumpy = ">=2.0"\nclick = "*"\n')
    res = resolve_project(pipfile, builtin_store())
    assert [name for _, name in res.recipe_covered] == ["numpy"]
    assert res.plain[0].name == "click" and res.plain[0].pinned

    pyproject = tmp_path / "pyproject.toml"
    pyproject.write_text(
        '[project]\nname = "demo"\nversion = "0"\n'
        'dependencies = ["numpy>=2.0", "click; python_version >= \'3.8\'", '
        '"definitely-missing; python_version < \'3\'"]\n')
    res = resolve_project(pyproject, builtin_store())
    assert [name for _, name in res.recipe_covered] == ["numpy"]
    # the false-marker dep is dropped, not a resolution error
    assert [r.name for r in res.plain] == ["click"]


def test_pipfile_lock_other_platform_marker_dropped(tmp_path):
    import json

    lock = tmp_path / "Pipfile.lock"
    lock.write_text(json.dumps({
        "default": {
            "numpy": {"version": "==2.0.2"},
            "colorama": {"version": "==0.4.6",
                         "markers": "sys_platform == 'win32'"},
        }}))
    res = resolve_project(lock, builtin_store())
    names = [name for _, name in res.recipe_covered] + [r.name for r in res.plain]
    assert "numpy" in names and "colorama" not in names
