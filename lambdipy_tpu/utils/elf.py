"""Minimal ELF inspection for the prune pass — no external deps.

Why this exists: ``strip --strip-unneeded`` corrupts some manylinux-built
shared objects (observed live on numpy's bundled
``libscipy_openblas64_.so``: post-strip the dynamic loader rejects it with
"ELF load command address/offset not page-aligned"). Those wheels are
post-processed by auditwheel/patchelf and carry LOAD segments whose
offset/vaddr congruence binutils strip does not preserve. The prune pass
therefore (a) only strips objects that actually have strippable sections,
and (b) validates LOAD alignment after stripping, restoring the original
bytes when strip broke it. This is the concrete form of SURVEY.md §9
hard-part #2 ("one wrong rm/strip breaks imports in ways only the
fresh-venv smoke catches").
"""

from __future__ import annotations

import struct
from pathlib import Path

_ELF_MAGIC = b"\x7fELF"
_PT_LOAD = 1


def is_elf(path: Path) -> bool:
    try:
        with open(path, "rb") as f:
            return f.read(4) == _ELF_MAGIC
    except OSError:
        return False


def _read_header(f) -> dict | None:
    ident = f.read(16)
    if len(ident) < 16 or ident[:4] != _ELF_MAGIC:
        return None
    if ident[4] != 2 or ident[5] != 1:  # only ELF64 little-endian (TPU VMs are x86-64/arm64 LE)
        return None
    rest = f.read(48)
    if len(rest) < 48:
        return None
    (e_type, e_machine, e_version, e_entry, e_phoff, e_shoff, e_flags,
     e_ehsize, e_phentsize, e_phnum, e_shentsize, e_shnum, e_shstrndx) = struct.unpack(
        "<HHIQQQIHHHHHH", rest)
    return {
        "phoff": e_phoff, "phentsize": e_phentsize, "phnum": e_phnum,
        "shoff": e_shoff, "shentsize": e_shentsize, "shnum": e_shnum,
        "shstrndx": e_shstrndx,
    }


def load_segments_aligned(path: Path) -> bool:
    """True when every PT_LOAD segment satisfies p_offset ≡ p_vaddr
    (mod p_align) — the invariant the dynamic loader enforces."""
    with open(path, "rb") as f:
        hdr = _read_header(f)
        if hdr is None:
            return True  # not inspectable -> don't block
        f.seek(hdr["phoff"])
        for _ in range(hdr["phnum"]):
            ent = f.read(hdr["phentsize"])
            if len(ent) < 56:
                return True
            p_type, _flags, p_offset, p_vaddr = struct.unpack("<IIQQ", ent[:24])
            p_align = struct.unpack("<Q", ent[48:56])[0]
            if p_type == _PT_LOAD and p_align > 1:
                if (p_offset % p_align) != (p_vaddr % p_align):
                    return False
    return True


def strippable_sections(path: Path) -> list[str]:
    """Names of .symtab/.debug* sections present — empty means stripping
    would save nothing (manylinux wheels ship pre-stripped)."""
    with open(path, "rb") as f:
        hdr = _read_header(f)
        if hdr is None or hdr["shnum"] == 0:
            return []
        f.seek(hdr["shoff"])
        raw = f.read(hdr["shentsize"] * hdr["shnum"])
        entries = []
        for i in range(hdr["shnum"]):
            ent = raw[i * hdr["shentsize"]:(i + 1) * hdr["shentsize"]]
            if len(ent) < 64:
                return []
            sh_name, _sh_type = struct.unpack("<II", ent[:8])
            sh_offset, sh_size = struct.unpack("<QQ", ent[24:40])
            entries.append((sh_name, sh_offset, sh_size))
        # section name string table
        strndx = hdr["shstrndx"]
        if strndx >= len(entries):
            return []
        str_off, str_size = entries[strndx][1], entries[strndx][2]
        f.seek(str_off)
        strtab = f.read(str_size)
        out = []
        for sh_name, _, _ in entries:
            end = strtab.find(b"\0", sh_name)
            name = strtab[sh_name:end if end >= 0 else None].decode("latin1")
            if name == ".symtab" or name.startswith(".debug"):
                out.append(name)
        return out
