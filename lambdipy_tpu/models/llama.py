"""Llama-3-style decoder-only LM: GQA + RoPE + RMSNorm + SwiGLU, with int8
weight-only quantization and a functional KV cache for ``lax.scan`` decode.

BASELINE.json config 5: Llama-3-8B int8 generate on v5e-4, weights tensor-
parallel over the ``tp`` mesh axis (sharding rules in
:func:`llama_tp_rules`; the module itself is sharding-agnostic).

TPU-first choices:
- decode loop is ``lax.scan`` over a static-shape KV cache
  (``dynamic_update_slice`` at the position index) — no Python control flow
  under jit, one compiled step reused for every token;
- int8 weight-only quant: weights stored int8 + per-output-channel fp32
  scale, dequantized into bf16 at the matmul (HBM-bandwidth win: 8B params
  fit v5e-4's 64 GB HBM with room for cache);
- fp32 RMSNorm/softmax accumulation, bf16 MXU matmuls.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden: int = 4096
    layers: int = 32
    heads: int = 32
    kv_heads: int = 8
    mlp: int = 14336
    max_len: int = 8192
    rope_theta: float = 500000.0
    # RoPE frequency scaling for long-context checkpoints, as a hashable
    # tuple (the config is a flax module attribute): None,
    # ("linear", factor), or ("llama3", factor, low_freq_factor,
    # high_freq_factor, original_max_position_embeddings) — the Llama-3.1+
    # scheme. Populated from HF configs by models/convert.py.
    rope_scaling: tuple | None = None
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    quant: str | None = None  # None | "int8"
    # KV-cache quantization: None (cache in ``dtype``) or "int8"
    # (per-token-per-head symmetric int8 + f32 scale). The decode cache is
    # the dominant HBM object of long-context serving (8B at 8k context:
    # 1 GB/row in bf16) and decode re-reads all of it every step — int8
    # halves that traffic and capacity for ~0.4% attention error; XLA
    # fuses the dequant into the attention einsum.
    kv_quant: str | None = None
    # Attention backend: "dense" (XLA-fused, default), "flash" (Pallas
    # kernel when shapes tile), "blocked" (length-aware blocked DECODE
    # attention, ops/decode_attention.py: single-token decode steps read
    # KV bytes proportional to each row's actual context instead of the
    # full static window — per-row active_len early exit on the TPU
    # kernel, dense-bitwise pure-jax reference elsewhere; prefill and
    # multi-token chunks stay dense, sharded/sp decode stands down to
    # the existing path), or "ring" — the LONG-CONTEXT pair:
    # sequence-parallel ring attention for prefill AND sequence-sharded
    # flash-decoding for decode steps over the ambient mesh's sp axis
    # (parallel/ring.py + parallel/spdecode.py; the KV cache never
    # gathers, per-step collectives are O(b*h*d)). Defaults measured,
    # not assumed: docs/kernels.md — XLA dense wins at <=4k context on
    # v5e; flash is the O(S)-memory fallback for contexts whose dense
    # score tensor would not fit.
    attn_backend: str = "dense"
    # Sparse MoE FFN (Mixtral-style): >0 replaces the dense SwiGLU with
    # moe_experts top-k routed experts (models/moe.py), expert dim sharded
    # over the mesh's ep axis.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_group_size: int = 256  # routing-group size (models/moe.py)
    # int8 matmul backend: "xla" (dequant fused by XLA, works under TP
    # sharding) or "pallas" (ops/quant.py blocked kernel — single-chip
    # serving; falls back per-matmul when shapes don't tile). Measured
    # head-to-head at 8B shapes (docs/kernels.md): XLA's fused dequant
    # runs at 390-710 GB/s effective weight bandwidth vs the kernel's
    # ~65, and the full 8B decode sits at 82% of the int8 roofline — the
    # default follows the data.
    matmul_backend: str = "xla"

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads


LLAMA3_8B = LlamaConfig()
LLAMA_TINY = LlamaConfig(vocab_size=512, hidden=64, layers=2, heads=4,
                         kv_heads=2, mlp=128, max_len=128, dtype=jnp.float32)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        dtype = x.dtype
        x32 = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (y * scale).astype(dtype)


class QDense(nn.Module):
    """Linear layer with optional int8 weight-only quantization.

    quant=None: a plain bf16 kernel. quant="int8": kernel stored as int8
    with per-output-channel fp32 scales; dequantized at the matmul so HBM
    traffic (the serving bottleneck) is 1 byte/param while the MXU still
    sees bf16.
    """

    features: int
    quant: str | None = None
    dtype: Any = jnp.bfloat16
    backend: str = "xla"  # "xla" | "pallas" (int8 only, unsharded)

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        if self.quant == "int8":
            def init_int8(key, shape, _dtype):
                w = nn.initializers.lecun_normal()(key, shape, jnp.float32)
                scale = jnp.max(jnp.abs(w), axis=0, keepdims=True) / 127.0
                return jnp.round(w / jnp.maximum(scale, 1e-8)).astype(jnp.int8)

            w_i8 = self.param("kernel_int8", init_int8,
                              (in_features, self.features), jnp.int8)
            # random-init scale approximates lecun magnitude; real weights
            # come through quantize_params() which computes true scales
            scale = self.param(
                "scale", nn.initializers.constant(1.0 / (127.0 * in_features ** 0.5)),
                (1, self.features), jnp.float32)
            if self.backend == "pallas":
                from lambdipy_tpu.ops.quant import int8_matmul
                from lambdipy_tpu.parallel.mesh import current_mesh

                # the blocked kernel is a manual (unpartitioned) op: only
                # take it when no mesh is ambient (single-chip serving)
                if current_mesh() is None:
                    flat = x.astype(self.dtype).reshape(-1, in_features)
                    out = int8_matmul(flat, w_i8, scale)
                    return out.reshape(*x.shape[:-1], self.features)
            w = w_i8.astype(self.dtype) * scale.astype(self.dtype)
        else:
            w = self.param("kernel", nn.initializers.lecun_normal(),
                           (in_features, self.features), self.dtype)
        return x.astype(self.dtype) @ w


def _scaled_rope_freqs(freqs, scaling):
    """Apply RoPE frequency scaling (inverse frequencies in, out).

    "llama3" is the Llama-3.1 scheme: low-frequency (long-wavelength)
    components are slowed by ``factor``, high-frequency ones kept, with a
    smooth ramp between the two wavelength thresholds derived from the
    original context length."""
    if scaling is None:
        return freqs
    kind = scaling[0]
    if kind == "linear":
        return freqs / jnp.float32(scaling[1])
    if kind == "llama3":
        factor, low_f, high_f, orig = map(float, scaling[1:])
        wavelen = 2.0 * jnp.pi / freqs
        smooth = (orig / wavelen - low_f) / (high_f - low_f)
        mid = (1.0 - smooth) * freqs / factor + smooth * freqs
        return jnp.where(wavelen > orig / low_f, freqs / factor,
                         jnp.where(wavelen < orig / high_f, freqs, mid))
    raise ValueError(f"unsupported rope scaling kind {kind!r}")


def rope(q, k, positions, theta: float, scaling: tuple | None = None):
    """Rotary position embeddings, fp32 trig, applied per head-dim pair."""
    head_dim = q.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    freqs = _scaled_rope_freqs(freqs, scaling)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b, s, hd/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]

    def rot(x):
        x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                               axis=-1).astype(x.dtype)

    return rot(q), rot(k)


def _kv_quantize(x):
    """[..., d] float -> (int8 values, f32 scale [..., 1]) per-vector
    symmetric quantization (one scale per token per kv-head)."""
    x32 = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(x32), axis=-1, keepdims=True) / 127.0,
                        1e-8)
    return jnp.round(x32 / scale).astype(jnp.int8), scale


def _kv_dequantize(q_i8, scale, dtype):
    return q_i8.astype(dtype) * scale.astype(dtype)


def cache_width(cache) -> int:
    """Sequence capacity of a decode/prefix cache (float or int8
    layout) — the ONE layout probe shared by the server's bucket math
    and the continuous engine's pack gate."""
    entry = cache[0]
    leaf = entry.get("k", entry.get("k_int8"))
    return leaf.shape[1]


def _kv_store(cfg, k, v) -> dict:
    """This step's (or chunk's) K/V in the cache's storage layout: the
    float leaves, or int8 values + scales under ``cfg.kv_quant``. The
    ONE place the layout is built — the dense decode path, the sp
    decode path, and prefill embedding all consume it."""
    if cfg.kv_quant == "int8":
        k_q, k_s = _kv_quantize(k)
        v_q, v_s = _kv_quantize(v)
        return {"k_int8": k_q, "k_scale": k_s,
                "v_int8": v_q, "v_scale": v_s}
    return {"k": k.astype(cfg.dtype), "v": v.astype(cfg.dtype)}


def _active_sp_mesh():
    """The ambient mesh when sequence parallelism is usable: an ``sp``
    axis > 1 and not inside a manual (shard_map / pipeline-stage) region
    where a nested whole-mesh shard_map cannot trace. The ONE gate
    shared by ring prefill and sp decode — they must agree, or prefill
    would shard what decode then replicates."""
    from lambdipy_tpu.parallel.mesh import current_mesh
    from lambdipy_tpu.parallel.sharding import shard_hints_suppressed

    mesh = current_mesh()
    if (mesh is not None and mesh.shape.get("sp", 1) > 1
            and not shard_hints_suppressed()):
        return mesh
    return None


def resolve_sp_prefill(mode: str, mesh) -> int:
    """Resolve the usable whole-prompt sp-prefill factor for
    ``prefill_mode``: 0 under ``chunked``; under ``sp`` the mesh's
    sp-axis size when >= 2, else 0 with a counted stand-down
    (``sp_prefill_without_sp_mesh``) — the ``spec_k_under_sp_mesh``
    idiom: the operator's ask is impossible on this mesh, so the serial
    path runs and the condition is visible on /metrics, never silent."""
    if mode != "sp":
        return 0
    sp = int(mesh.shape.get("sp", 1)) if mesh is not None else 1
    if sp >= 2:
        return sp
    from lambdipy_tpu.parallel.spdecode import note_standdown

    note_standdown("sp_prefill_without_sp_mesh")
    return 0


def _attend(q, k, v, mask):
    """Grouped-query attention core. q: [b,s,h,d]; k/v: [b,t,kvh,d].

    The shard_hints pin ONE layout through softmax and its jvp/transpose —
    batch over dp, kv-heads over tp, query seq over sp, key seq gathered
    (replicated over sp) — so the SPMD partitioner never falls back to
    involuntary full rematerialization bouncing between dp- and sp-sharded
    logits (ring attention is the layout that never gathers k/v)."""
    from lambdipy_tpu.parallel.sharding import shard_hint

    b, s, h, d = q.shape
    kvh = k.shape[2]
    group = h // kvh
    q = shard_hint(q.reshape(b, s, kvh, group, d), "dp", "sp", "tp")
    k = shard_hint(k, "dp", None, "tp")
    v = shard_hint(v, "dp", None, "tp")
    logits = jnp.einsum("bskgd,btkd->bkgst", q, k).astype(jnp.float32)
    logits = shard_hint(logits / jnp.sqrt(d).astype(jnp.float32),
                        "dp", "tp", None, "sp", None)
    logits = jnp.where(mask[:, None, None, :, :], logits, jnp.float32(-1e9))
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    probs = shard_hint(probs, "dp", "tp", None, "sp", None)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return shard_hint(out.reshape(b, s, h, d), "dp", "sp", "tp")


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    def _prefill_attend(self, q, k, v, mask, sp_prefill: int = 0):
        """Causal prefill attention via the configured backend.

        ``sp_prefill >= 2`` requests the whole-prompt sequence-parallel
        tier regardless of the configured backend: the first chunk of an
        sp-prefill program ring-shards the full prompt's attention over
        the sp axis. Falls through to the configured backend when no
        usable sp mesh exists (the caller counts the stand-down)."""
        cfg = self.cfg
        s = q.shape[1]
        backend = cfg.attn_backend
        if backend == "ring" or sp_prefill >= 2:
            from lambdipy_tpu.parallel.ring import ring_attention

            mesh = _active_sp_mesh()
            if mesh is not None:
                # sequence-parallel long-context path; the padding mask is
                # threaded as the ring's key-validity mask, so padded
                # batches match the dense backend exactly
                return ring_attention(q, k, v, mesh, causal=True,
                                      kv_mask=mask)
            backend = cfg.attn_backend if backend != "ring" else "dense"
        if backend == "flash":
            from lambdipy_tpu.ops.attention import flash_attention

            return flash_attention(q, k, v, causal=True)
        causal = jnp.tril(jnp.ones((s, s), dtype=jnp.bool_))
        attn_mask = mask[:, None, :] & causal[None, :, :]
        return _attend(q, k, v, attn_mask)

    @nn.compact
    def __call__(self, x, positions, mask, cache, sp_prefill: int = 0,
                 band: int = 0):
        """cache: None (prefill over full x) or dict(k, v, index) for decode.
        Returns (y, new_cache_entry).

        sp_prefill: static int — when >= 2, this is a whole-prompt
        sequence-parallel prefill program: the no-cache branch
        ring-shards the prompt's attention, the scalar-index
        continuation branch (s > 1) shards the chunk's queries over the
        sp axis (:func:`sp_chunk_attention`). 0 keeps every existing
        program byte-identical.
        band: static int — when > 0, restrict each scalar-index query at
        cache position p to keys in [max(0, (p//band - 1)*band), p]: the
        long-context SLIDING-WINDOW band, so one multi-chunk sp round
        attends exactly what the serial window/2 slide schedule would
        have exposed chunk by chunk."""
        cfg = self.cfg
        d = cfg.head_dim
        h = RMSNorm(cfg.norm_eps, name="attn_norm")(x)
        b, s, _ = h.shape
        q = QDense(cfg.heads * d, cfg.quant, cfg.dtype, cfg.matmul_backend, name="q_proj")(h)
        k = QDense(cfg.kv_heads * d, cfg.quant, cfg.dtype, cfg.matmul_backend, name="k_proj")(h)
        v = QDense(cfg.kv_heads * d, cfg.quant, cfg.dtype, cfg.matmul_backend, name="v_proj")(h)
        q = q.reshape(b, s, cfg.heads, d)
        k = k.reshape(b, s, cfg.kv_heads, d)
        v = v.reshape(b, s, cfg.kv_heads, d)
        q, k = rope(q, k, positions, cfg.rope_theta, cfg.rope_scaling)

        if cache is None:
            out = self._prefill_attend(q, k, v, mask, sp_prefill)
            new_cache = {"k": k, "v": v}
        else:
            from lambdipy_tpu.parallel.sharding import shard_hint

            # decode: append this step's k/v at cache index, attend over
            # prefix. The cache stays kv-head-sharded over tp across the
            # scan — the dominant serving HBM object must never be
            # gathered per step
            idx = cache["index"]  # int32 scalar, or [b] per-row positions
            # sequence-parallel decode (attn_backend="ring" + an sp
            # mesh): the cache seq dim stays SHARDED over sp for the
            # whole scan and each step combines per-shard online-softmax
            # partials with O(b*h*d) collectives — the long-context
            # decode path, pairing with ring-attention prefill
            # (parallel/spdecode.py). Composes with kv_quant: the int8
            # cache leaves shard the same way and the per-shard dequant
            # fuses into the local attention einsum.
            sp_done = False
            if jnp.ndim(idx) != 0 and cfg.attn_backend == "ring":
                sp_mesh = _active_sp_mesh()
                if sp_mesh is not None and s == 1:
                    from lambdipy_tpu.parallel.spdecode import (
                        sp_decode_step)

                    sp_new = _kv_store(cfg, k, v)
                    sp_cache = {name: cache[name] for name in sp_new}
                    out, new_cache = sp_decode_step(
                        q, sp_new, sp_cache, idx, sp_mesh)
                    sp_done = True
                elif sp_mesh is not None:
                    # a multi-token verify chunk under the ring backend:
                    # sp decode is a one-token-step formulation, so the
                    # chunk runs the replicated dense path — observable,
                    # not silent (ROADMAP direction-2 note)
                    from lambdipy_tpu.parallel.spdecode import (
                        note_standdown)

                    note_standdown("multi_token_chunk")
            elif jnp.ndim(idx) != 0 and _active_sp_mesh() is not None:
                # the mesh HAS an sp axis but the configured backend
                # (blocked/dense/flash) routes decode around sp_decode:
                # the cache this step reads is replicated despite the
                # sharding the operator asked for. Count + log once per
                # reason so the condition is visible on /metrics.
                from lambdipy_tpu.parallel.spdecode import note_standdown

                note_standdown(f"attn_backend={cfg.attn_backend}")
            if not sp_done:
                # quantize this chunk's k/v once under kv_quant; the
                # cache stays int8 in HBM and the dequant fuses into
                # the attention einsum
                store = _kv_store(cfg, k, v)
                new_cache = {}
                if jnp.ndim(idx) == 0:
                    for name, val in store.items():
                        new_cache[name] = jax.lax.dynamic_update_slice(
                            cache[name], val, (0, idx, 0, 0))
                    # chunk query j attends keys <= idx + j — causal
                    # within the chunk, everything before it. s == 1 is
                    # the familiar decode-step mask; s > 1 is a
                    # multi-token continuation chunk (prefix-cache
                    # suffix prefill).
                    t = new_cache[next(iter(store))].shape[1]
                    valid = (jnp.arange(t)[None, None, :]
                             <= (idx + jnp.arange(s))[None, :, None])
                    if band:
                        # long-context sliding band: query at cache
                        # position p sees keys from the start of the
                        # PREVIOUS band block — exactly the window the
                        # serial window/2 slide schedule leaves resident
                        # when p's chunk runs
                        qpos = idx + jnp.arange(s)
                        band_start = jnp.maximum(
                            0, (qpos // band - 1) * band)
                        valid = valid & (jnp.arange(t)[None, None, :]
                                         >= band_start[None, :, None])
                else:
                    # ragged batch (rows decode from different prompt
                    # lengths): per-row scatter of this step's (or
                    # chunk's) positions. s == 1 is the familiar decode
                    # step; s > 1 is a SPECULATIVE VERIFY CHUNK — row
                    # r's chunk lands at idx[r]..idx[r]+s-1 and query j
                    # attends keys <= idx[r]+j (causal within the
                    # chunk). Out-of-bounds scatter indices DROP (jax
                    # .at[] default), which is exactly the engine's
                    # over-decode/rollback contract: a rejected tail or
                    # past-the-window write lands nowhere a kept token
                    # can read.
                    rows = jnp.arange(b)
                    cols = idx[:, None] + jnp.arange(s)[None, :]  # [b, s]
                    for name, val in store.items():
                        new_cache[name] = cache[name].at[
                            rows[:, None], cols].set(val)
                    t = new_cache[next(iter(store))].shape[1]
                    valid = (jnp.arange(t)[None, None, :]
                             <= cols[:, :, None])  # [b, s, t]
                new_cache = {name: shard_hint(val, "dp", None, "tp")
                             for name, val in new_cache.items()}
                # length-aware blocked decode attention: one-token steps
                # read each row's ACTIVE window instead of the full
                # static cache (bytes scale with context actually held).
                # Manual (unpartitioned) op like QDense's pallas backend:
                # only taken with no ambient mesh; the valid mask built
                # above is exactly "position < index + 1", so active_len
                # = idx + 1 reproduces it row for row.
                blocked = False
                if cfg.attn_backend == "blocked" and s == 1:
                    from lambdipy_tpu.ops.decode_attention import (
                        decode_attention)
                    from lambdipy_tpu.parallel.mesh import current_mesh

                    if current_mesh() is None:
                        active = jnp.broadcast_to(
                            jnp.asarray(idx, jnp.int32) + 1, (b,))
                        if cfg.kv_quant == "int8":
                            out = decode_attention(
                                q, new_cache["k_int8"],
                                new_cache["v_int8"], active,
                                k_scale=new_cache["k_scale"],
                                v_scale=new_cache["v_scale"])
                        else:
                            out = decode_attention(
                                q, new_cache["k"], new_cache["v"], active)
                        blocked = True
                if not blocked:
                    if cfg.kv_quant == "int8":
                        ck = _kv_dequantize(new_cache["k_int8"],
                                            new_cache["k_scale"], cfg.dtype)
                        cv = _kv_dequantize(new_cache["v_int8"],
                                            new_cache["v_scale"], cfg.dtype)
                    else:
                        ck, cv = new_cache["k"], new_cache["v"]
                    attn_mask = jnp.broadcast_to(valid, (b, s, t))
                    sp_mesh = (_active_sp_mesh()
                               if (sp_prefill >= 2 and s > 1
                                   and jnp.ndim(idx) == 0
                                   and s % sp_prefill == 0) else None)
                    if sp_mesh is not None:
                        # sp-prefill continuation chunk: queries shard
                        # over sp, the cache stays replicated (as decode
                        # keeps it) — score memory and the softmax walk
                        # split across the mesh, no per-layer collective
                        from lambdipy_tpu.parallel.ring import (
                            sp_chunk_attention)

                        out = sp_chunk_attention(q, ck, cv, attn_mask,
                                                 sp_mesh)
                    else:
                        out = _attend(q, ck, cv, attn_mask)

        out = out.reshape(b, s, cfg.heads * d)
        x = x + QDense(cfg.hidden, cfg.quant, cfg.dtype, cfg.matmul_backend, name="o_proj")(out)

        h = RMSNorm(cfg.norm_eps, name="mlp_norm")(x)
        if cfg.moe_experts:
            from lambdipy_tpu.models.moe import MoEMLP

            x = x + MoEMLP(cfg.moe_experts, cfg.mlp, cfg.moe_top_k,
                           cfg.moe_capacity_factor, cfg.dtype, cfg.quant,
                           group_size=cfg.moe_group_size, name="moe")(h)
        else:
            gate = QDense(cfg.mlp, cfg.quant, cfg.dtype, cfg.matmul_backend, name="gate_proj")(h)
            up = QDense(cfg.mlp, cfg.quant, cfg.dtype, cfg.matmul_backend, name="up_proj")(h)
            x = x + QDense(cfg.hidden, cfg.quant, cfg.dtype, cfg.matmul_backend, name="down_proj")(
                nn.silu(gate) * up)
        return x, new_cache


class LlamaModel(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, positions=None, mask=None, cache=None,
                 logit_positions=None, exit_layer=None, sp_prefill=0,
                 band=0):
        """Returns (logits, new_cache).

        prefill: cache=None, tokens [b, s] -> cache entries sized s.
        decode:  cache=list of {k,v,index} (static max_len), tokens [b, 1].
        logit_positions: optional [b] int32 — compute lm_head only at that
        position per row (logits [b, 1, v]). Serving prefill needs one
        row of logits, not s: the full [b, s, vocab] f32 tensor is the
        largest activation of the whole serve path (8B at 8k context:
        4 GB) and s unneeded lm_head matmuls.
        exit_layer: optional int — a SHALLOW-EXIT forward: run only
        layers 0..exit_layer-1, then final_norm + the TIED lm_head over
        that early hidden state (the self-drafting head for the
        speculative draft tier). Params for the skipped layers are
        simply never looked up, so the same param tree serves both
        depths; ``cache`` (when given) holds one entry per RUN layer.
        """
        cfg = self.cfg
        n_layers = (cfg.layers if exit_layer is None
                    else max(1, min(int(exit_layer), cfg.layers)))
        b, s = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        if mask is None:
            mask = jnp.ones((b, s), dtype=jnp.bool_)
        emb = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                       param_dtype=cfg.dtype, name="embed")
        x = emb(tokens)
        new_cache = []
        for i in range(n_layers):
            layer_cache = None if cache is None else cache[i]
            x, c = LlamaBlock(cfg, name=f"layer_{i}")(
                x, positions, mask, layer_cache, sp_prefill=sp_prefill,
                band=band)
            new_cache.append(c)
        x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
        if logit_positions is not None:
            x = jnp.take_along_axis(
                x, jnp.broadcast_to(logit_positions[:, None, None],
                                    (b, 1, x.shape[-1])), axis=1)
        logits = QDense(cfg.vocab_size, cfg.quant, jnp.float32, cfg.matmul_backend, name="lm_head")(x)
        return logits, new_cache


def _empty_cache_entry(cfg: LlamaConfig, batch: int, max_len: int) -> dict:
    shape = (batch, max_len, cfg.kv_heads, cfg.head_dim)
    if cfg.kv_quant == "int8":
        return {"k_int8": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.full(shape[:3] + (1,), 1e-8, jnp.float32),
                "v_int8": jnp.zeros(shape, jnp.int8),
                "v_scale": jnp.full(shape[:3] + (1,), 1e-8, jnp.float32)}
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


def init_decode_cache(cfg: LlamaConfig, batch: int, max_len: int):
    """Static-shape KV cache for decode (one entry per layer)."""
    return [{**_empty_cache_entry(cfg, batch, max_len), "index": jnp.int32(0)}
            for _ in range(cfg.layers)]


# The ONE KV-cache layout rule for tensor-parallel serving: every
# store-layout leaf is [..., seq, kv_heads, d-or-1], so the kv-head dim
# (axis 2 for both the [b, t, kvh, *] decode cache and the
# [n_pages, page, kvh, *] arena) shards over ``tp`` and everything else
# replicates. Matches the in-program ``shard_hint(..., "dp", None,
# "tp")`` the decode write path pins, so host-placed caches and
# program-produced caches agree on layout — per-device KV HBM drops
# ~1/tp and XLA never round-trips the cache through a gather.
def _kv_leaf_sharding(mesh, ndim: int):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from lambdipy_tpu.parallel.sharding import _filter_spec

    return NamedSharding(mesh, _filter_spec(P(None, None, "tp"), mesh, ndim))


def shard_kv_cache(cache, mesh):
    """Place a host-built decode cache (list of per-layer dicts, as
    :func:`init_decode_cache` / :func:`concat_cache_blocks` return) on
    ``mesh``: KV leaves kv-head-sharded over ``tp``, ``index`` leaves
    replicated. A mesh without a ``tp`` axis places everything
    replicated — the 1-device degenerate mesh is an exact no-op."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    return [{name: jax.device_put(
                 val, rep if name == "index"
                 else _kv_leaf_sharding(mesh, val.ndim))
             for name, val in entry.items()}
            for entry in cache]


def shard_page_arena(arena, mesh):
    """Place a paged KV arena (:func:`init_page_arena`) on ``mesh`` —
    same kv-head-over-``tp`` rule as :func:`shard_kv_cache`, applied to
    the ``[n_pages, page, kv_heads, *]`` leaves."""
    return [{name: jax.device_put(val, _kv_leaf_sharding(mesh, val.ndim))
             for name, val in entry.items()}
            for entry in arena]


def validate_serving_mesh(cfg: LlamaConfig, mesh) -> None:
    """Reject serving meshes the TP layout cannot honor. ``shard_hint``
    silently DROPS an axis that does not divide the dim it would split —
    correct for a training forward, but a serving bundle that declared
    ``tp=8`` over 4 kv heads would then pay an 8-chip mesh to replicate
    its dominant HBM object. Raise loudly instead."""
    shape = dict(getattr(mesh, "shape", {}) or {})
    tp = int(shape.get("tp", 1))
    if tp <= 1:
        return
    bad = []
    if cfg.kv_heads % tp:
        bad.append(f"kv_heads={cfg.kv_heads}")
    if cfg.heads % tp:
        bad.append(f"heads={cfg.heads}")
    if cfg.mlp % tp:
        bad.append(f"mlp={cfg.mlp}")
    if bad:
        raise ValueError(
            f"mesh tp={tp} does not divide {', '.join(bad)}: the "
            "tensor-parallel layout shards attention heads and the MLP "
            "hidden dim over tp, and the KV cache over kv_heads — pick "
            "a tp that divides all three (or drop the mesh)")


def slice_cache_blocks(cache, start: int, width: int):
    """Store-layout ``[start, start + width)`` sequence slices of a decode
    cache, one dict per layer (``index`` dropped) — the block-granular
    unit the radix prefix store (runtime/prefixstore.py) keeps. Slices
    are fresh buffers, so they stay valid when the source cache is later
    donated to an extension program."""
    return [{name: jax.lax.dynamic_slice_in_dim(val, start, width, 1)
             for name, val in entry.items() if name != "index"}
            for entry in cache]


def concat_cache_blocks(cfg: LlamaConfig, blocks, cache_len: int):
    """Assemble per-layer block slices (as :func:`slice_cache_blocks`
    returns, one list entry per block, in sequence order) back into a
    full ``cache_len`` decode cache with ``index`` = total assembled
    width — the inverse of slicing at block boundaries. KV values are
    position-dependent (RoPE is applied before the cache store), so the
    caller must place blocks at the absolute positions they were sliced
    from; a radix path does that by construction."""
    from lambdipy_tpu.parallel.mesh import current_mesh

    total = sum(next(iter(b[0].values())).shape[1] for b in blocks)
    # sharding-preserving under an ambient tp mesh: the assembled
    # full-window buffer is the big allocation here — place the fresh
    # dest kv-head-sharded BEFORE the updates, so the eager
    # dynamic_update_slice of (tp-sharded) block slices never gathers
    # and the registered cache costs 1/tp per device like its sources
    mesh = current_mesh()
    shard = (mesh is not None and mesh.shape.get("tp", 1) > 1)
    out = []
    for i in range(cfg.layers):
        dest = _empty_cache_entry(cfg, 1, cache_len)
        if shard:
            dest = {name: jax.device_put(
                        val, _kv_leaf_sharding(mesh, val.ndim))
                    for name, val in dest.items()}
        for name in blocks[0][i]:
            merged = jnp.concatenate([b[i][name] for b in blocks], axis=1)
            dest[name] = jax.lax.dynamic_update_slice(
                dest[name], merged.astype(dest[name].dtype), (0, 0, 0, 0))
        dest["index"] = jnp.int32(total)
        out.append(dest)
    return out


def init_page_arena(cfg: LlamaConfig, n_pages: int, page: int, mesh=None):
    """The paged KV arena (runtime/pagepool.py): per layer, the decode
    cache's store-layout leaves re-shaped page-major —
    ``[n_pages, page, kv_heads, head_dim]`` — with NO ``index`` leaf
    (positions live in the per-row block tables, not the storage).
    Page 0 is the reserved null page; it starts zero like everything
    else and only ever accumulates unread garbage. With ``mesh`` the
    arena is placed kv-head-sharded over ``tp``
    (:func:`shard_page_arena`): per-device arena HBM drops ~1/tp and
    the paged gather/scatter programs keep the layout end to end."""
    shape = (n_pages, page, cfg.kv_heads, cfg.head_dim)
    if cfg.kv_quant == "int8":
        arena = [{"k_int8": jnp.zeros(shape, jnp.int8),
                  "k_scale": jnp.full(shape[:3] + (1,), 1e-8, jnp.float32),
                  "v_int8": jnp.zeros(shape, jnp.int8),
                  "v_scale": jnp.full(shape[:3] + (1,), 1e-8, jnp.float32)}
                 for _ in range(cfg.layers)]
    else:
        arena = [{"k": jnp.zeros(shape, cfg.dtype),
                  "v": jnp.zeros(shape, cfg.dtype)}
                 for _ in range(cfg.layers)]
    return arena if mesh is None else shard_page_arena(arena, mesh)


def page_kv_bytes(cfg: LlamaConfig, page: int) -> int:
    """Exact stored bytes of ONE page across all layers and leaves — the
    page-granular unit of the pool's byte accounting (host arithmetic,
    no device access)."""
    import numpy as np

    per_pos = cfg.kv_heads * cfg.head_dim
    if cfg.kv_quant == "int8":
        # int8 k + v values, f32 per-position-per-head scales
        per_layer = page * (2 * per_pos + 2 * cfg.kv_heads * 4)
    else:
        per_layer = page * 2 * per_pos * np.dtype(cfg.dtype).itemsize
    return int(cfg.layers * per_layer)


def _gather_page_cache(arena, tables, window: int, page: int, index):
    """Materialize each row's first ``window`` positions from its block
    table into a contiguous decode cache (one dict per layer, ``index``
    attached) — the XLA twin of the paged kernel's table-lookup DMA.
    tables: [b, >= window/page] int32 page ids; entries past a row's
    allocation point at the null page, whose values are only ever read
    masked. The gathered values are bitwise the pages' values, so every
    downstream program (the shared ``_scan_decode``, the continuation)
    sees exactly what a dense contiguous cache would hold."""
    from lambdipy_tpu.parallel.sharding import shard_hint

    nb = window // page
    b = tables.shape[0]
    cols = tables[:, :nb].reshape(-1)
    out = []
    for entry in arena:
        # the hint keeps the gathered working cache in the arena's
        # kv-head-over-tp layout (no-op without a mesh): the page gather
        # touches only the pages/seq dims, so the head dim never moves
        e = {name: shard_hint(
                 jnp.take(val, cols, axis=0).reshape(
                     b, nb * page, *val.shape[2:]),
                 "dp", None, "tp")
             for name, val in entry.items()}
        e["index"] = index
        out.append(e)
    return out


def _scatter_page_cache(arena, tables, cache, page: int):
    """Write a contiguous per-row cache back into its block-table pages
    (the inverse of :func:`_gather_page_cache`; ``index`` dropped).
    Pages shared between rows (frozen prefix pages) receive their own
    values back — decode never writes inside a row's matched prefix, so
    the round trip is bitwise a no-op there — and null-page duplicates
    may land in any order because nothing reads the null page
    unmasked."""
    b = tables.shape[0]
    new = []
    for aentry, centry in zip(arena, cache):
        e = {}
        for name, val in aentry.items():
            c = centry[name]
            nb = c.shape[1] // page
            pages = c.reshape(b * nb, page, *c.shape[2:]).astype(val.dtype)
            e[name] = val.at[tables[:, :nb].reshape(-1)].set(pages)
        new.append(e)
    return new


def arena_page_slices(arena, pid: int, page: int):
    """One arena page's per-layer KV as block slices shaped like
    :func:`slice_cache_blocks` returns (``[1, page, kv_heads, d-or-1]``
    per leaf) — the KV-EXPORT read primitive for paged prefix stores
    (runtime/kvwire.py framing): a shipped page leaves the arena in the
    exact block-slice layout a dense import would insert. Host fetch;
    the caller must hold a pool ref on ``pid`` so a concurrent release
    cannot recycle the page mid-read."""
    import numpy as np

    return [{name: np.asarray(val[int(pid)])[None, ...]
             for name, val in entry.items()}
            for entry in arena]


def copy_cache(cache):
    """Fresh-buffer copy of a decode cache: safe to feed a DONATING
    program (``_prefix_ext_fn``) while the original stays live in a
    shared store — donation would otherwise invalidate the stored
    buffers under every reader."""
    return [{name: jnp.copy(val) for name, val in entry.items()}
            for entry in cache]


def prefill_into_cache(cfg: LlamaConfig, prefill_cache, batch: int, max_len: int,
                       prompt_len: int):
    """Embed a prefill cache (float entries sized prompt_len) into a
    static max_len decode cache (quantizing when cfg.kv_quant). The
    shard_hint pins the embedded cache to the serving KV layout
    (kv-heads over tp) so prefill-produced caches — the prefix store's
    full-window entries included — leave their program tp-sharded
    instead of whatever replicated layout propagation falls back to
    (no-op without an ambient mesh)."""
    from lambdipy_tpu.parallel.sharding import shard_hint

    out = []
    for entry in prefill_cache:
        store = _kv_store(cfg, entry["k"], entry["v"])
        dest = _empty_cache_entry(cfg, batch, max_len)
        for name, val in store.items():
            dest[name] = shard_hint(
                jax.lax.dynamic_update_slice(dest[name], val, (0, 0, 0, 0)),
                "dp", None, "tp")
        dest["index"] = jnp.int32(prompt_len)
        out.append(dest)
    return out


_MOE_EXPERT_KEYS = ("experts_gate", "experts_up", "experts_down")


def quantize_params(float_params):
    """Convert a float LlamaModel params pytree (quant=None) into the int8
    layout (quant="int8"): each QDense ``kernel`` becomes ``kernel_int8`` +
    per-output-channel ``scale``, and each 3-D MoE expert stack becomes
    ``<name>_int8`` + per-(expert, channel) ``<name>_scale``. Embeddings,
    norms and the router stay float."""

    def convert(tree):
        if isinstance(tree, dict):
            if "kernel" in tree and getattr(tree["kernel"], "ndim", 0) == 2:
                w = jnp.asarray(tree["kernel"], jnp.float32)
                scale = jnp.max(jnp.abs(w), axis=0, keepdims=True) / 127.0
                scale = jnp.maximum(scale, 1e-8)
                out = dict(tree)
                del out["kernel"]
                out["kernel_int8"] = jnp.round(w / scale).astype(jnp.int8)
                out["scale"] = scale
                return out
            if any(k in tree and getattr(tree[k], "ndim", 0) == 3
                   for k in _MOE_EXPERT_KEYS):
                out = dict(tree)
                for k in _MOE_EXPERT_KEYS:
                    if k in out and getattr(out[k], "ndim", 0) == 3:
                        w = jnp.asarray(out[k], jnp.float32)  # [e, in, out]
                        scale = jnp.max(jnp.abs(w), axis=1, keepdims=True) / 127.0
                        scale = jnp.maximum(scale, 1e-8)
                        del out[k]
                        out[f"{k}_int8"] = jnp.round(w / scale).astype(jnp.int8)
                        out[f"{k}_scale"] = scale
                return {k: convert(v) if isinstance(v, dict) else v
                        for k, v in out.items()}
            return {k: convert(v) for k, v in tree.items()}
        return tree

    return convert(float_params)


def pipeline_forward(model: LlamaModel, params, tokens, mesh, *,
                     num_microbatches: int):
    """Forward scoring with the transformer blocks pipeline-parallel over
    the mesh's ``pp`` axis (GPipe microbatching, parallel/pipeline.py).

    Embedding and the final norm/lm_head run replicated outside the
    pipeline (they are a small fraction of FLOPs); the ``layers`` blocks are
    split into ``pp`` equal stages. Layer count must divide by pp size.
    """
    from lambdipy_tpu.parallel.pipeline import (
        merge_microbatches, pipeline_apply, split_microbatches,
        stack_stage_params)

    cfg = model.cfg
    p = params["params"]
    n_stages = mesh.shape["pp"]
    if cfg.layers % n_stages:
        raise ValueError(f"{cfg.layers} layers not divisible by pp={n_stages}")
    per_stage = cfg.layers // n_stages
    layer_trees = [p[f"layer_{i}"] for i in range(cfg.layers)]
    stage_trees = [
        jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                               *layer_trees[s * per_stage:(s + 1) * per_stage])
        for s in range(n_stages)
    ]
    stacked = stack_stage_params(stage_trees)  # leading dims [pp, per_stage, ...]

    b, s = tokens.shape
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible by {num_microbatches} microbatches")
    block = LlamaBlock(cfg)
    # batch dim 1: broadcasts against any local microbatch size, so the
    # replicated const stays valid when pipeline_apply also shards the
    # microbatch dim over dp/fsdp
    const = {
        "positions": jnp.arange(s)[None, :],
        "mask": jnp.ones((1, s), jnp.bool_),
    }

    def stage_fn(stage_params, h, const):
        for j in range(per_stage):
            layer = jax.tree_util.tree_map(lambda q, j=j: q[j], stage_params)
            h, _ = block.apply({"params": layer}, h, const["positions"],
                               const["mask"], None)
        return h

    x = jnp.take(p["embed"]["embedding"], tokens, axis=0)
    x = merge_microbatches(pipeline_apply(
        stage_fn, stacked, split_microbatches(x, num_microbatches), mesh,
        const=const))
    x = RMSNorm(cfg.norm_eps).apply({"params": p["final_norm"]}, x)
    return QDense(cfg.vocab_size, cfg.quant, jnp.float32, cfg.matmul_backend).apply(
        {"params": p["lm_head"]}, x)


def filter_logits(logits, *, top_k: int | None = None, top_p: float | None = None):
    """Mask logits outside the top-k / nucleus (top-p) sets to -inf.

    logits: [b, v] fp32. Static top_k/top_p (compile-time), the standard
    serving knobs. The highest-probability token is always kept.
    """
    neg = jnp.float32(-1e30)
    if top_k is not None and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p is not None and top_p < 1.0:
        sort = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sort, axis=-1)
        # keep while cumulative prob *before* this token is < top_p; the
        # head token is kept unconditionally so top_p <= 0 degrades to
        # greedy instead of masking the whole vocabulary
        keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
        keep = keep.at[..., 0].set(True)
        thresh = jnp.min(jnp.where(keep, sort, jnp.float32(jnp.inf)),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < thresh, neg, logits)
    return logits


def filter_logits_runtime(logits, top_k, top_p):
    """:func:`filter_logits` with the knobs as RUNTIME operands, so one
    compiled program serves every request (VERDICT r2 #3: static knobs
    forced a multi-second re-trace per novel sampling combination).

    top_k (int32) and top_p (f32) may be scalars or PER-ROW ``[b]``
    vectors — batcher-fused rows each filter under their own request's
    knobs (VERDICT r5 #2). <= 0 disables top_k, >= 1 disables top_p,
    per row. Same sequential semantics as the static version (top-k
    filter, then nucleus over the filtered distribution); the extra
    vocab-sized sort per emitted token is noise next to the per-step
    matmuls.
    """
    neg = jnp.float32(-1e30)
    v = logits.shape[-1]
    rows = logits.shape[:-1]
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), rows)
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), rows)
    srt = jnp.sort(logits, axis=-1)[..., ::-1]
    kth = jnp.take_along_axis(
        srt, jnp.clip(top_k - 1, 0, v - 1)[..., None], axis=-1)
    logits = jnp.where((top_k > 0)[..., None] & (logits < kth), neg, logits)
    srt = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(srt, axis=-1)
    keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p[..., None]
    keep = keep.at[..., 0].set(True)
    thresh = jnp.min(jnp.where(keep, srt, jnp.float32(jnp.inf)),
                     axis=-1, keepdims=True)
    return jnp.where((top_p < 1.0)[..., None] & (logits < thresh), neg,
                     logits)


def _split_rows(keys):
    """Advance per-row PRNG chains one step: ``[b, 2]`` uint32 keys ->
    (new keys ``[b, 2]``, per-row subkeys ``[b, 2]``). Each row's walk is
    a function of ITS key alone — a row splits identically whether it
    decodes solo or packed next to arbitrary traffic, which is what
    makes sampled requests batchable (VERDICT r5 #2)."""
    pair = jax.vmap(lambda k: jax.random.split(k, 2))(keys)  # [b, 2, 2]
    return pair[:, 0], pair[:, 1]


def _scan_decode(model: LlamaModel, params, select_fn, first, lp0, cache,
                 start, done0, keys, eos_id, decode_steps: int,
                 return_carry: bool = False, pos_offset=None):
    """The decode scan shared by the exact-shape path (:func:`_decode`),
    the bucketed serving path (:func:`_serve_decode`) and the streaming
    segment path: one compiled step per token over a static-shape cache.
    ``eos_id`` is an int32 scalar or per-row ``[b]`` operand; < 0
    disables eos latching for that row (``done`` then never becomes
    True, so the filler value is never emitted). ``keys`` is the per-row
    ``[b, 2]`` PRNG operand (:func:`_split_rows`). Emits ``(tokens,
    logprobs)`` — each token's raw model logprob rides along (one
    logsumexp per step, noise next to the forward); filler tokens after
    eos carry logprob 0. ``return_carry`` additionally returns the final
    (tok, lp, cache, pos, done, keys) carry so a later segment can
    continue the decode exactly where this one stopped.

    ``pos_offset`` (int32 scalar or ``[b]``, default None) splits the
    LOGICAL position from the cache-local one: the carry's ``pos`` stays
    the LOCAL frame (cache writes and the validity mask key off it — the
    windowed long-context path gathers a sliding view whose slot 0 is
    logical token ``pos_offset``), while RoPE sees ``pos + pos_offset``,
    the token's true logical position. None keeps every existing path
    byte-identical (no extra operand is traced)."""
    b = first.shape[0]
    has_eos = eos_id >= 0

    def step(carry, _):
        tok, lp, cache, pos, done, keys = carry  # pos: int32 scalar or [b]
        rope_pos = pos if pos_offset is None else pos + pos_offset
        positions = (rope_pos[:, None] if jnp.ndim(rope_pos)
                     else jnp.broadcast_to(rope_pos[None, None], (b, 1)))
        logits, new_cache = model.apply(params, tok[:, None],
                                        positions=positions, cache=cache)
        for entry in new_cache:
            entry["index"] = pos + 1
        keys, subs = _split_rows(keys)
        nxt, nlp = select_fn(logits[:, -1, :].astype(jnp.float32), subs)
        nxt = jnp.where(done, eos_id, nxt)
        nlp = jnp.where(done, jnp.float32(0.0), nlp)
        done = done | (has_eos & (nxt == eos_id))
        return (nxt, nlp, new_cache, pos + 1, done, keys), (tok, lp)

    carry, (toks, lps) = jax.lax.scan(
        step, (first, lp0, cache, start, done0, keys), None,
        length=decode_steps)
    out = (jnp.transpose(toks), jnp.transpose(lps))  # [b, decode_steps] x2
    return (out, carry) if return_carry else out


def _serve_decode(model: LlamaModel, params, prompt, length, temperature,
                  top_k, top_p, rng, eos_id, *, decode_steps: int,
                  cache_len: int):
    """Serving decode with every request knob as a runtime operand.

    prompt: [b, sb] int32, right-padded to the bucket size sb; length:
    int32 scalar or [b] — PER-ROW true prompt lengths, so one program
    serves a ragged batch of different-length prompts (each row decodes
    from its own prompt end). Right padding is safe under causal
    attention — real positions never attend pad keys, and the decode loop
    overwrites each row's pad cache slots at index ``length[r] + j``
    before the validity mask (``pos <= index``) ever exposes them. The
    first sampled token reads row r's logits at ``length[r] - 1``.

    temperature (f32, <= 0 = greedy), top_k (int32, <= 0 = off), top_p
    (f32, >= 1 = off), eos_id (int32, < 0 = none) and the PRNG keys are
    all PER-ROW ``[b]`` traced operands (keys ``[b, 2]``): one compiled
    (sb, decode_steps) program serves every sampling configuration and
    every prompt length in the bucket, and batcher-fused rows each
    decode under their own request's knobs and their own seed-derived
    PRNG chain (VERDICT r5 #2).
    """
    select = _serve_select(temperature, top_k, top_p)
    carry = _serve_prefill(model, params, prompt, length, select, rng,
                           eos_id, cache_len=cache_len)
    return _scan_decode(model, params, select, *carry, eos_id, decode_steps)


def _token_logprob(lg, tok):
    """Raw model logprob of ``tok`` under fp32 logits ``lg`` [b, v] —
    log_softmax at the chosen index (knob-independent: what the MODEL
    assigned, not the sampling distribution)."""
    logz = jax.nn.logsumexp(lg, axis=-1)
    return jnp.take_along_axis(lg, tok[:, None], axis=-1)[:, 0] - logz


def _serve_select(temperature, top_k, top_p):
    """Token-selection closure over PER-ROW runtime knob operands
    (scalar or ``[b]``; batcher-fused rows each select under their own
    request's knobs). ``select(lg [b, v] f32, keys [b, 2])`` returns
    ``(token [b], raw model logprob of token [b])`` — row r's draw uses
    row r's subkey alone, so its tokens are independent of what shares
    the batch (VERDICT r5 #2)."""

    def select(lg, keys):
        lg = lg.astype(jnp.float32)
        t_row = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32),
                                 lg.shape[:-1])
        greedy_tok = jnp.argmax(lg, axis=-1).astype(jnp.int32)

        def sampled(args):
            lg, keys = args
            t = jnp.maximum(t_row, jnp.float32(1e-6))[:, None]
            filt = filter_logits_runtime(lg / t, top_k, top_p)
            draw = jax.vmap(
                lambda k, row: jax.random.categorical(k, row))(keys, filt)
            # greedy rows inside a mixed batch keep their argmax
            return jnp.where(t_row > 0, draw.astype(jnp.int32), greedy_tok)

        # cond, not where: an all-greedy batch (the bulk of serving
        # load) must not pay the sampling path's two vocab-sized sorts
        # per emitted token — they dominate small-model decode steps
        tok = jax.lax.cond(jnp.any(t_row > jnp.float32(0.0)), sampled,
                           lambda args: greedy_tok, (lg, keys))
        return tok, _token_logprob(lg, tok)

    return select


def _serve_prefill(model: LlamaModel, params, prompt, length, select, rng,
                   eos_id, *, cache_len: int, sp_prefill: int = 0):
    """Bucketed serving prefill: embed the prompt into a ``cache_len``
    decode cache and select the first token. Returns the decode carry
    ``(first, lp0, cache, pos, done, rng)`` consumed by
    :func:`_scan_decode` —
    either fused into one program (:func:`_serve_decode`) or as its own
    compiled program for streaming segments."""
    cfg = model.cfg
    b, sb = prompt.shape
    length = jnp.broadcast_to(jnp.asarray(length, jnp.int32), (b,))
    # lm_head only at each row's last real position: [b, 1, v], never the
    # [b, sb, v] full-prefill logits tensor
    logits, prefill_cache = model.apply(params, prompt,
                                        logit_positions=length - 1,
                                        sp_prefill=sp_prefill)
    cache = prefill_into_cache(cfg, prefill_cache, b, cache_len, 0)
    for entry in cache:
        entry["index"] = length
    keys, subs = _split_rows(rng)
    first, lp0 = select(logits[:, 0, :].astype(jnp.float32), subs)
    done0 = (eos_id >= 0) & (first == eos_id)
    return first, lp0, cache, length, done0, keys


def _continue_prefill(model: LlamaModel, params, cache, suffix, suffix_len,
                      select, rng, eos_id, sbs: int, pos_offset=None,
                      sp_prefill: int = 0, band: int = 0):
    """Continuation prefill from a cached prefix KV: embed the suffix
    chunk at positions after the cache index, select the first token, and
    return the decode carry ``(first, lp0, cache, pos, done, rng)``. The
    SINGLE source of the prefix-continuation math — the fused prefix path
    feeds this carry straight into :func:`_scan_decode`, the streaming
    prefix path returns it to segment programs, and their bitwise parity
    rests on this being one function. ``pos_offset`` is the windowed
    long-context split (see :func:`_scan_decode`): cache writes stay in
    the LOCAL frame (``index``), RoPE sees the logical position."""
    idx = cache[0]["index"]
    rope0 = idx if pos_offset is None else idx + pos_offset
    positions = (rope0 + jnp.arange(sbs))[None, :]
    logits, new_cache = model.apply(
        params, suffix, positions=positions, cache=cache,
        logit_positions=jnp.broadcast_to(suffix_len - 1, (1,)),
        sp_prefill=sp_prefill, band=band)
    # The carry must come out in the SEG-PROGRAM family's shapes: per-row
    # (1,) index/pos, matching what _serve_prefill produces. The prefix
    # cache's scalar index fed model.apply above (the multi-token chunk
    # needs the scalar-index branch), but a scalar carry here would make
    # the shared ('stream', ...) segment program silently retrace — and
    # FAIL against its shape-strict AOT-loaded executable (ADVICE r4).
    start = jnp.broadcast_to(idx + suffix_len, (1,))
    for entry in new_cache:
        entry["index"] = start
    keys, subs = _split_rows(rng)
    first, lp0 = select(logits[:, 0, :].astype(jnp.float32), subs)
    done0 = (eos_id >= 0) & (first == eos_id)
    return first, lp0, new_cache, start, done0, keys


def _next_bucket(n: int, lo: int) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _spec_accept_resample(probs, draft, keys):
    """The deterministic-draft rejection-sampling core of SAMPLED
    speculative decoding (the delta-proposal case of Leviathan-style
    speculative sampling).

    probs: [kb, v] target distributions per chunk position (post
    temperature/top-k/top-p); draft: [kb-1] proposed tokens; keys:
    [kb, 2] — one uniform per accept test plus one for the final draw.
    Position i accepts draft_i with probability p_i(draft_i); the
    first rejection resamples position m from the RESIDUAL (p_m with
    the rejected token zeroed, renormalized), and a full accept draws
    position kb-1 fresh from p_{kb-1}. Emitting
    ``[pending, draft[:m]]`` with ``new_tok`` as the next pending is
    exactly ancestral sampling from the target chain — the identity
    ``p = q * min(1, p/q) + (1 - accept) * residual`` with q a delta.
    Returns (m accepted-draft count 0..kb-1, new_tok)."""
    kb, v = probs.shape
    p_draft = jnp.take_along_axis(probs[: kb - 1], draft[:, None],
                                  1)[:, 0]
    u = jax.vmap(lambda key: jax.random.uniform(key))(keys[: kb - 1])
    acc = (u < p_draft).astype(jnp.int32)
    m = jnp.sum(jnp.cumprod(acc))  # 0..kb-1
    pm = probs[m]
    rejected = m < kb - 1
    # v is out of range -> no zeroing on a full accept
    dm = jnp.where(rejected, draft[jnp.clip(m, 0, kb - 2)], v)
    pm = jnp.where(jnp.arange(v) == dm, 0.0, pm)
    pm = pm / jnp.maximum(pm.sum(), 1e-30)
    new_tok = jax.random.categorical(
        keys[kb - 1], jnp.log(jnp.maximum(pm, 1e-38)))
    return m, new_tok.astype(jnp.int32)


def _spec_chain_verify(select, lg, draft, lp_in, keys):
    """Chain-deterministic draft verification — the continuous engine's
    accept/rollback core (the batched counterpart of the solo verify
    fns, specialized to the engine's bitwise contract).

    lg: [b, kb, v] f32 logits of the verify chunk (position i
    conditioned on the pending token + drafts before i); draft:
    [b, kb-1] proposals; lp_in: [b] the pending token's logprob carry;
    keys: [b, 2] the per-row PRNG chains as of the pending token.

    The target here is not a distribution but the CHAIN itself: given a
    row's seed, ``_scan_decode`` emits a deterministic sequence (greedy
    rows by argmax, sampled rows by categorical draws along the row's
    own split-per-step key walk). Verification re-derives that chain's
    next token at every chunk position — advancing the key walk exactly
    as the one-token scan would — and accepts the longest draft prefix
    that MATCHES it. Emitted tokens are therefore bitwise the
    non-speculative engine's for greedy AND seeded-sampled rows alike
    (speculation changes how many tokens each weight read verifies,
    never which tokens) — the property ``bench.py --spec`` gates on.
    Relative to :func:`_spec_accept_resample`'s rejection sampling (the
    solo sampled path's distributional contract) the accept test is
    stricter — token equality instead of probability mass — costing
    some acceptance on high-entropy sampled rows and buying exact
    replay/parity. The rejected tail's key splits roll back: the
    returned chain state is the walk after exactly ``count``
    selections, so a later segment continues precisely where plain
    decode would.

    Returns ``(lps_block [b, kb], count [b] in 1..kb, tok' [b],
    lp' [b], keys' [b, 2])``; ``lps_block[:, 0]`` is the pending
    token's logprob and column j >= 1 the (j-1)'th selection's — only
    the first ``count`` columns are meaningful, like the token block."""
    b, kb, _ = lg.shape
    tgt, tlp, kstack = [], [], [keys]
    cur = keys
    for i in range(kb):
        cur, subs = _split_rows(cur)
        t_i, l_i = select(lg[:, i, :], subs)
        tgt.append(t_i)
        tlp.append(l_i)
        kstack.append(cur)
    tgt = jnp.stack(tgt)          # [kb, b]
    tlp = jnp.stack(tlp)          # [kb, b]
    kstack = jnp.stack(kstack)    # [kb + 1, b, 2]
    ok = (tgt[: kb - 1] == jnp.transpose(draft)).astype(jnp.int32)
    m = jnp.sum(jnp.cumprod(ok, axis=0), axis=0)   # [b] 0..kb-1
    count = m + 1
    tok2 = jnp.take_along_axis(tgt, m[None, :], axis=0)[0]
    lp2 = jnp.take_along_axis(tlp, m[None, :], axis=0)[0]
    keys2 = jnp.take_along_axis(
        kstack, jnp.broadcast_to(count[None, :, None], (1, b, 2)),
        axis=0)[0]
    lps_block = jnp.concatenate(
        [lp_in[:, None], jnp.transpose(tlp[: kb - 1])], axis=1)
    return lps_block, count, tok2, lp2, keys2


def _lookup_draft(context, k: int, ngram_max: int = 3) -> list:
    """Prompt-lookup drafting (host-side): propose the k tokens that
    followed the most recent earlier occurrence of the context's current
    suffix n-gram, falling back to repeating the last token.

    No draft model exists or is needed: the draft source is the sequence
    itself, which makes this free and surprisingly effective exactly
    where speculative decoding pays off — repetitive continuations
    (copying, templated output, and the cycles greedy decodes fall
    into). A wrong draft costs nothing beyond the verify chunk whose
    weight read was the point of the step anyway. An EMPTY context
    (nothing to look up in) drafts zeros — a draft is only ever a
    proposal, so a content-free one is safe, just never accepted."""
    return _lookup_draft_hit(context, k, ngram_max)[0]


def _lookup_draft_hit(context, k: int, ngram_max: int = 3) -> tuple:
    """:func:`_lookup_draft` plus whether an n-gram match was FOUND:
    ``(draft list of k, hit bool)``. ``hit=False`` marks the fallback
    (repeat-last-token, or zeros on an empty context) — the engine's
    per-row draft-miss accounting (``SpecDecodeStats.draft_misses``)
    keys off it, and ISSUE's "no match falls back to k=1" degeneracy is
    the observable consequence: a fallback draft usually verifies 0
    proposals, so the step emits exactly the 1 token plain decode
    would."""
    import numpy as np

    ctx = np.asarray(context, np.int64).reshape(-1)
    n = ctx.size
    if n == 0:
        return [0] * k, False
    for g in range(min(ngram_max, n - 1), 0, -1):
        suffix = ctx[n - g:]
        windows = np.lib.stride_tricks.sliding_window_view(ctx, g)[:n - g]
        hits = np.nonzero((windows == suffix).all(axis=1))[0]
        if hits.size:
            start = int(hits[-1]) + g
            cand = ctx[start:start + k]
            out = np.full(k, ctx[-1], np.int64)
            out[:cand.size] = cand
            return out.tolist(), True
    return [int(ctx[-1])] * k, False


def _shallow_draft(model, params, tok, cache, pos, kb: int,
                   exit_layer: int):
    """Self-drafting shallow-exit chain (device-side, traced INSIDE a
    verify program): run ``kb - 1`` sequential one-token forwards through
    only the first ``exit_layer`` layers + final_norm + the tied lm_head
    (:class:`LlamaModel`'s ``exit_layer`` path), each greedy-argmax token
    feeding the next step — the Medusa/EAGLE-style "cheap head over the
    target's own early hidden state" draft source, costing roughly
    ``exit_layer / layers`` of a full forward per proposed token.

    The chain reads/writes a SCRATCH alias of the early layers' windowed
    KV entries: each functional ``.at[].set`` write lands in throwaway
    arrays the caller discards, so the real cache the verify chunk runs
    over is untouched — the draft can never poison verification, and
    acceptance stays chain-deterministic whatever the drafts are. Because
    it runs in-program off the device-true carry token, the drafts are
    never stale at pipeline depth >= 2 (unlike host lookup, which must
    extrapolate across in-flight steps). Returns ``d_model [b, kb-1]``
    int32."""
    dcache = [dict(entry) for entry in cache[:exit_layer]]
    cur = tok
    drafts = []
    for j in range(kb - 1):
        step_pos = pos + j
        for entry in dcache:
            entry["index"] = step_pos
        lg, dcache = model.apply(params, cur[:, None],
                                 positions=step_pos[:, None],
                                 cache=dcache, exit_layer=exit_layer)
        nxt = jnp.argmax(lg[:, -1, :].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        drafts.append(nxt)
        cur = nxt
    return jnp.stack(drafts, axis=1)


class LlamaServer:
    """Compile-once decode serving: prompt-length bucketing (pad right to a
    power of two) + sampling knobs as runtime operands.

    One jitted ``_serve_decode`` per (batch, prompt-bucket, decode-bucket)
    triple serves every request that falls in it; a second request with a
    different prompt length, temperature, top-k/p, seed, or eos triggers
    ZERO new compiles (VERDICT r2 #3). Ragged batches are first-class:
    per-row length operands let rows of different prompt lengths decode
    together, each from its own prompt end. ``compile_count`` exposes the
    number of distinct compiled programs for tests and metrics.
    """

    def __init__(self, model: LlamaModel, params, *, mesh=None,
                 min_bucket: int = 16, decode_cap: int | None = None,
                 prefix_cache_max: int = 4, program_cache_max: int = 64,
                 prefill_chunk: int | None = None, aot=None):
        self.model = model
        self.params = params
        self.mesh = mesh
        if mesh is not None:
            # serving is strict where the training forward is lenient: a
            # tp that can't shard the heads must error, not silently
            # replicate the KV cache the operator paid a mesh to shard
            validate_serving_mesh(model.cfg, mesh)
        self.min_bucket = min_bucket
        # optional runtime/aot.AotStore: serving programs are loaded from
        # the bundle's serialized-executable tier instead of compiled
        # (the 8B boot pays ~70 s of remote compile PER program without
        # this), and aot_save_all() snapshots freshly compiled programs
        # after warmup so the next boot hits. Example operands for
        # probe/export are SYNTHESIZED from each program key — shapes are
        # fully determined by (bucket, cache_len, config).
        self._aot = aot
        self._aot_loaded: set = set()
        self.aot_hits = 0  # programs served from the AOT store this boot
        # Speculative-decoding counters. ``spec_stats`` (the legacy bare
        # dict — last call's counters, single-threaded convenience only)
        # is kept for back-compat; the LOCKED, cumulative,
        # /metrics-surfaced object is ``spec_metrics`` — ONE
        # SpecDecodeStats instance that both the solo
        # ``generate_speculative`` path and the continuous engine's
        # spec mode record into, so acceptance reporting has a single
        # source of truth under threaded serving.
        from lambdipy_tpu.runtime.metrics import SpecDecodeStats

        self.spec_stats: dict = {}  # last generate_speculative counters
        self.spec_metrics = SpecDecodeStats()
        # chunked prefill: prompts longer than this prefill through
        # fixed-width chunks against the growing KV cache instead of one
        # wide program. Memory for dense attention drops from O(s^2) to
        # O(chunk x s) — an 8k dense prefill's [h, s, s] f32 scores are
        # 8.6 GB in one shot but bounded at chunk width chunked — and
        # program count stays O(1) in prompt length. None = off.
        # The chunk width MUST divide max_len: every chunk (padded last
        # one included) writes its full width at a multiple-of-chunk
        # offset, and a write window crossing max_len would be CLAMPED by
        # dynamic_update_slice — silently overwriting real prefix KV.
        # Halve until it divides; disable if nothing >= min_bucket does.
        self.prefill_chunk = None
        if prefill_chunk:
            ck = max(self.min_bucket, _next_bucket(prefill_chunk, 16))
            while ck >= self.min_bucket and model.cfg.max_len % ck:
                ck //= 2
            if ck >= self.min_bucket:
                self.prefill_chunk = ck
        # default: anything the context window allows is servable (power-
        # of-two bucketing bounds distinct compiles at log2(max_len))
        self.decode_cap = decode_cap or model.cfg.max_len
        # Compiled-program cache. Bucketing bounds prompt/decode keys to
        # log2 counts, but ("continue", ...) keys multiply across prefix
        # lengths x suffix buckets x step buckets — a long-lived
        # multi-tenant server must not accrete programs without bound, so
        # the cache is LRU-capped (VERDICT r3 weak #8). The lock also
        # serializes check-then-insert: serving threads, streams, prefix
        # prefills, and the bucket-warm thread all race here, and an
        # unlocked miss makes each racer pay a duplicate multi-second
        # remote compile.
        from collections import OrderedDict

        self._fns: "OrderedDict[tuple, Any]" = OrderedDict()
        self._fns_lock = threading.Lock()
        self._fns_max = max(1, program_cache_max)
        self._fn_evictions = 0
        # prefix KV cache (shared system prompts): key -> (cache, length).
        # The KV cache is FUNCTIONAL (immutable jax arrays), so serving
        # from a cached prefix never copies or locks it — each request's
        # programs produce fresh buffers. LRU-bounded: a full-window
        # cache entry is max_len * kv_heads * head_dim * 2 * layers bytes.
        from collections import OrderedDict

        self._prefix_cache_max = max(1, prefix_cache_max)
        self._prefixes: "OrderedDict[str, tuple]" = OrderedDict()
        # the jax arrays are immutable, but the LRU BOOKKEEPING is not:
        # serving threads insert/refresh/evict concurrently. _inflight
        # collapses a thundering herd of first requests for the SAME new
        # prefix to one device prefill (key -> Event the rest wait on).
        self._prefix_lock = threading.Lock()
        self._prefix_inflight: dict[str, Any] = {}

    @property
    def buckets(self) -> list[tuple]:
        """Snapshot of the bucket keys compiled so far — (batch, prompt,
        decode) for fused programs, ("stream", batch, prompt, cache_len,
        segment) for streaming pairs (repr-keyed sort tolerates the mixed
        tuple shapes)."""
        with self._fns_lock:
            return sorted(self._fns, key=repr)

    @property
    def compile_count(self) -> int:
        with self._fns_lock:
            fns = list(self._fns.values())
        # AOT-loaded executables are not jit objects; count each as one
        # compiled program
        return sum(getattr(f, "_cache_size", lambda: 1)()
                   for fn in fns
                   for f in (fn if isinstance(fn, tuple) else (fn,)))

    @property
    def program_evictions(self) -> int:
        """Programs LRU-evicted from the compiled cache (a rising count on
        a steady workload means program_cache_max is too small and the
        server is recompiling hot buckets)."""
        return self._fn_evictions

    def _fn_cached(self, key: tuple, build):
        """LRU get-or-build under the cache lock. ``build()`` only wraps
        with ``jax.jit`` (lazy — tracing/compiling happens at first call),
        so holding the lock through it is cheap; what the lock buys is
        that at most one wrapper per key ever exists, so concurrent racers
        share one compiled program instead of each tracing their own.
        With an AOT store attached, a miss first tries the bundle's
        serialized executables (outside the lock — a probe invokes the
        program) before falling back to the jit wrapper."""
        with self._fns_lock:
            fn = self._fns.get(key)
            if fn is not None:
                self._fns.move_to_end(key)
                return fn
        loaded = self._aot_load(key) if self._aot is not None else None
        with self._fns_lock:
            fn = self._fns.get(key)  # a racer may have won meanwhile
            if fn is None:
                if loaded is None:
                    fn = build()
                else:
                    # partial hits are real (ADVICE r4: the continuous
                    # engine's pair only ever executes its seg half, so
                    # the snapshot may hold one part): loaded parts are
                    # used, missing parts fall back to the jit wrapper
                    if all(p is not None for p in loaded):
                        merged = list(loaded)
                    else:
                        built = build()
                        built = (built if isinstance(built, tuple)
                                 else (built,))
                        merged = [l if l is not None else b
                                  for l, b in zip(loaded, built)]
                    fn = merged[0] if len(merged) == 1 else tuple(merged)
                    for i, p in enumerate(loaded):
                        if p is not None:
                            self._aot_loaded.add((key, i))
                            self.aot_hits += 1
                self._fns[key] = fn
            while len(self._fns) > self._fns_max:
                self._fns.popitem(last=False)
                self._fn_evictions += 1
            return fn

    # -- AOT snapshot/restore of compiled serving programs -------------------

    # Serving-program AOT generation: bump when any serving program's
    # SIGNATURE or carry shape changes, so a pre-change bundle's aot/
    # dir (which persists across in-place upgrade) orphans its stale
    # executables instead of loading them. g2 = round 5: per-row knob /
    # PRNG operands + the (1,)-shaped prefix-continuation carry.
    _AOT_GEN = "g2"

    @classmethod
    def aot_prefix(cls) -> str:
        """Artifact-name prefix for THIS generation's serving programs.
        The generation tag sits in the prefix so boot-time bulk
        operations (AotStore.preload) can glob exactly the loadable
        artifacts — a stale generation's executables must not be
        device-loaded just to sit unconsumed (code-review r5)."""
        return f"srv-{cls._AOT_GEN}-"

    @classmethod
    def _aot_name(cls, key: tuple) -> str | None:
        """Artifact name(s) for a program-cache key; None = not AOT-able."""
        if isinstance(key[0], int):  # fused decode (b, sb, steps)
            return cls.aot_prefix() + "dec-" + "-".join(map(str, key))
        kind = key[0]
        if kind in ("stream", "prefix", "continue", "stream_prefix",
                    "spec", "spec_s"):
            return cls.aot_prefix() + f"{kind}-" + "-".join(map(str, key[1:]))
        # "prefix_ext" stays un-AOT-able on purpose: it donates its cache
        # argument, which the store's double-call probe would invalidate
        # between calls — and warmup never compiles it, so there would be
        # nothing to snapshot anyway
        return None

    def _aot_examples(self, key: tuple):
        """Synthesized example operand tuples (excluding params) matching
        the traced shapes of the key's program(s). Returns a list — one
        per callable the key maps to (streaming keys map to a pair)."""
        cfg = self.model.cfg

        def knobs_for(b):
            return self._knob_operands(0.0, None, None, 0, None, b=b)

        def prompt_ops(b, sb):
            return (jnp.zeros((b, sb), jnp.int32),
                    jnp.ones((b,), jnp.int32))

        def prefix_cache(cache_len):
            cache = init_decode_cache(cfg, 1, cache_len)
            for entry in cache:
                entry["index"] = jnp.int32(1)  # prefix cache: scalar index
            return cache

        if isinstance(key[0], int):
            b, sb, _steps = key
            return [(*prompt_ops(b, sb), *knobs_for(b))]
        kind = key[0]
        if kind == "stream":
            _, b, sb, cache_len, _segment = key
            t, k, p, rng, eos = knobs_for(b)
            index = jnp.ones((b,), jnp.int32)  # per-row, like the prefill
            cache = init_decode_cache(cfg, b, cache_len)
            for entry in cache:
                entry["index"] = index
            seg_ex = (t, k, p,
                      jnp.zeros((b,), jnp.int32),    # first token
                      jnp.zeros((b,), jnp.float32),  # lp
                      cache, index,                  # pos
                      jnp.zeros((b,), jnp.bool_),    # done
                      rng, eos)
            return [(*prompt_ops(b, sb), t, k, p, rng, eos), seg_ex]
        if kind == "prefix":
            _, sb, _cache_len = key
            return [(jnp.zeros((1, sb), jnp.int32), jnp.int32(1))]
        if kind == "continue":
            _, sbs, _steps, cache_len = key
            return [(prefix_cache(cache_len), jnp.zeros((1, sbs), jnp.int32),
                     jnp.int32(1), *knobs_for(1))]
        if kind == "stream_prefix":
            # 2-tuple: full-window continuation (the prefix path);
            # 3-tuple: continuation over a capped engine cache
            sbs = key[1]
            cache_len = key[2] if len(key) > 2 else cfg.max_len
            return [(prefix_cache(cache_len),
                     jnp.zeros((1, sbs), jnp.int32), jnp.int32(1),
                     *knobs_for(1))]
        if kind == "spec":
            # verify inputs are scalar-index (generate_speculative
            # normalizes the prefill carry before the first call)
            _, kb, cache_len = key
            return [(jnp.zeros((1, kb), jnp.int32),
                     jnp.zeros((1,), jnp.int32), prefix_cache(cache_len))]
        if kind == "spec_s":
            _, kb, cache_len = key
            return [(jnp.zeros((1, kb), jnp.int32),
                     jnp.zeros((1,), jnp.int32), prefix_cache(cache_len),
                     jnp.float32(1.0), jnp.int32(0), jnp.float32(1.0),
                     jnp.zeros((kb, 2), jnp.uint32))]
        return None

    def _aot_load(self, key: tuple):
        """Best-effort load of the key's program(s) from the AOT store.
        Returns a list aligned with the key's parts — loaded executable
        per hit, None per miss — or None when nothing hit at all.
        Multi-part keys (the streaming pair) load PARTIALLY: the
        continuous engine only ever runs a pair's seg half, so a
        snapshot legitimately holds one part (ADVICE r4) and the boot
        should still skip that compile."""
        name = self._aot_name(key)
        if name is None:
            return None
        # existence first (a stat per part): synthesizing probe operands
        # allocates full KV caches on device — wasted work for every
        # never-saved key (first boots, fresh prefix buckets)
        names = [name] if not isinstance(key[0], str) or \
            key[0] != "stream" else [f"{name}-p0", f"{name}-p1"]
        if not any(self._aot.has(n) for n in names):
            return None
        try:
            examples = self._aot_examples(key)
        except Exception:
            return None
        if len(examples) != len(names):
            return None
        parts = []
        for part_name, ex in zip(names, examples):
            if not self._aot.has(part_name):
                parts.append(None)
                continue
            with self._mesh_ctx():
                hit = self._aot.load(part_name, (self.params, *ex))
            parts.append(None if hit is None else hit[0])
        if not any(p is not None for p in parts):
            return None
        return parts

    def aot_save_all(self) -> int:
        """Snapshot every compiled serving program that was NOT itself
        loaded from the store into the bundle's AOT exec tier (called
        after warmup — build-time by the warm runner, serve-time after a
        fresh compile — so the next boot loads executables instead of
        compiling). Returns the number of artifacts written."""
        if self._aot is None:
            return 0
        with self._fns_lock:
            items = list(self._fns.items())
        n = 0
        for key, fn in items:
            name = self._aot_name(key)
            if name is None:
                continue
            try:
                examples = self._aot_examples(key)
            except Exception:
                continue
            fns = fn if isinstance(fn, tuple) else (fn,)
            if len(fns) != len(examples):
                continue
            for i, (part, ex) in enumerate(zip(fns, examples)):
                with self._fns_lock:
                    # saved (or AOT-loaded) once; a later call (e.g.
                    # after the background bucket warm) must not
                    # re-export it
                    if (key, i) in self._aot_loaded:
                        continue
                # only snapshot parts that actually COMPILED: a jit
                # wrapper that never ran (e.g. the prefill half of a
                # pair the continuous engine only uses the seg half of)
                # would pay a fresh multi-second compile inside
                # save_from_jitted's lower().compile() instead of the
                # in-session cache hit the executed ones get. Parts save
                # INDEPENDENTLY (ADVICE r4): the executed half of a
                # pair snapshots even when its sibling never ran.
                if getattr(part, "_cache_size", lambda: 0)() == 0:
                    continue
                part_name = (name if len(examples) == 1
                             else f"{name}-p{i}")
                try:
                    # both tiers: exec loads in seconds where it works
                    # (single-device; the remote-tunnel cold-start path),
                    # hlo + the warmed persistent cache covers platforms
                    # where exec cannot load (e.g. multi-device CPU)
                    meta = self._aot.save_from_jitted(
                        part_name, part, (self.params, *ex))
                except Exception:  # noqa: BLE001 — AOT is best-effort
                    continue
                wrote = len(meta.get("tiers", ()))
                if wrote:
                    n += wrote
                    with self._fns_lock:
                        self._aot_loaded.add((key, i))
        return n

    def _compiled(self, b: int, sb: int, steps: int):
        cache_len = min(sb + steps, self.model.cfg.max_len)

        def build():
            def fn(params, prompt, length, temperature, top_k, top_p, rng,
                   eos_id):
                return _serve_decode(
                    self.model, params, prompt, length, temperature, top_k,
                    top_p, rng, eos_id, decode_steps=steps,
                    cache_len=cache_len)

            return jax.jit(fn)

        return self._fn_cached((b, sb, steps), build)

    def _validate(self, s: int, max_new_tokens: int) -> None:
        cfg = self.model.cfg
        if max_new_tokens < 0:
            raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
        if max_new_tokens > self.decode_cap:
            raise ValueError(
                f"max_new_tokens {max_new_tokens} exceeds the server's "
                f"decode cap {self.decode_cap}")
        if s + max_new_tokens > cfg.max_len:
            raise ValueError(
                f"prompt {s} + max_new_tokens {max_new_tokens} exceeds "
                f"max_len {cfg.max_len}")

    @staticmethod
    def _pad_rows(rows, lengths, bb: int, sb: int):
        """(padded [bb, sb] int32 array, per-row length operand) — dummy
        length-1 rows fill the batch bucket; they are free under per-row
        lengths."""
        import numpy as np

        padded = np.zeros((bb, sb), np.int32)
        for r, row in enumerate(rows):
            padded[r, :lengths[r]] = row
        return (jnp.asarray(padded),
                jnp.asarray(lengths + [1] * (bb - len(rows)), jnp.int32))

    @staticmethod
    def _knob_operands(temperature, top_k, top_p, seed, eos_id, b: int = 1):
        """PER-ROW runtime sampling-knob operands shared by the fused and
        streaming programs: ``(temperature [b] f32, top_k [b] i32,
        top_p [b] f32, keys [b, 2] u32, eos [b] i32)``.

        Each knob may be a scalar (broadcast over the b rows; None = the
        knob's disabled sentinel) or a length-<=b list of per-row values
        (batcher-fused rows each carrying their own request's knobs;
        short lists pad with the disabled sentinel for the bucket's
        dummy rows). Row r's PRNG stream is ``fold_in(PRNGKey(seed_r),
        0)`` for listed seeds and ``fold_in(PRNGKey(seed), r)`` for one
        shared seed — a function of the row's own request alone, NEVER
        of batch composition, so a row samples identically solo or
        packed next to arbitrary traffic (VERDICT r5 #2)."""
        import numpy as np

        def vec(x, default, dtype):
            if isinstance(x, (list, tuple, np.ndarray)):
                vals = [default if e is None else e for e in x]
                vals += [default] * (b - len(vals))
                return jnp.asarray(vals[:b], dtype)
            return jnp.full((b,), default if x is None else x, dtype)

        if isinstance(seed, (list, tuple, np.ndarray)):
            seeds = ([int(s) if s is not None else 0 for s in seed]
                     + [0] * b)[:b]
            keys = jnp.stack([jax.random.fold_in(jax.random.PRNGKey(s), 0)
                              for s in seeds])
        else:
            base = jax.random.PRNGKey(int(seed) if seed is not None else 0)
            keys = jax.vmap(lambda r: jax.random.fold_in(base, r))(
                jnp.arange(b))
        return (vec(temperature, 0.0, jnp.float32),
                vec(top_k, 0, jnp.int32),
                vec(top_p, 1.0, jnp.float32),
                keys,
                vec(eos_id, -1, jnp.int32))

    def _mesh_ctx(self):
        if self.mesh is None:
            from contextlib import nullcontext

            return nullcontext()
        from lambdipy_tpu.parallel.mesh import use_mesh

        return use_mesh(self.mesh)

    def generate(self, prompt_tokens, *, max_new_tokens: int,
                 temperature: float = 0.0, top_k: int | None = None,
                 top_p: float | None = None, seed: int = 0,
                 eos_id: int | None = None, prefix=None,
                 return_logprobs: bool = False):
        """prompt_tokens: [s], [b, s], or a RAGGED list of rows with
        different lengths (each row decodes from its own prompt end) ->
        [b, max_new_tokens].

        Every sampling knob (``temperature``/``top_k``/``top_p``/
        ``seed``/``eos_id``) may be a scalar (applies to all rows) or a
        length-b list of per-row values — the form the batchers use to
        fuse requests with unrelated knobs into one device call. A
        row's sampled tokens depend only on its own seed, never on what
        shares the batch (:meth:`_knob_operands`).

        ``prefix``: optional shared-prefix tokens (single-row requests): a
        cached prefill KV for them is reused across requests
        (:meth:`cache_prefix`), and only ``prompt_tokens`` — the suffix
        after the prefix — is prefilled per request. With the float KV
        cache, output is exactly ``generate(prefix + prompt)``; under
        ``kv_quant`` the suffix attends the QUANTIZED prefix KV (the full
        prompt prefills against exact float K/V), so outputs agree only
        to quantization tolerance."""
        import numpy as np

        cfg = self.model.cfg
        rows, lengths = self._normalize_prompts(prompt_tokens)
        b, s = len(rows), max(lengths)
        if prefix is not None:
            return self._generate_with_prefix(
                prefix, rows, lengths, max_new_tokens, temperature, top_k,
                top_p, seed, eos_id, return_logprobs=return_logprobs)
        self._validate(s, max_new_tokens)
        # prefer power-of-two buckets for reuse, but shrink toward the
        # exact request near the max_len boundary instead of rejecting:
        # any request with s + max_new <= max_len must be servable
        steps = min(_next_bucket(max_new_tokens, self.min_bucket),
                    self.decode_cap, cfg.max_len - s)
        sb = min(_next_bucket(s, self.min_bucket), cfg.max_len - steps)
        # batch is bucketed too (micro-batching produces nondeterministic
        # sizes; each distinct b would otherwise compile at request time)
        bb = _next_bucket(b, 1)
        fn = self._compiled(bb, sb, steps)
        prompt_op, length_op = self._pad_rows(rows, lengths, bb, sb)
        args = (self.params, prompt_op, length_op,
                *self._knob_operands(temperature, top_k, top_p, seed,
                                     eos_id, b=bb))
        with self._mesh_ctx():
            toks, lps = fn(*args)
        toks = np.asarray(jax.device_get(toks))[:b, :max_new_tokens]
        if return_logprobs:
            lps = np.asarray(jax.device_get(lps))[:b, :max_new_tokens]
            return toks, lps
        return toks

    # -- prefix caching ------------------------------------------------------

    @staticmethod
    def _prefix_key(tokens) -> str:
        import hashlib

        import numpy as np

        arr = np.asarray(tokens, np.int32).reshape(-1)
        return hashlib.sha1(arr.tobytes()).hexdigest()

    def cache_prefix(self, prefix_tokens) -> str:
        """Prefill ``prefix_tokens`` once and keep its KV cache for
        :meth:`generate`'s ``prefix=`` path (idempotent; LRU-bounded).
        Returns the cache key. The stored cache is sized to the full
        context window so any suffix + decode the window allows can
        continue from it."""
        cfg = self.model.cfg
        rows, lengths = self._normalize_prompts(prefix_tokens)
        if len(rows) != 1:
            raise ValueError("prefix caching is single-row")
        s = lengths[0]
        if s >= cfg.max_len:
            raise ValueError(f"prefix {s} fills the whole context window")
        key = self._prefix_key(rows[0])
        wait_s, timeouts, max_timeouts = 300.0, 0, 2
        while True:
            with self._prefix_lock:
                if key in self._prefixes:
                    self._prefixes.move_to_end(key)
                    return key
                waiter = self._prefix_inflight.get(key)
                if waiter is None:
                    # we own the prefill for this key
                    self._prefix_inflight[key] = threading.Event()
                    break
            # another thread is prefilling this exact prefix — wait for it
            # instead of duplicating the device work, then re-check (its
            # prefill may have failed or been evicted already). A wait
            # that TIMES OUT means the owner's device prefill is likely
            # wedged (the documented tunnel failure mode): surface an
            # error after a bounded number of timeouts rather than
            # looping forever with nothing reported to the client.
            if not waiter.wait(timeout=wait_s):
                timeouts += 1
                if timeouts >= max_timeouts:
                    raise RuntimeError(
                        f"prefix prefill (key {key[:8]}...) owned by "
                        f"another thread did not complete within "
                        f"{timeouts * wait_s:.0f}s — device prefill "
                        "appears wedged; failing this request")
        try:
            return self._prefill_prefix(key, rows, lengths)
        finally:
            with self._prefix_lock:
                self._prefix_inflight.pop(key).set()

    def get_prefix(self, key: str):
        """LRU-refreshing peek: ``(cache, length)`` for an exact prefix
        key, or None — never prefills (the radix prefix store's fast
        path; :meth:`cache_prefix` is the prefill-on-miss sibling)."""
        with self._prefix_lock:
            entry = self._prefixes.get(key)
            if entry is not None:
                self._prefixes.move_to_end(key)
            return entry

    def register_prefix(self, key: str, cache, length: int) -> None:
        """Insert an externally built full-window prefix cache under
        ``key`` (same LRU bound as :meth:`cache_prefix`) — the radix
        prefix store's injection point: it assembles a cache from its
        block slices (or finishes an extension walk) and registers it
        here so every existing ``prefix=`` path — fused, streaming,
        continuous-engine join, speculative — serves from it
        unchanged."""
        with self._prefix_lock:
            self._prefixes[key] = (cache, int(length))
            self._prefixes.move_to_end(key)
            while len(self._prefixes) > self._prefix_cache_max:
                self._prefixes.popitem(last=False)

    def _prefix_first_fn(self, sb: int, cache_len: int):
        """First-chunk prefix prefill: embed the (padded) chunk into a
        full-window cache, index = true length."""
        def build():
            def pf(params, prompt, length):
                _, prefill_cache = self.model.apply(
                    params, prompt,
                    logit_positions=jnp.zeros((1,), jnp.int32))
                cache = prefill_into_cache(self.model.cfg, prefill_cache, 1,
                                           cache_len, 0)
                for entry in cache:
                    entry["index"] = length  # int32 scalar
                return cache

            return jax.jit(pf)

        return self._fn_cached(("prefix", sb, cache_len), build)

    def _prefix_ext_fn(self, sbs: int):
        """Extend a full-window prefix cache by one PADDED chunk (no token
        selection; lm_head at one position so the vocab matmul is
        skipped). Every chunk except the last must be full-width: the
        scalar-index write covers the whole padded chunk, the NEXT
        chunk's write overwrites those padding cells, and the final
        ragged chunk's padding stays unreachable behind the cache
        index."""
        def build():
            def ext(params, cache, chunk, chunk_len):
                idx = cache[0]["index"].reshape(())
                cache = [{**c, "index": idx} for c in cache]
                positions = (idx + jnp.arange(sbs))[None, :]
                _, new_cache = self.model.apply(
                    params, chunk, positions=positions, cache=cache,
                    logit_positions=jnp.zeros((1,), jnp.int32))
                for entry in new_cache:
                    entry["index"] = idx + chunk_len
                return new_cache

            # donate the incoming cache: it is single-owner inside the
            # chunk loop, and without donation every ext call copies the
            # full-window KV (multi-GB at 8B) to write one chunk
            return jax.jit(ext, donate_argnums=(1,))

        return self._fn_cached(("prefix_ext", sbs), build)

    def _sp_first_fn(self, sb: int, cache_len: int, sp: int):
        """Whole-prompt sequence-parallel first chunk: ONE sharded
        program embeds the (padded) round into a full-window cache with
        the prompt's attention ring-sharded over the sp axis
        (``sp_prefill=sp`` routes the no-cache branch through
        :func:`~lambdipy_tpu.parallel.ring.ring_attention`). For a
        prompt that fits one round this IS the cold prefill — one
        program, critical path 1/sp of the chunk chain."""
        if sb % sp:
            raise ValueError(f"sp first-chunk width {sb} % sp={sp} != 0")

        def build():
            def pf(params, prompt, length):
                _, prefill_cache = self.model.apply(
                    params, prompt,
                    logit_positions=jnp.zeros((1,), jnp.int32),
                    sp_prefill=sp)
                cache = prefill_into_cache(self.model.cfg, prefill_cache, 1,
                                           cache_len, 0)
                for entry in cache:
                    entry["index"] = length  # int32 scalar
                return cache

            return jax.jit(pf)

        return self._fn_cached(("sp_prefill", 1, sb // sp, cache_len, sp),
                               build)

    def _sp_ext_fn(self, sbs: int, sp: int):
        """Sequence-parallel twin of :meth:`_prefix_ext_fn`: extend the
        cache by one ROUND of ``sp`` chunk-widths in a single program —
        the round's queries shard over the sp axis
        (:func:`~lambdipy_tpu.parallel.ring.sp_chunk_attention`), the
        cache write and index math are byte-identical to the serial
        ext's (same scalar-index branch, same padded-chunk contract:
        only the last round may be ragged)."""
        if sbs % sp:
            raise ValueError(f"sp round width {sbs} % sp={sp} != 0")

        def build():
            def ext(params, cache, chunk, chunk_len):
                idx = cache[0]["index"].reshape(())
                cache = [{**c, "index": idx} for c in cache]
                positions = (idx + jnp.arange(sbs))[None, :]
                _, new_cache = self.model.apply(
                    params, chunk, positions=positions, cache=cache,
                    logit_positions=jnp.zeros((1,), jnp.int32),
                    sp_prefill=sp)
                for entry in new_cache:
                    entry["index"] = idx + chunk_len
                return new_cache

            return jax.jit(ext, donate_argnums=(1,))

        return self._fn_cached(("sp_prefill_ext", 1, sbs // sp, sp), build)

    def _sp_prefill_cache(self, row, upto: int, cache_len: int, sp: int,
                          stats=None):
        """Whole-prompt sequence-parallel cold prefill: embed
        ``row[:upto]`` through rounds of ``sp * prefill_chunk`` tokens —
        each round ONE sharded program — instead of the serial chunk
        chain. ceil(upto / (sp*ck)) program dispatches on the TTFT
        critical path where the chunked walk pays ceil(upto / ck).
        Caller holds the mesh context (the programs shard over its sp
        axis) and has resolved ``sp`` via :func:`resolve_sp_prefill`."""
        ck = self.prefill_chunk
        rk = max(ck * sp, sp)
        layers = self.model.cfg.layers
        first = min(rk, upto)
        sb = min(_next_bucket(max(first, sp), self.min_bucket * sp),
                 cache_len)
        pf_fn = self._sp_first_fn(sb, cache_len, sp)
        prompt_op, _ = self._pad_rows([row[:first]], [first], 1, sb)
        cache = pf_fn(self.params, prompt_op, jnp.int32(first))
        if stats is not None:
            stats.record_round(-(-first // max(ck, 1)), sp,
                               ring_hops=layers * sp)
        pos = first
        if pos < upto:
            ext = self._sp_ext_fn(rk, sp)
            while pos < upto:
                n = min(rk, upto - pos)
                chunk_op, _ = self._pad_rows([row[pos:pos + n]], [n], 1, rk)
                cache = ext(self.params, cache, chunk_op, jnp.int32(n))
                if stats is not None:
                    stats.record_round(-(-n // max(ck, 1)), sp)
                pos += n
        return cache

    def _chunked_prefill_cache(self, row, upto: int, cache_len: int,
                               sp: int = 0, stats=None):
        """Embed ``row[:upto]`` into a fresh ``cache_len`` KV cache
        through the fixed-width chunk programs (first + ext): bounded
        attention memory (O(ck x s), not O(s^2)) and O(1) compiled
        programs in prompt length. Requires ``upto > prefill_chunk``;
        the final chunk may be ragged (its padding stays unreachable
        behind the cache index). The ONE chunk-walk shared by the
        prefix cache and the continuous engine's chunked joiner
        prefill — the donation-sensitive ext loop must not fork.
        Caller holds the mesh context.

        ``sp >= 2`` (resolved via :func:`resolve_sp_prefill`) takes the
        whole-prompt sequence-parallel walk instead: same cache result
        (token-for-token), 1/sp the serial program chain."""
        if sp >= 2:
            return self._sp_prefill_cache(row, upto, cache_len, sp,
                                          stats=stats)
        ck = self.prefill_chunk
        pf_fn = self._prefix_first_fn(ck, cache_len)
        prompt_op, _ = self._pad_rows([row[:ck]], [ck], 1, ck)
        cache = pf_fn(self.params, prompt_op, jnp.int32(ck))
        if stats is not None:
            stats.record_round(1, 1)
        ext = self._prefix_ext_fn(ck)
        pos = ck
        while pos < upto:
            n = min(ck, upto - pos)
            chunk_op, _ = self._pad_rows([row[pos:pos + n]], [n], 1, ck)
            cache = ext(self.params, cache, chunk_op, jnp.int32(n))
            if stats is not None:
                stats.record_round(1, 1)
            pos += n
        return cache

    def _prefill_prefix(self, key: str, rows, lengths) -> str:
        cfg = self.model.cfg
        s = lengths[0]
        cache_len = cfg.max_len
        ck = self.prefill_chunk
        with self._mesh_ctx():
            if ck and s > ck:
                cache = self._chunked_prefill_cache(rows[0], s, cache_len)
            else:
                sb = min(_next_bucket(s, self.min_bucket), cfg.max_len)
                pf_fn = self._prefix_first_fn(sb, cache_len)
                prompt_op, _ = self._pad_rows(rows, lengths, 1, sb)
                cache = pf_fn(self.params, prompt_op, jnp.int32(s))
        self.register_prefix(key, cache, s)
        return key

    def _prefix_entry(self, prefix_tokens):
        """(cache, prefix_len) for ``prefix_tokens``, prefilling if absent.
        (Re)ensure + fetch atomically: a concurrent burst of distinct
        prefixes may evict this one between ensure and lookup — retry,
        don't 500."""
        entry = None
        for _ in range(3):
            key = self.cache_prefix(prefix_tokens)  # idempotent fast path
            with self._prefix_lock:
                entry = self._prefixes.get(key)
                if entry is not None:
                    self._prefixes.move_to_end(key)
                    break
        if entry is None:
            raise RuntimeError(
                "prefix cache thrashing: entry evicted immediately after "
                "insert 3x; raise prefix_cache_max")
        return entry

    def _generate_with_prefix(self, prefix_tokens, rows, lengths,
                              max_new_tokens, temperature, top_k, top_p,
                              seed, eos_id, return_logprobs: bool = False):
        """Continue-prefill + decode from a cached prefix KV (batch 1).
        With the float cache, output is exactly `generate(prefix +
        suffix)` — the suffix chunk attends the cached prefix through the
        same masked-attention core, so masked-out padding contributes
        exact zeros either way. Under ``kv_quant`` the prefix KV is read
        back quantized, so parity is to quantization tolerance."""
        import numpy as np

        cfg = self.model.cfg
        if len(rows) != 1:
            raise ValueError("prefix= requires a single prompt row")
        cache, plen = self._prefix_entry(prefix_tokens)
        s = lengths[0]
        self._validate(plen + s, max_new_tokens)
        steps = min(_next_bucket(max_new_tokens, self.min_bucket),
                    self.decode_cap, cfg.max_len - plen - s)
        sbs = min(_next_bucket(s, self.min_bucket),
                  cfg.max_len - plen - steps)
        cache_len = cache_width(cache)

        def build():
            def fn(params, cache, suffix, suffix_len, temperature, top_k,
                   top_p, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                carry = _continue_prefill(self.model, params, cache, suffix,
                                          suffix_len, select, rng, eos_id,
                                          sbs)
                return _scan_decode(self.model, params, select, *carry,
                                    eos_id, steps)

            return jax.jit(fn)

        cont_fn = self._fn_cached(("continue", sbs, steps, cache_len), build)
        suffix_op, _ = self._pad_rows(rows, lengths, 1, sbs)
        args = (self.params, cache, suffix_op, jnp.int32(s),
                *self._knob_operands(temperature, top_k, top_p, seed, eos_id))
        with self._mesh_ctx():
            toks, lps = cont_fn(*args)
        toks = np.asarray(jax.device_get(toks))[:, :max_new_tokens]
        if return_logprobs:
            return toks, np.asarray(jax.device_get(lps))[:, :max_new_tokens]
        return toks

    def _stream_fns(self, b: int, sb: int, cache_len: int, segment: int,
                    sp_prefill: int = 0):
        """Compiled (prefill, segment) pair for streaming. The prefill
        program returns the decode carry; each segment program advances it
        ``segment`` tokens and returns (tokens, carry). Cached like the
        fused programs, so streaming adds at most two programs per
        bucket. ``sp_prefill >= 2`` keys a variant whose prefill member
        ring-shards the prompt's attention over the sp axis (the
        continuous engine's sharded GROUP prefill); the segment member
        is byte-identical to the serial pair's."""
        def build():
            def prefill(params, prompt, length, temperature, top_k, top_p,
                        rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                return _serve_prefill(self.model, params, prompt, length,
                                      select, rng, eos_id,
                                      cache_len=cache_len,
                                      sp_prefill=sp_prefill)

            def seg(params, temperature, top_k, top_p, first, lp, cache,
                    pos, done, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                return _scan_decode(self.model, params, select, first, lp,
                                    cache, pos, done, rng, eos_id, segment,
                                    return_carry=True)

            return (jax.jit(prefill), jax.jit(seg))

        key = (("stream", b, sb, cache_len, segment) if not sp_prefill
               else ("stream", b, sb, cache_len, segment, sp_prefill))
        return self._fn_cached(key, build)

    def _windowed_seg_fn(self, b: int, cache_len: int, window: int,
                         segment: int):
        """Window-bucketed segment decode for the continuous engine: the
        program slices the first ``window`` positions of the B-slot
        cache, runs the segment scan over that NARROW cache — decode
        attention reads ``window`` positions per step instead of
        ``cache_len`` — and writes the advanced window back into the
        full carry. The decode-side twin of prefill's pow-2 bucketing:
        XLA KV reads scale with the live batch's actual context, no
        kernel required. Exactness: the engine only dispatches here when
        every active row's positions stay below ``window`` for the whole
        segment, and positions past a row's index are masked to exact
        zeros either way, so tokens are bitwise the full-window
        program's (asserted in tests). Keyed ("seg_w", ...) in the LRU
        program cache; deliberately not AOT-able (window buckets are
        load-dependent — snapshotting every variant would bloat the
        store for programs that compile in seconds at tiny windows)."""
        def build():
            def seg(params, temperature, top_k, top_p, first, lp, cache,
                    pos, done, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                win = [{name: (val if name == "index"
                               else jax.lax.slice_in_dim(val, 0, window,
                                                         axis=1))
                        for name, val in entry.items()} for entry in cache]
                (toks, lps), carry = _scan_decode(
                    self.model, params, select, first, lp, win, pos, done,
                    rng, eos_id, segment, return_carry=True)
                f2, lp2, wcache, pos2, done2, rng2 = carry
                merged = [
                    {name: (val if name == "index"
                            else jax.lax.dynamic_update_slice_in_dim(
                                cache[i][name], val, 0, axis=1))
                     for name, val in entry.items()}
                    for i, entry in enumerate(wcache)]
                return (toks, lps), (f2, lp2, merged, pos2, done2, rng2)

            return jax.jit(seg)

        return self._fn_cached(("seg_w", b, cache_len, window, segment),
                               build)

    def _spec_seg_fn(self, b: int, cache_len: int, window: int, kb: int):
        """B-slot SPECULATIVE verify segment for the continuous engine:
        one multi-token forward scores each row's pending token plus its
        kb-1 host-drafted proposals through the existing window-bucketed
        segment math (slice the first ``window`` positions, run, merge
        back — :meth:`_windowed_seg_fn`'s shape), then
        :func:`_spec_chain_verify` accepts per row the longest draft
        prefix matching the row's deterministic chain and rolls the
        PRNG walk back past the rejected tail. The carry advances by a
        VARIABLE per-row ``count`` (1..kb): the cache index moves to
        ``pos + count``, so rejected-tail K/V writes sit beyond the
        index in already-garbage positions — unreachable behind the
        validity mask, overwritten by the next chunk before any query
        could expose them (the same rollback-by-index trick the solo
        verify fns use, batched). Same 6-leaf carry as the plain
        segment programs, so the pack/joiner machinery is untouched.
        Keyed ("spec_seg", ...) in the LRU cache; deliberately not
        AOT-able, like every load-dependent window variant."""
        def build():
            def seg(params, temperature, top_k, top_p, draft, tok, lp,
                    cache, pos, done, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                win = cache
                if window < cache_len:
                    win = [{name: (val if name == "index"
                                   else jax.lax.slice_in_dim(
                                       val, 0, window, axis=1))
                            for name, val in entry.items()}
                           for entry in cache]
                # embed a CLAMPED copy of the drafts (an out-of-vocab
                # proposal would gather a NaN fill row, and 0 * NaN
                # through the masked attention poisons every row's
                # output) while verifying against the RAW values — a
                # clamped alias can therefore never be falsely accepted
                chunk = jnp.concatenate(
                    [tok[:, None],
                     jnp.clip(draft, 0, self.model.cfg.vocab_size - 1)],
                    axis=1)
                positions = pos[:, None] + jnp.arange(kb)[None, :]
                logits, new_cache = self.model.apply(
                    params, chunk, positions=positions, cache=win)
                lg = logits.astype(jnp.float32)        # [b, kb, v]
                lps_block, count, tok2, lp2, keys2 = _spec_chain_verify(
                    select, lg, draft, lp, rng)
                pos2 = pos + count
                for entry in new_cache:
                    entry["index"] = pos2
                merged = new_cache
                if window < cache_len:
                    merged = [
                        {name: (val if name == "index"
                                else jax.lax.dynamic_update_slice_in_dim(
                                    cache[i][name], val, 0, axis=1))
                         for name, val in entry.items()}
                        for i, entry in enumerate(new_cache)]
                return ((chunk, lps_block, count, tok2),
                        (tok2, lp2, merged, pos2, done, keys2))

            return jax.jit(seg)

        return self._fn_cached(("spec_seg", b, cache_len, window, kb),
                               build)

    def _mspec_seg_fn(self, b: int, cache_len: int, window: int, kb: int,
                      exit_layer: int):
        """MODEL-DRAFT twin of :meth:`_spec_seg_fn`: before the verify
        chunk, :func:`_shallow_draft` runs ``kb - 1`` shallow-exit
        (``exit_layer`` layers + tied lm_head) greedy steps in-program
        off the row's true carry token, then each row takes its model
        chain or the host-provided draft operand per the ``use_model``
        mask — the per-row provider seam's device half. Host-masked
        positions arrive as RAW ``-1`` in ``draft_host`` with
        ``use_model 0``, so a row drafting fewer than ``kb - 1`` tokens
        (per-row adaptive k) can never have its padding accepted: the
        chain compares raw values and every real chain token is in
        ``[0, vocab)``. Verification is untouched — the same
        :func:`_spec_chain_verify` walk decides acceptance, so emitted
        tokens stay bitwise the non-speculative engine's whatever the
        draft source proposes."""
        def build():
            def seg(params, temperature, top_k, top_p, draft_host,
                    use_model, tok, lp, cache, pos, done, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                win = cache
                if window < cache_len:
                    win = [{name: (val if name == "index"
                                   else jax.lax.slice_in_dim(
                                       val, 0, window, axis=1))
                            for name, val in entry.items()}
                           for entry in cache]
                d_model = _shallow_draft(self.model, params, tok, win,
                                         pos, kb, exit_layer)
                draft = jnp.where(use_model > 0, d_model, draft_host)
                # clamp-for-embedding / compare-raw, as in _spec_seg_fn
                chunk = jnp.concatenate(
                    [tok[:, None],
                     jnp.clip(draft, 0, self.model.cfg.vocab_size - 1)],
                    axis=1)
                positions = pos[:, None] + jnp.arange(kb)[None, :]
                logits, new_cache = self.model.apply(
                    params, chunk, positions=positions, cache=win)
                lg = logits.astype(jnp.float32)        # [b, kb, v]
                lps_block, count, tok2, lp2, keys2 = _spec_chain_verify(
                    select, lg, draft, lp, rng)
                pos2 = pos + count
                for entry in new_cache:
                    entry["index"] = pos2
                merged = new_cache
                if window < cache_len:
                    merged = [
                        {name: (val if name == "index"
                                else jax.lax.dynamic_update_slice_in_dim(
                                    cache[i][name], val, 0, axis=1))
                         for name, val in entry.items()}
                        for i, entry in enumerate(new_cache)]
                return ((chunk, lps_block, count, tok2),
                        (tok2, lp2, merged, pos2, done, keys2))

            return jax.jit(seg)

        return self._fn_cached(
            ("mspec_seg", b, cache_len, window, kb, exit_layer), build)

    # -- paged KV programs (runtime/pagepool.py arena) ------------------------
    #
    # The paged engine's device programs. Each one follows the same
    # shape: gather the rows' pages into the contiguous cache the
    # EXISTING decode/continuation math expects, run that math
    # unchanged, scatter the written pages back — so paged tokens are
    # bitwise the dense engine's by construction (the gathered values
    # ARE the page values, and masked positions contribute exact zeros
    # either way). Keyed in the LRU program cache; deliberately not
    # AOT-able (like the window-bucket variants, they are load-dependent
    # and compile in seconds at engine shapes).

    def _paged_seg_fn(self, b: int, n_pages: int, page: int, window: int,
                      segment: int):
        """Paged segment decode: gather each row's first ``window``
        positions from its block table, run the shared segment scan over
        that contiguous window (the same ``_scan_decode`` every other
        decode path uses), scatter the advanced window back into the
        arena. Composes with window bucketing exactly like
        :meth:`_windowed_seg_fn` — the gather width is the pow-2 window
        of the live batch's max context."""
        def build():
            def seg(params, temperature, top_k, top_p, first, lp, arena,
                    tables, pos, done, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                cache = _gather_page_cache(arena, tables, window, page, pos)
                (toks, lps), carry = _scan_decode(
                    self.model, params, select, first, lp, cache, pos,
                    done, rng, eos_id, segment, return_carry=True)
                f2, lp2, wcache, pos2, done2, rng2 = carry
                new_arena = _scatter_page_cache(arena, tables, wcache, page)
                return (toks, lps), (f2, lp2, new_arena, pos2, done2, rng2)

            return jax.jit(seg)

        return self._fn_cached(("pseg", b, n_pages, page, window, segment),
                               build)

    def _spec_pseg_fn(self, b: int, n_pages: int, page: int, window: int,
                      kb: int):
        """Paged twin of :meth:`_spec_seg_fn`: gather each row's first
        ``window`` positions through its block table, run the same
        verify-chunk math, scatter the written window back. The
        rollback story composes with paging for free: rejected-tail
        writes inside the window land in the row's OWN pages at
        positions beyond its index (overwritten by the next chunk), and
        writes past the row's allocated pages scatter through
        null-padded table entries into the reserved null page — page 0
        absorbs them exactly as it absorbs the dense engine's
        over-decode, so no transient page charge is needed for the
        worst-case k-token advance."""
        def build():
            def seg(params, temperature, top_k, top_p, draft, tok, lp,
                    arena, tables, pos, done, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                cache = _gather_page_cache(arena, tables, window, page,
                                           pos)
                # clamp-for-embedding / compare-raw, as in _spec_seg_fn
                chunk = jnp.concatenate(
                    [tok[:, None],
                     jnp.clip(draft, 0, self.model.cfg.vocab_size - 1)],
                    axis=1)
                positions = pos[:, None] + jnp.arange(kb)[None, :]
                logits, new_cache = self.model.apply(
                    params, chunk, positions=positions, cache=cache)
                lg = logits.astype(jnp.float32)        # [b, kb, v]
                lps_block, count, tok2, lp2, keys2 = _spec_chain_verify(
                    select, lg, draft, lp, rng)
                pos2 = pos + count
                new_arena = _scatter_page_cache(arena, tables, new_cache,
                                                page)
                return ((chunk, lps_block, count, tok2),
                        (tok2, lp2, new_arena, pos2, done, keys2))

            return jax.jit(seg)

        return self._fn_cached(
            ("spec_pseg", b, n_pages, page, window, kb), build)

    def _mspec_pseg_fn(self, b: int, n_pages: int, page: int, window: int,
                       kb: int, exit_layer: int):
        """Paged twin of :meth:`_mspec_seg_fn`: gather the rows' pages
        into the contiguous window, run the in-program shallow-exit
        draft chain over a SCRATCH alias of that gathered window's early
        layers (writes land in throwaway gathered arrays — the arena is
        only ever written by the verify chunk's scatter), then the same
        per-row provider select + chunk verify, and scatter back. The
        null-page-0 over-allocation story is unchanged: only the verify
        chunk's ``new_cache`` reaches :func:`_scatter_page_cache`."""
        def build():
            def seg(params, temperature, top_k, top_p, draft_host,
                    use_model, tok, lp, arena, tables, pos, done, rng,
                    eos_id):
                select = _serve_select(temperature, top_k, top_p)
                cache = _gather_page_cache(arena, tables, window, page,
                                           pos)
                d_model = _shallow_draft(self.model, params, tok, cache,
                                         pos, kb, exit_layer)
                draft = jnp.where(use_model > 0, d_model, draft_host)
                # clamp-for-embedding / compare-raw, as in _spec_seg_fn
                chunk = jnp.concatenate(
                    [tok[:, None],
                     jnp.clip(draft, 0, self.model.cfg.vocab_size - 1)],
                    axis=1)
                positions = pos[:, None] + jnp.arange(kb)[None, :]
                logits, new_cache = self.model.apply(
                    params, chunk, positions=positions, cache=cache)
                lg = logits.astype(jnp.float32)        # [b, kb, v]
                lps_block, count, tok2, lp2, keys2 = _spec_chain_verify(
                    select, lg, draft, lp, rng)
                pos2 = pos + count
                new_arena = _scatter_page_cache(arena, tables, new_cache,
                                                page)
                return ((chunk, lps_block, count, tok2),
                        (tok2, lp2, new_arena, pos2, done, keys2))

            return jax.jit(seg)

        return self._fn_cached(
            ("mspec_pseg", b, n_pages, page, window, kb, exit_layer),
            build)

    def _paged_pack_fn(self, gb: int, n_pages: int, page: int, width: int):
        """Pack row ``src`` of a ``gb``-row contiguous prefill carry into
        batch slot ``slot`` — the scalar leaves via the same
        dynamic-update-slice the dense pack uses, the cache row
        scattered into the slot's block-table pages. Table entries past
        the row's allocation are the null page (the prefill cache is
        zeros there, so the null page just absorbs zeros)."""
        def build():
            def pack(tok, lp, pos, done, keys, group_carry, src, slot,
                     arena, table):
                def upd(b_leaf, g_leaf):
                    row = jax.lax.dynamic_slice_in_dim(g_leaf, src, 1, 0)
                    return jax.lax.dynamic_update_slice_in_dim(
                        b_leaf, row.astype(b_leaf.dtype), slot, 0)

                gtok, glp, gcache, gpos, gdone, gkeys = group_carry
                new5 = (upd(tok, gtok), upd(lp, glp), upd(pos, gpos),
                        upd(done, gdone), upd(keys, gkeys))
                nb = width // page
                new_arena = []
                for aentry, centry in zip(arena, gcache):
                    e = {}
                    for name, val in aentry.items():
                        row = jax.lax.dynamic_slice_in_dim(
                            centry[name], src, 1, 0)[0]  # [width, ...]
                        pages = row.reshape(
                            nb, page, *row.shape[1:]).astype(val.dtype)
                        e[name] = val.at[table].set(pages)
                    new_arena.append(e)
                return new5, new_arena

            return jax.jit(pack)

        return self._fn_cached(("ppack", gb, n_pages, page, width), build)

    def _paged_continue_fn(self, sbs: int, n_pages: int, page: int,
                           window: int):
        """Continue-prefill from SHARED prefix pages: gather the row's
        table (matched prefix pages + freshly allocated suffix pages)
        into a contiguous window, run the one
        :func:`_continue_prefill` every prefix path shares, scatter the
        written suffix back. The prefix pages are read in place and
        written back bitwise-unchanged — this is the zero-copy hit: no
        ``concat_cache_blocks`` assembly, no registered full-window
        duplicate, no peak-HBM spike; the hit's cost is a refcount
        bump plus the suffix prefill the request owes anyway."""
        def build():
            def cont(params, arena, table, plen, suffix, suffix_len,
                     temperature, top_k, top_p, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                cache = _gather_page_cache(arena, table, window, page, plen)
                first, lp0, new_cache, start, done0, keys = \
                    _continue_prefill(self.model, params, cache, suffix,
                                      suffix_len, select, rng, eos_id, sbs)
                new_arena = _scatter_page_cache(arena, table, new_cache,
                                                page)
                return first, lp0, new_arena, start, done0, keys

            return jax.jit(cont)

        return self._fn_cached(("pcont", sbs, n_pages, page, window), build)

    def _lpaged_seg_fn(self, b: int, n_pages: int, page: int, window: int,
                       segment: int):
        """LOGICAL-window twin of :meth:`_paged_seg_fn` (the long-context
        tier, runtime/longctx.py): the block table maps a SLIDING view of
        a context far larger than the compiled ``window`` — slot 0 of the
        gathered cache is logical token ``base`` — so the carry's ``pos``
        is the LOCAL frame (cache writes, validity mask) while RoPE sees
        ``pos + base``, the token's logical position. With ``base = 0``
        this computes exactly what :meth:`_paged_seg_fn` computes (int32
        ``+ 0`` is exact); the host slides ``base`` by whole pages
        between segments, spilling evicted pages to the offload arena."""
        def build():
            def seg(params, temperature, top_k, top_p, first, lp, arena,
                    tables, local, base, done, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                cache = _gather_page_cache(arena, tables, window, page,
                                           local)
                (toks, lps), carry = _scan_decode(
                    self.model, params, select, first, lp, cache, local,
                    done, rng, eos_id, segment, return_carry=True,
                    pos_offset=base)
                f2, lp2, wcache, local2, done2, rng2 = carry
                new_arena = _scatter_page_cache(arena, tables, wcache,
                                                page)
                return (toks, lps), (f2, lp2, new_arena, local2, done2,
                                     rng2)

            return jax.jit(seg)

        return self._fn_cached(("lpseg", b, n_pages, page, window, segment),
                               build)

    def _lpaged_continue_fn(self, sbs: int, n_pages: int, page: int,
                            window: int):
        """LOGICAL-window twin of :meth:`_paged_continue_fn`: continue a
        windowed prefill from the view's filled head — the gathered
        window holds logical tokens ``[base, base + local)``, the suffix
        chunk lands at local positions ``[local, local + suffix_len)``
        with RoPE at their LOGICAL positions. Chained over chunks (the
        host sliding ``base`` between them) this is the long-context
        prefill schedule; with ``base = 0`` and one chunk it computes
        exactly the paged continuation."""
        def build():
            def cont(params, arena, table, local, base, suffix,
                     suffix_len, temperature, top_k, top_p, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                cache = _gather_page_cache(arena, table, window, page,
                                           local)
                first, lp0, new_cache, start, done0, keys = \
                    _continue_prefill(self.model, params, cache, suffix,
                                      suffix_len, select, rng, eos_id,
                                      sbs, pos_offset=base)
                new_arena = _scatter_page_cache(arena, table, new_cache,
                                                page)
                return first, lp0, new_arena, start, done0, keys

            return jax.jit(cont)

        return self._fn_cached(("lpcont", sbs, n_pages, page, window),
                               build)

    def _lsp_round_fn(self, n_chunks: int, n_pages: int, page: int,
                      window: int, sp: int):
        """Paged twin of the whole-prompt sp-prefill family for the
        long-context tier: ONE sharded program runs ``n_chunks`` of the
        serial window/2 slide schedule as a single ROUND. The gathered
        UNION view holds the prior half-window (``prior_len`` tokens —
        0 on round 0) followed by the round's ``n_chunks * window/2``
        tokens; ``band = window/2`` restricts every query to exactly the
        keys its serial chunk would have seen resident, RoPE sees
        logical positions via ``base``, and the written KV scatters
        straight back into the arena pages (prior pages come back
        bitwise-unchanged, the validated ``_page_write_fn``-shaped
        per-page layout). The S/(window/2) serial chain collapses to
        ceil(S / (sp * window/2)) rounds."""
        w2 = window // 2
        rbs = n_chunks * w2       # round token width
        uw = (n_chunks + 1) * w2  # union view: prior half-window + round

        def build():
            def rnd(params, arena, table, prior_len, base, chunk,
                    round_len, temperature, top_k, top_p, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                cache = _gather_page_cache(arena, table, uw, page,
                                           prior_len)
                first, lp0, new_cache, start, done0, keys = \
                    _continue_prefill(self.model, params, cache, chunk,
                                      round_len, select, rng, eos_id,
                                      rbs, pos_offset=base - prior_len,
                                      sp_prefill=sp, band=w2)
                new_arena = _scatter_page_cache(arena, table, new_cache,
                                                page)
                return first, lp0, new_arena, start, done0, keys

            return jax.jit(rnd)

        return self._fn_cached(
            ("sp_pprefill", n_chunks, n_pages, page, window, sp), build)

    def _paged_gather_fn(self, n_pages: int, page: int, window: int):
        """Read-only page gather -> contiguous single-row cache (index
        attached): the prefix store's extend path continues a cold walk
        from cached pages without any host-visible assembly."""
        def build():
            def g(arena, table, index):
                return _gather_page_cache(arena, table, window, page,
                                          index)

            return jax.jit(g)

        return self._fn_cached(("pgather", n_pages, page, window), build)

    def _page_write_fn(self, n_pages: int, page: int):
        """Write one block's per-layer KV slices (as
        :func:`slice_cache_blocks` returns) into arena page ``pid`` —
        the prefix store's insertion primitive (one program total; the
        page id is a traced operand)."""
        def build():
            def w(arena, pid, block_kv):
                new = []
                for aentry, bentry in zip(arena, block_kv):
                    e = {}
                    for name, val in aentry.items():
                        blk = bentry[name].reshape(
                            1, page, *val.shape[2:]).astype(val.dtype)
                        e[name] = jax.lax.dynamic_update_slice(
                            val, blk, (pid,) + (0,) * (val.ndim - 1))
                    new.append(e)
                return new

            return jax.jit(w)

        return self._fn_cached(("pwrite", n_pages, page), build)

    def _stream_prefix_fn(self, sbs: int, cache_len: int | None = None):
        """Continue-prefill program for streaming-from-a-cached-prefix:
        same continuation math as the fused prefix path, but returns the
        decode CARRY so segment programs take over (the combination the
        VERDICT r3 called out: TTFT and KV reuse were mutually
        exclusive). By default the carry's cache is the prefix cache's
        full-window size, pairing with segment programs keyed at
        cache_len=max_len; a non-None ``cache_len`` keys a separate
        program for continuation over a smaller cache (the continuous
        engine's chunked joiner prefill) — sharing the default key
        would collide with its shape-strict AOT executable."""
        def build():
            def cont(params, cache, suffix, suffix_len, temperature, top_k,
                     top_p, rng, eos_id):
                select = _serve_select(temperature, top_k, top_p)
                return _continue_prefill(self.model, params, cache, suffix,
                                         suffix_len, select, rng, eos_id,
                                         sbs)

            return jax.jit(cont)

        key = (("stream_prefix", sbs) if cache_len is None
               else ("stream_prefix", sbs, cache_len))
        return self._fn_cached(key, build)

    def _generate_stream_with_prefix(self, prefix_tokens, rows, lengths,
                                     max_new_tokens, temperature, top_k,
                                     top_p, seed, eos_id, segment,
                                     return_logprobs):
        """Streaming decode from a cached prefix KV (batch 1): one
        continue-prefill of the suffix, then the same segment walk as
        :meth:`generate_stream`. Token/RNG parity with the fused
        ``generate(prefix=...)`` path is exact — the continuation and the
        per-step RNG walk are identical, segments only change where the
        host observes them."""
        import numpy as np

        cfg = self.model.cfg
        if len(rows) != 1:
            raise ValueError("prefix= streaming requires a single row")
        cache, plen = self._prefix_entry(prefix_tokens)
        s = lengths[0]
        self._validate(plen + s, max_new_tokens)
        sbs = min(_next_bucket(s, self.min_bucket), cfg.max_len - plen)
        cache_len = cache_width(cache)
        cont = self._stream_prefix_fn(sbs)
        _, seg = self._stream_fns(1, sbs, cache_len, segment)
        suffix_op, _ = self._pad_rows(rows, lengths, 1, sbs)
        *knobs, key, eos = self._knob_operands(temperature, top_k, top_p,
                                               seed, eos_id)
        with self._mesh_ctx():
            carry = cont(self.params, cache, suffix_op, jnp.int32(s),
                         *knobs, key, eos)
            emitted = 0
            while emitted < max_new_tokens:
                (toks, lps), carry = seg(self.params, *knobs, *carry, eos)
                chunk = np.asarray(jax.device_get(toks))
                take = min(chunk.shape[1], max_new_tokens - emitted)
                emitted += take
                if return_logprobs:
                    lp_chunk = np.asarray(jax.device_get(lps))
                    yield chunk[:, :take], lp_chunk[:, :take]
                else:
                    yield chunk[:, :take]
                if eos_id is not None:
                    done = np.asarray(jax.device_get(carry[4]))
                    if bool(done.all()):
                        return

    def generate_stream(self, prompt_tokens, *, max_new_tokens: int,
                        temperature: float = 0.0, top_k: int | None = None,
                        top_p: float | None = None, seed: int = 0,
                        eos_id: int | None = None, segment: int = 16,
                        prefix=None, return_logprobs: bool = False):
        """Streaming :meth:`generate`: yields ``[b, k]`` numpy chunks
        (k <= segment) as they decode — ``(tokens, logprobs)`` pairs when
        ``return_logprobs`` — stopping early once every row has latched
        eos. Concatenated chunks are EXACTLY the fused ``generate``
        output prefix — the segment boundaries don't change the RNG
        walk, so a seeded sampled stream matches its non-streamed twin
        token for token. Time-to-first-token is one prefill plus one
        segment instead of the whole decode. ``prefix=`` streams from a
        cached prefix KV (single row), combining TTFT with KV reuse."""
        import numpy as np

        cfg = self.model.cfg
        rows, lengths = self._normalize_prompts(prompt_tokens)
        b, s = len(rows), max(lengths)
        if max_new_tokens == 0:
            # nothing to emit: skip the device work (the prefix path's
            # continue-prefill would otherwise compile + run for nothing)
            self._validate(s, max_new_tokens)
            return
        if prefix is not None:
            segment = max(1, min(int(segment), max(1, max_new_tokens)))
            yield from self._generate_stream_with_prefix(
                prefix, rows, lengths, max_new_tokens, temperature, top_k,
                top_p, seed, eos_id, segment, return_logprobs)
            return
        self._validate(s, max_new_tokens)
        segment = max(1, min(int(segment), max(1, max_new_tokens)))
        # same bucketing discipline as generate(): pow-2 prompt bucket
        # (shrinking toward the exact prompt near max_len), batch
        # bucketed, and the SEGMENT COUNT pow-2 bucketed too — cache_len
        # is part of the compiled-program key, so without it every
        # distinct ceil(max_new/segment) would compile a fresh pair.
        # Only ceil(max_new/segment) segments ever run; the bucketed
        # extras just size the cache. The last segment may run past
        # max_new_tokens; those tail tokens are discarded, and any of
        # their cache writes that would land past max_len are
        # scatter-dropped — every KEPT token attends an in-bounds cache
        # (length_r + max_new <= max_len is validated above).
        n_needed = -(-max_new_tokens // segment)
        n_segs = _next_bucket(n_needed, 1)
        if s + n_segs * segment > cfg.max_len:
            n_segs = n_needed  # shrink toward exact near the boundary
        sb = max(s, min(_next_bucket(s, self.min_bucket),
                        cfg.max_len - n_segs * segment))
        bb = _next_bucket(b, 1)
        cache_len = min(sb + n_segs * segment, cfg.max_len)
        prefill, seg = self._stream_fns(bb, sb, cache_len, segment)
        prompt_op, length_op = self._pad_rows(rows, lengths, bb, sb)
        *knobs, key, eos = self._knob_operands(temperature, top_k, top_p,
                                               seed, eos_id, b=bb)
        with self._mesh_ctx():
            carry = prefill(self.params, prompt_op, length_op,
                            *knobs, key, eos)
            emitted = 0
            while emitted < max_new_tokens:
                (toks, lps), carry = seg(self.params, *knobs, *carry, eos)
                chunk = np.asarray(jax.device_get(toks))[:b]
                take = min(chunk.shape[1], max_new_tokens - emitted)
                emitted += take
                if return_logprobs:
                    lp_chunk = np.asarray(jax.device_get(lps))[:b]
                    yield chunk[:, :take], lp_chunk[:, :take]
                else:
                    yield chunk[:, :take]
                # all real rows latched eos -> nothing more can be
                # emitted. Fetch the done flags only when eos is active:
                # each fetch is a host round trip per segment, pure waste
                # without an eos to latch.
                if eos_id is not None:
                    done = np.asarray(jax.device_get(carry[4]))[:b]
                    if bool(done.all()):
                        return

    # -- speculative decoding ------------------------------------------------

    def _spec_verify_fn(self, kb: int, cache_len: int):
        """Compiled verify step for speculative decoding: run the pending
        token + kb-1 draft tokens as ONE multi-token chunk (the scalar-
        index continuation branch of the cache), greedily re-derive the
        true successor at every position, and accept the longest draft
        prefix that matches. Emits 1..kb tokens per WEIGHT READ — decode
        is weight-bytes-bound, so accepted drafts are nearly free, which
        is the only way past the 1-token-per-read decode roofline.
        Rollback after partial acceptance is just the cache index: the
        attention validity mask never exposes entries past it, so the
        stale K/V written for rejected drafts is unreachable."""
        def build():
            def vf(params, draft, tok, cache):
                idx = cache[0]["index"].reshape(())  # scalar-index branch
                cache = [{**c, "index": idx} for c in cache]
                chunk = jnp.concatenate(
                    [tok.reshape(1, 1), draft[:, :kb - 1]], axis=1)
                positions = (idx + jnp.arange(kb))[None, :]
                logits, new_cache = self.model.apply(
                    params, chunk, positions=positions, cache=cache)
                lg = logits[0].astype(jnp.float32)          # [kb, v]
                g = jnp.argmax(lg, axis=-1).astype(jnp.int32)  # true succ.
                ok = (g[:kb - 1] == draft[0, :kb - 1]).astype(jnp.int32)
                m = jnp.sum(jnp.cumprod(ok))                # 0..kb-1
                count = m + 1          # emitted: [tok, d_0..d_{m-1}]
                # logprob of the GREEDY token at each position: equals
                # the accepted draft's logprob where drafts match, and is
                # the right value for the new pending token where the
                # draft was rejected
                logz = jax.nn.logsumexp(lg, axis=-1)
                lp_g = jnp.take_along_axis(
                    lg, g[:, None], axis=1)[:, 0] - logz
                new_tok = jax.lax.dynamic_slice(g, (m,), (1,))
                new_idx = idx + count
                for entry in new_cache:
                    entry["index"] = new_idx
                return chunk[0], lp_g, count, new_tok, new_cache

            return jax.jit(vf)

        return self._fn_cached(("spec", kb, cache_len), build)

    def _spec_steps(self, rows, max_new_tokens: int, kb: int, eos_id,
                    ngram_max: int, stats_out: dict, prefix=None,
                    prefix_entry=None, temperature: float = 0.0,
                    top_k=None, top_p=None, seed: int = 0):
        """The speculative verify loop as a per-step generator: yields
        ``(tokens, logprobs)`` LISTS per verify step (1..kb tokens each —
        the accepted draft prefix plus the corrected token), filling
        ``stats_out`` with the acceptance counters as it goes. Both the
        fused :meth:`generate_speculative` and the streaming
        :meth:`generate_speculative_stream` consume this one loop, so
        their emitted tokens agree by construction. With ``prefix`` the
        initial carry comes from the cached prefix KV's continuation
        program (only the suffix prefills; the prefix tokens still feed
        the lookup-draft context — a shared system prompt is prime
        n-gram material)."""
        cfg = self.model.cfg
        s = len(rows[0])
        cache_len = cfg.max_len
        sampled = (temperature or 0.0) > 0.0
        # the prefill/continuation selects the FIRST pending token under
        # the request's own knobs (greedy callers pass t=0 -> argmax)
        knobs = self._knob_operands(temperature, top_k, top_p, seed, None)
        with self._mesh_ctx():
            if prefix is not None:
                # the caller already fetched the entry for validation —
                # don't re-hash the (possibly long) prefix per request
                pcache, plen = (prefix_entry if prefix_entry is not None
                                else self._prefix_entry(prefix))
                sbs = min(_next_bucket(s, self.min_bucket),
                          cfg.max_len - plen)
                cont = self._stream_prefix_fn(sbs)
                suffix_op, _ = self._pad_rows(rows, [s], 1, sbs)
                tok, lp0, cache, _pos, _done, _rng = cont(
                    self.params, pcache, suffix_op, jnp.int32(s), *knobs)
                context0 = [int(t) for t in
                            jnp.asarray(prefix).reshape(-1).tolist()] \
                    + list(map(int, rows[0]))
            else:
                sb = min(_next_bucket(s, self.min_bucket), cache_len)
                # prefill keyed at the streaming default segment: the
                # prefill program does not depend on the segment size,
                # so every k (and the streaming path itself) shares ONE
                # compiled prefill per bucket instead of compiling a
                # byte-identical copy per k
                prefill, _ = self._stream_fns(1, sb, cache_len, 16)
                prompt_op, length_op = self._pad_rows(rows, [s], 1, sb)
                tok, lp0, cache, _pos, _done, _rng = prefill(
                    self.params, prompt_op, length_op, *knobs)
                context0 = list(map(int, rows[0]))
        if sampled:
            vf = self._spec_sampled_verify_fn(kb, cache_len)
            t_op = jnp.float32(temperature)  # the verify fn clamps
            k_op = jnp.int32(top_k if top_k is not None else 0)
            p_op = jnp.float32(top_p if top_p is not None else 1.0)
            # verify-step randomness: its own seed-derived stream (the
            # draw STRUCTURE differs from plain sampling, so bitwise
            # parity is impossible by construction; determinism per
            # seed is the contract)
            base_key = jax.random.fold_in(
                jax.random.PRNGKey(int(seed)), 1)
        else:
            vf = self._spec_verify_fn(kb, cache_len)
        # normalize the prefill cache's per-row (1,) index to the scalar
        # the verify fn itself writes: without this the first vf call
        # traces a second shape variant, doubling the (multi-second
        # remote) warm compile per ('spec', kb, cache_len) key (ADVICE r4)
        cache = [{**c, "index": c["index"].reshape(())} for c in cache]
        pending, pending_lp = (
            float(x) for x in jax.device_get((tok[0], lp0[0])))
        pending = int(pending)
        emitted = 0
        context = context0
        generated: list[int] = []
        steps = 0
        while emitted < max_new_tokens:
            draft, draft_hit = _lookup_draft_hit(context + [pending], kb,
                                                 ngram_max=ngram_max)
            draft_op = jnp.asarray([draft], jnp.int32)
            with self._mesh_ctx():
                if sampled:
                    step_keys = jax.random.split(
                        jax.random.fold_in(base_key, steps), kb)
                    chunk, lp_next, count, new_tok, cache = vf(
                        self.params, draft_op, tok, cache, t_op, k_op,
                        p_op, step_keys)
                else:
                    chunk, lp_next, count, new_tok, cache = vf(
                        self.params, draft_op, tok, cache)
            chunk_h, lp_h, cnt, new_h = jax.device_get(
                (chunk, lp_next, count, new_tok))
            cnt = int(cnt)
            steps += 1
            toks_step = [int(t) for t in chunk_h[:cnt]]
            lps_step = [pending_lp] + [float(x) for x in lp_h[:cnt - 1]]
            emitted += cnt
            generated.extend(toks_step)
            pending, pending_lp = int(new_h[0]), float(lp_h[cnt - 1])
            tok = new_tok
            context = context0 + generated
            stats_out.update(
                {"steps": steps, "emitted": emitted,
                 "tokens_per_step": round(emitted / max(1, steps), 2),
                 "k": kb})
            # the cumulative /metrics surface (shared with the engine's
            # spec mode): proposals = the kb-1 drafts, accepted = the
            # cnt-1 that matched, emitted = accepted + the corrected
            # token the step owes regardless
            self.spec_metrics.record_step(
                proposed=kb - 1, accepted=cnt - 1, emitted=cnt,
                hit=draft_hit)
            yield toks_step, lps_step
            if eos_id is not None and eos_id in toks_step:
                return

    def generate_speculative_stream(self, prompt_tokens, *,
                                    max_new_tokens: int, k: int = 8,
                                    eos_id: int | None = None,
                                    return_logprobs: bool = False,
                                    ngram_max: int = 3,
                                    prefix=None,
                                    temperature: float = 0.0,
                                    top_k: int | None = None,
                                    top_p: float | None = None,
                                    seed: int = 0,
                                    stats_out: dict | None = None):
        """Streaming speculative decode (VERDICT r5 weak #2 composition):
        each verify step's ACCEPTED chunk is a stream segment, so
        time-to-first-token is one prefill plus one verify step — the
        TTFT-sensitive streamed traffic is exactly where lookup
        speculation pays most. Yields ``[1, c]`` arrays (1 <= c <= k;
        ``(tokens, logprobs)`` pairs when asked). Concatenated chunks
        equal :meth:`generate_speculative`'s output up to and including
        the first eos (the fused path then pads with eos filler) and are
        truncated at ``max_new_tokens``. Pass ``stats_out={}`` to
        receive the acceptance counters (thread-safe, unlike
        ``spec_stats``)."""
        import numpy as np

        cfg = self.model.cfg
        rows, lengths = self._normalize_prompts(prompt_tokens)
        if len(rows) != 1:
            raise ValueError("speculative decoding is single-row")
        s = lengths[0]
        plen, pentry = 0, None
        if prefix is not None:
            pentry = self._prefix_entry(prefix)
            plen = pentry[1]
        self._validate(plen + s, max_new_tokens)
        kb = max(2, _next_bucket(max(2, int(k)), 2))
        stats = {} if stats_out is None else stats_out
        if max_new_tokens == 0 or \
                plen + s + max_new_tokens + kb > cfg.max_len:
            # no room for a full verify chunk near the context boundary:
            # stream plain decode instead (same fallback as the fused
            # path, segment-bounded TTFT)
            stats.update({"fallback": "plain", "steps": max_new_tokens,
                          "emitted": max_new_tokens,
                          "tokens_per_step": 1.0, "k": kb})
            self.spec_metrics.record_fallback("near_window")
            yield from self.generate_stream(
                rows[0], max_new_tokens=max_new_tokens, eos_id=eos_id,
                prefix=prefix, temperature=temperature, top_k=top_k,
                top_p=top_p, seed=seed, return_logprobs=return_logprobs)
            return
        emitted = 0
        for toks_step, lps_step in self._spec_steps(
                rows, max_new_tokens, kb, eos_id, ngram_max, stats,
                prefix=prefix, prefix_entry=pentry,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=seed):
            take = min(len(toks_step), max_new_tokens - emitted)
            if take <= 0:
                return
            chunk, lp_chunk = toks_step[:take], lps_step[:take]
            # stop at the row's eos: deliver through it, drop the rest
            if eos_id is not None and eos_id in chunk:
                cut = chunk.index(eos_id) + 1
                chunk, lp_chunk = chunk[:cut], lp_chunk[:cut]
            emitted += len(chunk)
            arr = np.asarray([chunk], np.int32)
            if return_logprobs:
                yield arr, np.asarray([lp_chunk], np.float32)
            else:
                yield arr
            if eos_id is not None and eos_id in chunk:
                return

    def _spec_sampled_verify_fn(self, kb: int, cache_len: int):
        """Compiled verify step for SAMPLED speculative decoding: one
        multi-token forward over the pending token + kb-1 drafts, then
        the delta-proposal rejection core (:func:`_spec_accept_resample`)
        under per-request runtime knobs. Same cache-rollback-by-index
        trick as the greedy verify; the emitted sequence is exactly
        target-chain distributed (not bitwise the non-speculative
        sampled stream — the draw structure differs — but
        seed-deterministic within the speculative path)."""
        def build():
            def vf(params, draft, tok, cache, temperature, top_k, top_p,
                   keys):
                idx = cache[0]["index"].reshape(())
                cache = [{**c, "index": idx} for c in cache]
                chunk = jnp.concatenate(
                    [tok.reshape(1, 1), draft[:, :kb - 1]], axis=1)
                positions = (idx + jnp.arange(kb))[None, :]
                logits, new_cache = self.model.apply(
                    params, chunk, positions=positions, cache=cache)
                lg = logits[0].astype(jnp.float32)          # [kb, v]
                t = jnp.maximum(temperature, jnp.float32(1e-6))
                filt = filter_logits_runtime(lg / t, top_k, top_p)
                probs = jax.nn.softmax(filt, axis=-1)
                m, new_tok = _spec_accept_resample(
                    probs, draft[0, :kb - 1], keys)
                count = m + 1  # emitted: [tok, d_0..d_{m-1}]
                # raw model logprobs of the EMITTED tokens: the accepted
                # drafts at their positions, the fresh draw at position
                # m (knob-independent log_softmax, like every other path)
                logz = jax.nn.logsumexp(lg, axis=-1)
                lp_draft = jnp.take_along_axis(
                    lg[: kb - 1], draft[0, : kb - 1, None],
                    axis=1)[:, 0] - logz[: kb - 1]
                lp_out = jnp.where(
                    jnp.arange(kb) < m,
                    jnp.concatenate([lp_draft, jnp.zeros((1,))]),
                    jnp.float32(0.0))
                lp_new = jnp.take(lg[m], new_tok) - logz[m]
                lp_out = lp_out.at[m].set(lp_new)
                new_idx = idx + count
                for entry in new_cache:
                    entry["index"] = new_idx
                return (chunk[0], lp_out, count, new_tok.reshape(1),
                        new_cache)

            return jax.jit(vf)

        return self._fn_cached(("spec_s", kb, cache_len), build)

    def generate_speculative(self, prompt_tokens, *, max_new_tokens: int,
                             k: int = 8, eos_id: int | None = None,
                             return_logprobs: bool = False,
                             return_stats: bool = False,
                             ngram_max: int = 3, prefix=None,
                             temperature: float = 0.0,
                             top_k: int | None = None,
                             top_p: float | None = None, seed: int = 0):
        """Decode with prompt-lookup speculative verification (single
        row). Greedy by default: in exact arithmetic the output is
        BITWISE :meth:`generate`'s greedy output — speculation only
        changes how many tokens each weight read verifies, never the
        argmax — and the CPU f32 tests assert that equality. With
        ``temperature > 0`` the verify step runs delta-proposal
        REJECTION SAMPLING (:func:`_spec_accept_resample`): the emitted
        sequence is exactly target-chain distributed and deterministic
        per seed, but its draw structure necessarily differs from the
        non-speculative sampled stream, so the same seed yields a
        different (equally valid) sample than plain sampling. On bf16 hardware an
        argmax whose top-2 logit gap sits below bf16 resolution can
        break differently between the chunked verification forward and
        the one-token step (measured on v5e at 8B: first divergence at a
        0.006 logit gap); every emitted token is still the argmax of a
        forward over the correct emitted prefix, i.e. the result is a
        valid greedy decode under the chunked forward's numerics — the
        same caveat class as batch-shape-dependent reductions. Returns
        the same ``[1, max_new_tokens]`` array (plus logprobs when
        asked), with ``self.spec_stats`` recording the step/acceptance
        counters of the last call."""
        import numpy as np

        cfg = self.model.cfg
        rows, lengths = self._normalize_prompts(prompt_tokens)
        if len(rows) != 1:
            raise ValueError("speculative decoding is single-row")
        s = lengths[0]
        plen, pentry = 0, None
        if prefix is not None:
            pentry = self._prefix_entry(prefix)
            plen = pentry[1]
        self._validate(plen + s, max_new_tokens)
        kb = max(2, _next_bucket(max(2, int(k)), 2))
        if max_new_tokens == 0 or \
                plen + s + max_new_tokens + kb > cfg.max_len:
            # no room for a full verify chunk near the context boundary
            out = self.generate(rows[0], max_new_tokens=max_new_tokens,
                                eos_id=eos_id, prefix=prefix,
                                temperature=temperature, top_k=top_k,
                                top_p=top_p, seed=seed,
                                return_logprobs=return_logprobs)
            stats = {"fallback": "plain", "steps": max_new_tokens,
                     "emitted": max_new_tokens, "tokens_per_step": 1.0,
                     "k": kb}
            self.spec_stats = stats
            self.spec_metrics.record_fallback("near_window")
            return (out, stats) if return_stats else out
        emitted: list[int] = []
        lps: list[float] = []
        stats: dict = {}
        for toks_step, lps_step in self._spec_steps(
                rows, max_new_tokens, kb, eos_id, ngram_max, stats,
                prefix=prefix, prefix_entry=pentry,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=seed):
            emitted.extend(toks_step)
            lps.extend(lps_step)
        # kept as a convenience for single-threaded callers/tests; the
        # thread-safe channel is return_stats (a threaded server must not
        # read another request's counters)
        self.spec_stats = stats
        toks = emitted[:max_new_tokens]
        lps = lps[:max_new_tokens]
        # eos latch parity with the fused path: truncate + fill
        if eos_id is not None and eos_id in toks:
            cut = toks.index(eos_id) + 1
            toks = toks[:cut] + [eos_id] * (max_new_tokens - cut)
            lps = lps[:cut] + [0.0] * (max_new_tokens - cut)
        # pad (loop may break early only on eos; otherwise it fills)
        toks += [eos_id if eos_id is not None else 0] * \
            (max_new_tokens - len(toks))
        lps += [0.0] * (max_new_tokens - len(lps))
        out = np.asarray([toks], np.int32)
        if return_logprobs:
            out = (out, np.asarray([lps], np.float32))
        return (out, stats) if return_stats else out

    @staticmethod
    def _normalize_prompts(prompt_tokens):
        """-> (list of 1-D int32 row arrays, list of true lengths)."""
        import numpy as np

        if isinstance(prompt_tokens, (list, tuple)) and prompt_tokens and \
                isinstance(prompt_tokens[0], (list, tuple, np.ndarray)):
            rows = [np.asarray(r, np.int32).reshape(-1) for r in prompt_tokens]
        else:
            ids = np.asarray(prompt_tokens, np.int32)
            rows = list(ids[None, :] if ids.ndim == 1 else ids)
        if not rows or any(len(r) < 1 for r in rows):
            raise ValueError("empty prompt")
        return rows, [len(r) for r in rows]


def _decode(model: LlamaModel, params, prompt_tokens, *, max_new_tokens: int,
            max_len: int | None, select_fn, rng, eos_id: int | None):
    """Shared decode loop: prefill once, then ``lax.scan`` one compiled
    step per token; ``select_fn(logits_f32, rng) -> (token ids, logprobs)``.
    Returns token ids only (the legacy generate API)."""
    cfg = model.cfg
    b, s = prompt_tokens.shape
    max_len = max_len or min(cfg.max_len, s + max_new_tokens)

    logits, prefill_cache = model.apply(
        params, prompt_tokens,
        logit_positions=jnp.full((b,), s - 1, jnp.int32))
    cache = prefill_into_cache(cfg, prefill_cache, b, max_len, s)
    # per-row PRNG chains (row r = fold_in of the caller's key), the same
    # scheme the serving path uses (_knob_operands)
    keys = jax.vmap(lambda r: jax.random.fold_in(rng, r))(jnp.arange(b))
    keys, subs = _split_rows(keys)
    first_token, lp0 = select_fn(logits[:, -1, :].astype(jnp.float32), subs)
    eos = jnp.int32(-1 if eos_id is None else eos_id)
    done0 = (eos >= 0) & (first_token == eos)
    toks, _ = _scan_decode(model, params, select_fn, first_token, lp0, cache,
                           jnp.int32(s), done0, keys, eos, max_new_tokens)
    return toks


def greedy_generate(model: LlamaModel, params, prompt_tokens, *, max_new_tokens: int,
                    max_len: int | None = None, eos_id: int | None = None):
    """Greedy decode. prompt_tokens: [b, s] int32 -> [b, max_new_tokens].
    After ``eos_id`` (when given) a sequence keeps emitting ``eos_id``."""

    def select(logits, _rng):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return tok, _token_logprob(logits, tok)

    return _decode(model, params, prompt_tokens, max_new_tokens=max_new_tokens,
                   max_len=max_len, select_fn=select,
                   rng=jax.random.PRNGKey(0), eos_id=eos_id)


def sample_generate(model: LlamaModel, params, prompt_tokens, *, rng,
                    max_new_tokens: int, temperature: float = 1.0,
                    top_k: int | None = None, top_p: float | None = None,
                    max_len: int | None = None, eos_id: int | None = None):
    """Stochastic decode: temperature + top-k + nucleus filtering, one
    categorical draw per step from the shared ``lax.scan`` loop.
    temperature <= 0 degrades to greedy."""
    if temperature <= 0.0:
        return greedy_generate(model, params, prompt_tokens,
                               max_new_tokens=max_new_tokens, max_len=max_len,
                               eos_id=eos_id)

    def select(logits, keys):
        filt = filter_logits(logits / jnp.float32(temperature),
                             top_k=top_k, top_p=top_p)
        tok = jax.vmap(jax.random.categorical)(keys, filt).astype(jnp.int32)
        return tok, _token_logprob(logits, tok)

    return _decode(model, params, prompt_tokens, max_new_tokens=max_new_tokens,
                   max_len=max_len, select_fn=select, rng=rng, eos_id=eos_id)
