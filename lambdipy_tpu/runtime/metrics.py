"""Serve metrics: invoke latency percentiles + cold-start breakdown.

SURVEY.md §6 metrics row: the reference has stdout echo only; the rebuild
keeps p50/p99 and cold-start stage timings as first-class, exported on
``/metrics`` as JSON.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field


@dataclass
class LatencyStats:
    """Bounded reservoir of recent latencies (ms) with percentile report."""

    capacity: int = 2048
    samples: list[float] = field(default_factory=list)
    count: int = 0
    errors: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record(self, ms: float) -> None:
        with self._lock:
            self.count += 1
            if len(self.samples) >= self.capacity:
                self.samples[self.count % self.capacity] = ms
            else:
                self.samples.append(ms)

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def percentile(self, q: float) -> float | None:
        with self._lock:
            if not self.samples:
                return None
            s = sorted(self.samples)
            idx = min(len(s) - 1, max(0, round(q / 100.0 * (len(s) - 1))))
            return s[idx]

    def report(self) -> dict:
        return {
            "count": self.count,
            "errors": self.errors,
            "p50_ms": self.percentile(50),
            "p90_ms": self.percentile(90),
            "p99_ms": self.percentile(99),
        }
