"""Automatic cross-request prefix KV cache: radix reuse for the serve path.

Real generate traffic is dominated by shared prompt prefixes — system
prompts, few-shot templates, multi-turn histories — and prefill is the
compute-bound axis of TPU serving (round 5 measured dense 8B prefill at
57-76% MFU). Before this module the repo only reused a prefix when the
CLIENT shipped the prefix token ids explicitly (``prefix=`` requests);
every ordinary request re-prefilled its whole prompt. :class:`PrefixStore`
makes reuse automatic and transparent, in the style of SGLang's
RadixAttention / vLLM's automatic prefix caching:

- The store keeps a RADIX TREE keyed by fixed-width token blocks. A node
  at depth d holds the KV slice (store layout — float, or int8 + scales
  under ``kv_quant``) for its own block at absolute positions
  ``[d*block, (d+1)*block)``; KV is position-dependent (RoPE is applied
  before the cache store), so depth pins position by construction.
- On arrival :meth:`route` longest-prefix-matches the prompt against the
  tree in whole blocks (capped so at least one suffix token remains for
  the continuation to select from). Matched blocks are assembled into a
  full-window decode cache (``models/llama.py concat_cache_blocks``) and
  registered in the server's prefix-entry LRU, so every EXISTING
  ``prefix=`` path — fused, streaming, continuous-engine join,
  speculative — serves the suffix-only continuation unchanged.
- Unmatched whole blocks are prefilled HERE, through the server's
  fixed-width chunk programs (the same first/ext family chunked prefill
  uses), and their slices inserted into the tree as the walk goes: the
  request's own prefill IS the insertion, so a cold prefix costs one
  prefill total and every later request extends the match for free.
  Concurrent first requests for the same target path collapse to one
  device walk (per-key inflight events, like ``cache_prefix``).
- An HBM budget bounds the tree: block bytes are accounted exactly from
  the stored leaves, and inserts beyond the budget evict
  least-recently-used LEAF nodes (evicting an interior node would orphan
  the positions after it). Counters ride
  :class:`lambdipy_tpu.runtime.metrics.PrefixCacheStats` into
  ``/metrics`` as ``handler.prefix_cache``.

Correctness bar (carried over from the continuous engine): with the
float KV cache a routed request's tokens are BITWISE the unrouted ones —
the continuation attends the same masked KV the wide prefill would have
produced — asserted for greedy and seeded-sampled decode in
tests/test_prefixstore.py. Under ``kv_quant`` the cached prefix reads
back quantized (tolerance-level parity), so the handler keeps automatic
reuse opt-in there.

Every failure path FAILS OPEN: a store error logs and the request serves
unrouted — the cache is an optimization, never an availability risk.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any

from lambdipy_tpu.runtime.metrics import PrefixCacheStats
from lambdipy_tpu.utils.logs import get_logger

log = get_logger("lambdipy.prefixstore")


class _Node:
    """One block of a cached prefix: ``kv`` is the per-layer store-layout
    slice list for this block's absolute positions."""

    __slots__ = ("parent", "token_key", "children", "kv", "nbytes",
                 "last_used")

    def __init__(self, parent, token_key, kv=None, nbytes=0):
        self.parent = parent
        self.token_key = token_key  # tuple of this block's tokens
        self.children: dict[tuple, "_Node"] = {}
        self.kv = kv
        self.nbytes = nbytes
        self.last_used = 0


def _slices_bytes(slices) -> int:
    """Exact stored bytes of one block's per-layer slice list."""
    return sum(int(v.size) * v.dtype.itemsize
               for entry in slices for v in entry.values())


class PrefixStore:
    """Radix-tree prefix KV store over a ``LlamaServer``."""

    def __init__(self, server: Any, *, block: int = 32,
                 budget_mb: float = 512.0):
        from lambdipy_tpu.models.llama import _next_bucket

        self.server = server
        cfg = server.model.cfg
        # pow-2 block that divides the context window: every block write
        # lands at a multiple-of-block offset and must never cross
        # max_len (dynamic_update_slice would clamp it onto real KV) —
        # the same constraint chunked prefill enforces for prefill_chunk
        b = _next_bucket(max(1, int(block)), 1)
        while b > 1 and cfg.max_len % b:
            b //= 2
        self.block = min(b, cfg.max_len)
        # cold-miss walks dispatch in WIDER chunks than the tree's block
        # (block slices are cut from the final cache either way): a
        # unique long prompt should not pay one device dispatch per 32
        # tokens. Prefer the server's existing prefill_chunk program
        # family (zero new compiles) when it block-aligns, else a
        # 256-token family; block-width remains the tail/fallback.
        ck = getattr(server, "prefill_chunk", None)
        if ck and ck % self.block == 0:
            wide = ck
        else:
            wide = max(self.block, min(256, cfg.max_len))
        while wide > self.block and cfg.max_len % wide:
            wide //= 2
        self.walk_chunk = wide
        self.budget_bytes = max(0, int(float(budget_mb) * 2**20))
        self.stats_counters = PrefixCacheStats()
        self._root = _Node(None, None)
        self._lock = threading.Lock()
        self._clock = itertools.count(1)
        # target-path key -> Event: concurrent cold requests for the same
        # prefix wait for one device walk instead of duplicating it
        self._inflight: dict[str, threading.Event] = {}

    # -- host-side matching --------------------------------------------------

    def _target_len(self, n_tokens: int) -> int:
        """Largest cacheable block-aligned prefix of an n-token prompt:
        at least one token must remain as suffix (the continuation
        program selects the first output token from it)."""
        return ((n_tokens - 1) // self.block) * self.block

    def match_len(self, tokens) -> int:
        """Host-only longest-prefix match in whole blocks — no device
        work, no mutation beyond LRU bookkeeping. This is also the
        scheduler's cost probe: admission prices the SUFFIX a cache-hit
        request will actually prefill (runtime/server.py)."""
        try:
            row = [int(t) for t in tokens]
        except (TypeError, ValueError):
            return 0
        with self._lock:
            return self._match_locked(row)[0]

    def _match_locked(self, row: list) -> tuple[int, list]:
        """(matched token count, path nodes) under the store lock."""
        cap = self._target_len(len(row))
        m, node, path = 0, self._root, []
        while m < cap:
            child = node.children.get(tuple(row[m:m + self.block]))
            if child is None:
                break
            child.last_used = next(self._clock)
            path.append(child)
            node = child
            m += self.block
        return m, path

    # -- the routing entry point ---------------------------------------------

    def route(self, row) -> int:
        """Match + extend + register for one single-row prompt. Returns
        the block-aligned prefix length the request should dispatch with
        (``prefix=row[:m]``, prompt = the suffix), or 0 when the prompt
        is too short to cache or the store failed (serve unrouted).

        A cold prompt is NOT a fast no-op: the unmatched whole blocks
        prefill here (that work replaces the prefill the request would
        have paid anyway) and insert into the tree, so the first request
        for a prefix pays ~one prefill and every later request rides it.
        """
        row = [int(t) for t in row]
        cfg = self.server.model.cfg
        if len(row) > cfg.max_len:
            # the request itself is doomed (server._validate rejects it):
            # a walk here would burn up to a full window of device
            # prefill and evict hot LRU entries for nothing
            return 0
        # the clamp also keeps every block write inside the window —
        # an unclamped target would let the ext loop's writes reach
        # max_len, where dynamic_update_slice CLAMPS them back onto
        # real tail KV (the documented chunked-prefill trap)
        target = min(self._target_len(len(row)),
                     cfg.max_len - self.block)
        if target <= 0:
            return 0  # sub-block prompt: can never hit, don't count it
        with self._lock:
            matched, path = self._match_locked(row)
        self.stats_counters.record_request(matched)
        try:
            if matched >= target:
                self._ensure_assembled(row, path[:target // self.block])
            else:
                self._extend(row, target)
            return target
        except Exception as e:  # noqa: BLE001 — fail open, serve unrouted
            log.error("prefix store routing failed (serving without "
                      "reuse): %s", e)
            return 0

    # -- assembly / extension ------------------------------------------------

    def _ensure_assembled(self, row: list, path: list) -> None:
        """Make sure the server's prefix LRU holds the full-window cache
        for ``row[:len(path)*block]``, assembling it from the tree's
        block slices when it was evicted."""
        from lambdipy_tpu.models.llama import concat_cache_blocks

        m = len(path) * self.block
        key = self.server._prefix_key(row[:m])
        if self.server.get_prefix(key) is not None:
            return
        cfg = self.server.model.cfg
        with self.server._mesh_ctx():
            cache = concat_cache_blocks(cfg, [n.kv for n in path],
                                        cfg.max_len)
        self.server.register_prefix(key, cache, m)

    def _extend(self, row: list, target: int) -> None:
        """Prefill ``row`` up to ``target`` tokens through the server's
        block-width chunk programs, inserting each new block into the
        tree and registering the final cache as the target's prefix
        entry. Re-matches after any inflight wait — the owner usually
        inserted the very blocks this thread wanted."""
        key = self.server._prefix_key(row[:target])
        while True:
            owner, waiter = False, None
            with self._lock:
                matched, path = self._match_locked(row)
                if matched < target:
                    waiter = self._inflight.get(key)
                    if waiter is None:
                        self._inflight[key] = threading.Event()
                        owner = True
            if matched >= target:
                self._ensure_assembled(row, path[:target // self.block])
                return
            if owner:
                try:
                    self._walk(row, matched, target, path)
                finally:
                    with self._lock:
                        event = self._inflight.pop(key, None)
                    if event is not None:
                        event.set()
                return
            if not waiter.wait(timeout=300.0):
                raise RuntimeError(
                    f"prefix walk for key {key[:8]}... owned by another "
                    "thread did not complete within 300s")

    def _walk(self, row: list, matched: int, target: int,
              path: list) -> None:
        import jax.numpy as jnp

        from lambdipy_tpu.models.llama import (
            concat_cache_blocks,
            copy_cache,
            slice_cache_blocks,
        )

        server = self.server
        cfg = server.model.cfg
        bk = self.block
        with server._mesh_ctx():
            if matched == 0:
                # first chunk rides the wide family too when it fits
                fw = self.walk_chunk if target >= self.walk_chunk else bk
                pf = server._prefix_first_fn(fw, cfg.max_len)
                prompt_op, _ = server._pad_rows([row[:fw]], [fw], 1, fw)
                cache = pf(server.params, prompt_op, jnp.int32(fw))
                pos = fw
            else:
                key_m = server._prefix_key(row[:matched])
                entry = server.get_prefix(key_m)
                if entry is not None:
                    # the ext loop DONATES its cache argument; the LRU's
                    # copy must stay live for concurrent readers
                    cache = copy_cache(entry[0])
                else:
                    cache = concat_cache_blocks(
                        cfg, [n.kv for n in path], cfg.max_len)
                pos = matched
            # full-width wide chunks where they fit, block-width tail.
            # A wide write must stay inside max_len: the ext program
            # writes its whole padded window at the cache index, and
            # dynamic_update_slice would CLAMP a crossing window back
            # onto real prefix KV (the documented chunked-prefill trap)
            wk = self.walk_chunk
            ext = server._prefix_ext_fn(bk)
            ext_wide = server._prefix_ext_fn(wk) if wk > bk else None
            while pos < target:
                if (ext_wide is not None and target - pos >= wk
                        and pos + wk <= cfg.max_len):
                    chunk_op, _ = server._pad_rows(
                        [row[pos:pos + wk]], [wk], 1, wk)
                    cache = ext_wide(server.params, cache, chunk_op,
                                     jnp.int32(wk))
                    pos += wk
                else:
                    chunk_op, _ = server._pad_rows(
                        [row[pos:pos + bk]], [bk], 1, bk)
                    cache = ext(server.params, cache, chunk_op,
                                jnp.int32(bk))
                    pos += bk
            new_blocks = [slice_cache_blocks(cache, p, bk)
                          for p in range(matched, target, bk)]
        server.register_prefix(server._prefix_key(row[:target]), cache,
                               target)
        self._insert(row, matched, new_blocks)

    def _insert(self, row: list, start: int, new_blocks: list) -> None:
        """Attach the freshly computed block slices under the matched
        path (idempotent against racers), then sweep the budget."""
        with self._lock:
            # re-walk from the root: a racer may have restructured the
            # path (or inserted some of these very blocks) meanwhile
            node, m = self._root, 0
            while m < start + len(new_blocks) * self.block:
                tok_key = tuple(row[m:m + self.block])
                child = node.children.get(tok_key)
                if child is None:
                    idx = (m - start) // self.block
                    if m < start or idx >= len(new_blocks):
                        # a racer evicted part of our base path: give up
                        # the insert — the KV is already serving
                        break
                    kv = new_blocks[idx]
                    child = _Node(node, tok_key, kv, _slices_bytes(kv))
                    node.children[tok_key] = child
                    self.stats_counters.record_insert(1, child.nbytes)
                child.last_used = next(self._clock)
                node = child
                m += self.block
            self._evict_locked()

    def _evict_locked(self) -> None:
        """LRU leaf eviction until the budget holds (leaves only: an
        interior node's KV is position-prefixed by its parents, so
        dropping it would orphan every descendant block)."""
        while self.stats_counters.report()["bytes"] > self.budget_bytes:
            leaves = [n for n in self._iter_nodes()
                      if not n.children and n.kv is not None]
            if not leaves:
                return
            victim = min(leaves, key=lambda n: n.last_used)
            victim.parent.children.pop(victim.token_key, None)
            self.stats_counters.record_evict(1, victim.nbytes)
            victim.kv = None

    def _iter_nodes(self):
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        out = self.stats_counters.report()
        out["block"] = self.block
        out["budget_bytes"] = self.budget_bytes
        # the assembled full-window caches live in the SERVER's
        # count-bounded prefix LRU (prefix_cache_max), OUTSIDE this
        # budget — surface their real footprint so an operator sizing
        # HBM sees both consumers, not just the tree
        try:
            with self.server._prefix_lock:
                entries = list(self.server._prefixes.values())
            out["assembled_entries"] = len(entries)
            out["assembled_bytes"] = sum(
                int(v.size) * v.dtype.itemsize
                for cache, _len in entries for entry in cache
                for v in entry.values() if hasattr(v, "dtype"))
        except Exception:  # noqa: BLE001 — stats must never break /metrics
            pass
        return out
