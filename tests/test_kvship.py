"""KV-block shipping for disaggregated prefill/decode serving: wire
framing (runtime/kvwire.py), the prefix store's export/import surface,
and the replica HTTP endpoints.

The acceptance bar mirrors every serve-path PR: KV that crosses the
wire must read back BITWISE — export→import round trips across
dense/paged stores and float/int8-with-scales layouts produce outputs
identical to the unshipped path, garbage frames are rejected before
they touch the radix tree, and a full page arena surfaces as priced
backpressure instead of silent cache loss."""

import numpy as np
import pytest

from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
from lambdipy_tpu.runtime.kvwire import decode_frame, encode_frame
from lambdipy_tpu.runtime.pagepool import (
    PagePool,
    PagesExhausted,
    page_width,
)
from lambdipy_tpu.runtime.prefixstore import PrefixStore

BLOCK = 16


@pytest.fixture(scope="module")
def tiny_server():
    from lambdipy_tpu.models import registry

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    return adapter.make_server(params)


@pytest.fixture(scope="module")
def int8_server():
    from lambdipy_tpu.models import registry

    adapter = registry.get("llama-tiny").build(
        extra={"kv_quant": "int8"})
    params = adapter.init_params(seed=0)
    return adapter.make_server(params)


def mk_pool(server, *, n_windows=4, extra_pages=0, block=BLOCK):
    cfg = server.model.cfg
    page = page_width(cfg.max_len, block)
    n_pages = n_windows * (cfg.max_len // page) + 1 + extra_pages
    return PagePool(n_pages=n_pages, page=page,
                    page_bytes=page_kv_bytes(cfg, page),
                    make_arena=lambda: init_page_arena(cfg, n_pages,
                                                       page))


def clear_prefix_lru(server):
    """Stores in these tests share one server: drop the server-level
    assembled-prefix LRU so the importing store must serve from its OWN
    tree, not from the exporter's registered entry."""
    with server._prefix_lock:
        server._prefixes.clear()


# -- wire format --------------------------------------------------------------


def _fake_blocks(n_blocks, layers=2, dtype=np.float32, int8=False):
    rng = np.random.default_rng(0)
    out = []
    for b in range(n_blocks):
        blk = []
        for layer in range(layers):
            if int8:
                blk.append({
                    "k_int8": rng.integers(-127, 127, (1, BLOCK, 2, 4),
                                           dtype=np.int8),
                    "k_scale": rng.random((1, BLOCK, 2, 1),
                                          dtype=np.float32),
                    "v_int8": rng.integers(-127, 127, (1, BLOCK, 2, 4),
                                           dtype=np.int8),
                    "v_scale": rng.random((1, BLOCK, 2, 1),
                                          dtype=np.float32),
                })
            else:
                blk.append({
                    "k": rng.random((1, BLOCK, 2, 4)).astype(dtype),
                    "v": rng.random((1, BLOCK, 2, 4)).astype(dtype),
                })
        out.append(blk)
    return out


@pytest.mark.parametrize("int8", [False, True])
def test_wire_roundtrip_bitwise(int8):
    blocks = _fake_blocks(3, int8=int8)
    tokens = list(range(3 * BLOCK))
    frame = encode_frame(tokens, BLOCK, blocks)
    t2, bk2, out = decode_frame(frame)
    assert t2 == tokens and bk2 == BLOCK and len(out) == 3
    for b1, b2 in zip(blocks, out):
        for e1, e2 in zip(b1, b2):
            assert set(e1) == set(e2)
            for name in e1:
                assert e1[name].dtype == e2[name].dtype
                np.testing.assert_array_equal(e1[name], e2[name])


def test_wire_roundtrip_bfloat16():
    """bf16 bundles ship their KV bitwise through the ml_dtypes name
    resolution, not a float32 detour."""
    import ml_dtypes

    blocks = _fake_blocks(1, dtype=ml_dtypes.bfloat16)
    frame = encode_frame(list(range(BLOCK)), BLOCK, blocks)
    _, _, out = decode_frame(frame)
    assert out[0][0]["k"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        out[0][0]["k"].view(np.uint16),
        np.asarray(blocks[0][0]["k"]).view(np.uint16))


def test_wire_rejects_garbage():
    blocks = _fake_blocks(2)
    frame = encode_frame(list(range(2 * BLOCK)), BLOCK, blocks)
    with pytest.raises(ValueError, match="magic"):
        decode_frame(b"NOPE" + frame[4:])
    with pytest.raises(ValueError, match="truncated|body"):
        decode_frame(frame[:-10])
    with pytest.raises(ValueError, match="body"):
        decode_frame(frame + b"\x00" * 8)
    with pytest.raises(ValueError):
        decode_frame(b"")
    with pytest.raises(ValueError, match="header length"):
        decode_frame(b"LKV1" + b"\xff\xff\xff\xff" + b"x" * 32)
    # a header that lies about its leaves must not survive validation
    import json as _json
    import struct as _struct
    hlen = _struct.unpack_from("<I", frame, 4)[0]
    header = _json.loads(frame[8:8 + hlen])
    header["leaves"][0][0] = "not_a_leaf"
    hb = _json.dumps(header).encode()
    with pytest.raises(ValueError, match="leaf names"):
        decode_frame(b"LKV1" + _struct.pack("<I", len(hb)) + hb
                     + frame[8 + hlen:])
    # tokens not covering the blocks
    header = _json.loads(frame[8:8 + hlen])
    header["tokens"] = header["tokens"][:-1]
    hb = _json.dumps(header).encode()
    with pytest.raises(ValueError, match="tokens"):
        decode_frame(b"LKV1" + _struct.pack("<I", len(hb)) + hb
                     + frame[8 + hlen:])


def test_encode_validates_coverage():
    with pytest.raises(ValueError, match="cover"):
        encode_frame(list(range(BLOCK + 1)), BLOCK, _fake_blocks(1))
    with pytest.raises(ValueError, match="nothing"):
        encode_frame([], BLOCK, [])


# -- store-level export / import ---------------------------------------------


def test_dense_ship_parity_greedy_and_sampled(tiny_server):
    """export→wire→import between two dense stores: the importing
    replica's routed output is BITWISE the unrouted output, greedy and
    seeded-sampled."""
    exp = PrefixStore(tiny_server, block=BLOCK, budget_mb=8)
    imp = PrefixStore(tiny_server, block=BLOCK, budget_mb=8)
    row = list(range(3, 45))  # 42 tokens -> 32-token head
    for kw in ({}, dict(temperature=0.9, seed=11, top_k=5, top_p=0.9)):
        off = tiny_server.generate(row, max_new_tokens=8, **kw)
        head, blocks = exp.export_blocks(row)
        assert len(head) == 32
        tokens, bk, wire = decode_frame(
            encode_frame(head, exp.block, blocks))
        clear_prefix_lru(tiny_server)
        res = imp.import_blocks(tokens, wire)
        assert res["mode"] == "dense"
        m = imp.route(row)
        assert m == 32
        on = tiny_server.generate(row[m:], prefix=row[:m],
                                  max_new_tokens=8, **kw)
        np.testing.assert_array_equal(on, off, err_msg=str(kw))
    # second import of the same frame is an idempotent no-op
    head, blocks = exp.export_blocks(row)
    res = imp.import_blocks(*decode_frame(
        encode_frame(head, exp.block, blocks))[0::2])
    assert res == {"present": 2, "inserted": 0, "mode": "dense"}


def test_paged_import_is_zero_copy(tiny_server):
    """A ship arrival on a paged decode replica lands in arena pages:
    the hit is an acquire_pages refcount bump — engine output bitwise,
    zero assembly bytes."""
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    exp = PrefixStore(tiny_server, block=BLOCK, budget_mb=8)
    pool = mk_pool(tiny_server)
    imp = PrefixStore(tiny_server, block=BLOCK, budget_mb=64, pool=pool)
    row = list(range(5, 47))
    off = tiny_server.generate(row, max_new_tokens=8)
    head, blocks = exp.export_blocks(row)
    clear_prefix_lru(tiny_server)
    res = imp.import_blocks(*decode_frame(
        encode_frame(head, exp.block, blocks))[0::2])
    assert res["mode"] == "paged" and res["inserted"] == 2
    got = imp.acquire_pages(head)
    assert got is not None and got[1] == 32
    pool.release(got[0])
    eng = ContinuousBatcher(tiny_server, slots=4, segment=8,
                            page_pool=pool)
    eng.prefix_pages_fn = imp.acquire_pages
    m = imp.route(row)
    assert m == 32
    on = eng.generate(row[m:], max_new_tokens=8, prefix=row[:m])
    np.testing.assert_array_equal(np.asarray(on), np.asarray(off))
    # the paged consume path never assembled a full-window cache
    assert imp.stats()["assembly_bytes_peak"] == 0
    pool.check_invariants()


def test_paged_export_to_dense_import(tiny_server):
    """The wire is mode-agnostic: pages exported from a paged store
    import into a dense store bitwise."""
    pool = mk_pool(tiny_server)
    exp = PrefixStore(tiny_server, block=BLOCK, budget_mb=64, pool=pool)
    imp = PrefixStore(tiny_server, block=BLOCK, budget_mb=8)
    row = list(range(9, 51))
    off = tiny_server.generate(row, max_new_tokens=8)
    head, blocks = exp.export_blocks(row)
    assert len(head) == 32 and len(blocks) == 2
    clear_prefix_lru(tiny_server)
    imp.import_blocks(*decode_frame(
        encode_frame(head, exp.block, blocks))[0::2])
    m = imp.route(row)
    on = tiny_server.generate(row[m:], prefix=row[:m], max_new_tokens=8)
    np.testing.assert_array_equal(on, off)
    pool.check_invariants()


def test_int8_ship_roundtrip(int8_server):
    """int8 KV ships as int8 + f32 scales (first-class wire leaves) and
    the imported replica's routed output is bitwise the exporter's
    routed output — the stored bytes crossed unchanged."""
    exp = PrefixStore(int8_server, block=BLOCK, budget_mb=8)
    imp = PrefixStore(int8_server, block=BLOCK, budget_mb=8)
    row = list(range(4, 46))
    head, blocks = exp.export_blocks(row)
    assert {"k_int8", "k_scale", "v_int8", "v_scale"} == set(blocks[0][0])
    m = exp.route(row)
    routed_a = int8_server.generate(row[m:], prefix=row[:m],
                                    max_new_tokens=8)
    clear_prefix_lru(int8_server)
    imp.import_blocks(*decode_frame(
        encode_frame(head, exp.block, blocks))[0::2])
    m2 = imp.route(row)
    assert m2 == m
    routed_b = int8_server.generate(row[m2:], prefix=row[:m2],
                                    max_new_tokens=8)
    np.testing.assert_array_equal(routed_b, routed_a)


def test_partial_block_tail_prefills_locally(tiny_server):
    """A prompt with a sub-block tail ships only its whole blocks; the
    decode side prefills the tail itself — outputs still bitwise."""
    exp = PrefixStore(tiny_server, block=BLOCK, budget_mb=8)
    imp = PrefixStore(tiny_server, block=BLOCK, budget_mb=8)
    row = list(range(7, 50))  # 43 tokens: head 32, 11-token tail
    off = tiny_server.generate(row, max_new_tokens=8)
    head, blocks = exp.export_blocks(row)
    assert len(head) == 32
    clear_prefix_lru(tiny_server)
    imp.import_blocks(*decode_frame(
        encode_frame(head, exp.block, blocks))[0::2])
    m = imp.route(row)
    assert m == 32  # the tail stays suffix
    on = tiny_server.generate(row[m:], prefix=row[:m], max_new_tokens=8)
    np.testing.assert_array_equal(on, off)


def test_export_sub_block_returns_none(tiny_server):
    store = PrefixStore(tiny_server, block=BLOCK, budget_mb=8)
    assert store.export_blocks(list(range(BLOCK - 1))) is None


def test_prefix_walk_fault_fails_open_bitwise(tiny_server):
    """The prefix_walk chaos site: an injected walk exception must cost
    only the cache (route returns 0, the request serves unrouted and
    bitwise); a delay fires once per chunk dispatch."""
    from lambdipy_tpu.runtime.faults import FaultPlan

    plan = FaultPlan.from_spec("prefix_walk:exception@seg=1,n=1")
    store = PrefixStore(tiny_server, block=BLOCK, budget_mb=8,
                        faults=plan)
    row = list(range(6, 48))
    off = tiny_server.generate(row, max_new_tokens=8)
    assert store.route(row) == 0  # walk failed -> fail open
    on = tiny_server.generate(row, max_new_tokens=8)
    np.testing.assert_array_equal(on, off)
    # the rule is spent: the next route walks and caches normally
    assert store.route(row) == 32
    # delay kind: one firing per chunk dispatch, deterministic count
    plan2 = FaultPlan.from_spec("prefix_walk:delay@ms=1,n=inf")
    store2 = PrefixStore(tiny_server, block=BLOCK, budget_mb=8,
                         faults=plan2)
    row2 = list(range(60, 60 + 33))  # 32-token head, cold
    assert store2.route(row2) == 32
    fired = plan2.counts()["prefix_walk"]
    assert 1 <= fired <= 32 // BLOCK  # one per chunk, chunks >= blocks


def test_import_rejects_layout_mismatch(tiny_server, int8_server):
    """A frame that does not match the importing server's store layout
    (float vs int8, wrong shapes) raises and touches nothing."""
    exp = PrefixStore(tiny_server, block=BLOCK, budget_mb=8)
    row = list(range(2, 40))
    head, blocks = exp.export_blocks(row)
    imp = PrefixStore(int8_server, block=BLOCK, budget_mb=8)
    before = imp.stats()["blocks"]
    with pytest.raises(ValueError, match="store layout"):
        imp.import_blocks(head, blocks)
    assert imp.stats()["blocks"] == before
    # token/blocks mismatch
    imp2 = PrefixStore(tiny_server, block=BLOCK, budget_mb=8)
    with pytest.raises(ValueError, match="cover"):
        imp2.import_blocks(head[:BLOCK], blocks)
    # a shipped prefix that fills the whole window leaves no decode room
    cfg = tiny_server.model.cfg
    full = list(range(cfg.max_len))
    fake = blocks * (cfg.max_len // BLOCK // len(blocks))
    with pytest.raises(ValueError, match="no room"):
        imp2.import_blocks(full, fake)


def test_import_backpressure_propagates(tiny_server):
    """A paged import the arena cannot hold raises PagesExhausted (the
    priced-shed path) instead of silently caching nothing — and leaks
    no pages."""
    exp = PrefixStore(tiny_server, block=BLOCK, budget_mb=8)
    pool = mk_pool(tiny_server, n_windows=0, extra_pages=2)  # 2 usable
    imp = PrefixStore(tiny_server, block=BLOCK, budget_mb=64, pool=pool)
    row = list(range(11, 11 + 48 + 5))  # 48-token head = 3 blocks
    head, blocks = exp.export_blocks(row)
    assert len(blocks) == 3
    free_before = pool.free_count()
    with pytest.raises(PagesExhausted):
        imp.import_blocks(*decode_frame(
            encode_frame(head, exp.block, blocks))[0::2])
    assert pool.free_count() == free_before
    pool.check_invariants()


def test_import_lands_despite_garbage_distractor_pages(tiny_server):
    """Junk pages already in the arena (stale content from other rows)
    must not bleed into an imported prefix's pages — the block-table
    indirection isolates them."""
    import jax.numpy as jnp

    exp = PrefixStore(tiny_server, block=BLOCK, budget_mb=8)
    pool = mk_pool(tiny_server)
    imp = PrefixStore(tiny_server, block=BLOCK, budget_mb=64, pool=pool)
    # scribble junk into a few pages the import must route around
    junk_pids = pool.alloc(3, tokens=3 * BLOCK)
    write = tiny_server._page_write_fn(pool.n_pages, pool.page)
    cfg = tiny_server.model.cfg
    rng = np.random.default_rng(7)
    junk_block = [
        {name: jnp.asarray(rng.normal(
            size=(1, pool.page) + tuple(v.shape[2:])).astype(v.dtype))
         for name, v in entry.items()}
        for entry in init_page_arena(cfg, 2, pool.page)]
    with pool.arena_lock:
        arena = pool.ensure_arena()
        for pid in junk_pids:
            arena = write(arena, jnp.int32(pid), junk_block)
        pool.arena = arena
    row = list(range(21, 63))
    off = tiny_server.generate(row, max_new_tokens=8)
    head, blocks = exp.export_blocks(row)
    clear_prefix_lru(tiny_server)
    imp.import_blocks(*decode_frame(
        encode_frame(head, exp.block, blocks))[0::2])
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    eng = ContinuousBatcher(tiny_server, slots=4, segment=8,
                            page_pool=pool)
    eng.prefix_pages_fn = imp.acquire_pages
    m = imp.route(row)
    on = eng.generate(row[m:], max_new_tokens=8, prefix=row[:m])
    np.testing.assert_array_equal(np.asarray(on), np.asarray(off))
    pool.release(junk_pids)
    pool.check_invariants()


def test_bf16_ship_roundtrip():
    """A bfloat16 bundle's KV ships bitwise: the wire dtype names and
    the import-side leaf template both resolve bf16 through ml_dtypes
    (no float32 detour, no template crash)."""
    from lambdipy_tpu.models import registry

    adapter = registry.get("llama-tiny").build(dtype="bfloat16")
    params = adapter.init_params(seed=0)
    server = adapter.make_server(params)
    exp = PrefixStore(server, block=BLOCK, budget_mb=8)
    imp = PrefixStore(server, block=BLOCK, budget_mb=8)
    row = list(range(3, 45))
    off = np.asarray(server.generate(row, max_new_tokens=8))
    head, blocks = exp.export_blocks(row)
    clear_prefix_lru(server)
    res = imp.import_blocks(*decode_frame(
        encode_frame(head, BLOCK, blocks))[0::2])
    assert res["inserted"] == 2
    m = imp.route(row)
    on = np.asarray(server.generate(row[m:], prefix=row[:m],
                                    max_new_tokens=8))
    np.testing.assert_array_equal(on, off)


# -- replica HTTP endpoints ---------------------------------------------------


def test_http_kv_ship_e2e(tmp_path):
    """Two live bundle servers: export a prompt head from A over HTTP,
    import the frame into B, then B's completion for the full prompt is
    bitwise A's — and both replicas publish batching.disagg counters."""
    import json
    import urllib.request

    from lambdipy_tpu.buildengine import build_recipe
    from lambdipy_tpu.bundle import assemble_bundle
    from lambdipy_tpu.recipes.schema import load_recipe_dict
    from lambdipy_tpu.runtime.server import BundleServer

    doc = {
        "schema": 1, "name": "kvship-e2e", "version": "0.1",
        "device": "any", "base_layer": "jax-tpu", "requires": [],
        "payload": {
            "model": "llama-tiny",
            "handler": "lambdipy_tpu.runtime.handlers:generate_handler",
            "params": "init", "dtype": "float32",
            "extra": {"max_new_tokens": "8", "serve_aot": "0",
                      "warm_group_prefill": "0",
                      "prefix_cache_mb": "32", "prefix_block": "16"},
        },
    }
    result = build_recipe(load_recipe_dict(doc), tmp_path / "work",
                          run_smoke=False)
    bundle = tmp_path / "bundle"
    assemble_bundle(result, bundle, with_payload=True)
    a = BundleServer(bundle, warmup=False).start_background()
    b = BundleServer(bundle, warmup=False).start_background()
    try:
        def post(port, path, data, ctype="application/json"):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=data,
                headers={"Content-Type": ctype}, method="POST")
            with urllib.request.urlopen(req, timeout=300) as resp:
                return resp.status, resp.read()

        row = list(range(3, 45))
        _, ref = post(a.port, "/v1/completions", json.dumps(
            {"prompt": row, "max_tokens": 8, "temperature": 0}).encode())
        ref_tokens = json.loads(ref)["choices"][0]["tokens"]
        status, frame = post(a.port, "/v1/kv/export",
                             json.dumps({"tokens": row}).encode())
        assert status == 200 and frame[:4] == b"LKV1"
        status, out = post(b.port, "/v1/kv/import", frame,
                           "application/octet-stream")
        assert status == 200
        imported = json.loads(out)
        assert imported["ok"] and imported["inserted"] == 2
        _, got = post(b.port, "/v1/completions", json.dumps(
            {"prompt": row, "max_tokens": 8, "temperature": 0}).encode())
        assert json.loads(got)["choices"][0]["tokens"] == ref_tokens
        # B served the head from shipped KV: its store shows a hit
        with urllib.request.urlopen(
                f"http://127.0.0.1:{b.port}/metrics", timeout=30) as resp:
            m = json.loads(resp.read())
        dg = m["handler"]["batching"]["disagg"]
        assert dg["imports"] == 1 and dg["import_blocks"]["inserted"] == 2
        assert m["handler"]["prefix_cache"]["hits"] >= 1
        # a garbage frame answers 400 and inserts nothing
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(b.port, "/v1/kv/import", b"LKV1garbage",
                 "application/octet-stream")
        assert ei.value.code == 400
    finally:
        a.stop()
        b.stop()
