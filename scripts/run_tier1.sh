#!/usr/bin/env bash
# Tier-1 gate, runnable locally and in CI.
#
# Phase 1 fails FAST on collection errors: a module-level import break
# (like the tomllib one that silently knocked out 7 test files on
# Python 3.10) must turn the build red by itself, not hide behind
# --continue-on-collection-errors in the main run.
#
# Phase 2 is the EXACT tier-1 command from ROADMAP.md (its exit code
# still gates; the only change is that success falls through to the
# later phases instead of exiting inline).
#
# Phase 3 is a quick forced-CPU bench.py smoke (tiny model) so a bench
# orchestration regression turns tier-1 red, not measurement day.
#
# Phase 4 smokes the decode-window sweep; phase 5 the pipelined-engine
# sweep (bitwise parity across pipeline depths + depth-2 tok/s beating
# depth-1 under a synthetic fetch RTT — bench.py --pipeline exits
# nonzero on either regression); phase 6 the FLEET (2 CPU replicas
# behind the affinity router, one SIGKILLed mid-traffic — zero lost
# requests, ejection, supervisor respawn, re-admission, rolling
# restart — the slow tests in tests/test_fleet.py).
#
# Every phase prints its wall-clock so the budget breakdown is visible
# in the log (ROADMAP open item: phase 2 runs close to its 870 s cap).

set -u
cd "$(dirname "$0")/.."

phase_t0=0
phase_begin() { phase_t0=$(date +%s); echo "== $1 =="; }
phase_end() { echo "== $1 wall: $(( $(date +%s) - phase_t0 ))s =="; }

phase_begin "phase 1: collection must be clean"
rm -f /tmp/_t1_collect.log
env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --collect-only --continue-on-collection-errors \
    -p no:cacheprovider 2>&1 | tee /tmp/_t1_collect.log
if grep -qE '^ERROR |[0-9]+ errors? in ' /tmp/_t1_collect.log; then
    echo "FATAL: test collection errors (see above)" >&2
    exit 1
fi
phase_end "phase 1"

phase_begin "phase 2: tier-1 suite (ROADMAP.md verbatim)"
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
phase_end "phase 2"
if [ "$rc" -ne 0 ]; then exit "$rc"; fi

phase_begin "phase 3: bench.py CPU smoke"
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
    LAMBDIPY_BENCH_FORCE_PLATFORM=cpu LAMBDIPY_BENCH_MODEL=resnet50-tiny \
    python bench.py; then
    echo "FATAL: bench.py CPU smoke failed" >&2
    exit 1
fi
phase_end "phase 3"

phase_begin "phase 4: decode-window bench smoke"
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --decode-window; then
    echo "FATAL: bench.py --decode-window smoke failed" >&2
    exit 1
fi
phase_end "phase 4"

# Phase 5: pipelined-engine smoke — the sweep itself asserts bitwise
# parity between pipeline depths and that depth-2 throughput stays
# above depth-1 at the synthetic-RTT points (20/66 ms), so either
# regression turns tier-1 red here.
phase_begin "phase 5: pipeline bench smoke"
if ! timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --pipeline; then
    echo "FATAL: bench.py --pipeline smoke failed" >&2
    exit 1
fi
phase_end "phase 5"

# Phase 6: fleet smoke (~3-4 min CPU) — boots 2 supervised CPU replicas
# behind the affinity router, SIGKILLs one worker mid-traffic and
# asserts zero failed requests, ejection within a probe interval,
# re-admission after the supervisor respawn (same URL), then a rolling
# restart over the live floor; plus router-vs-direct bitwise parity,
# the live-server readiness split, and the shared-prefix
# affinity-concentration check (all the `slow` tests in test_fleet.py).
phase_begin "phase 6: fleet smoke (tests/test_fleet.py -m slow)"
if ! timeout -k 10 900 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_fleet.py -q -m slow \
    -p no:cacheprovider -p no:xdist -p no:randomly; then
    echo "FATAL: fleet smoke failed" >&2
    exit 1
fi
phase_end "phase 6"
exit 0
