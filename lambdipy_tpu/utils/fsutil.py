"""Filesystem helpers used by the build engine, prune pass and bundle store.

Hashing prefers the native C extension (:mod:`lambdipy_tpu._native`) when it
has been built (``python setup_native.py build_ext --inplace``); otherwise it
falls back to :mod:`hashlib`. Bundle manifests record a content hash per file
(the provenance pattern of the TPU base-image exemplar's post-build manifest,
SURVEY.md §3.4 ``jss:generate_manifest.sh``).
"""

from __future__ import annotations

import hashlib
import os
import shutil
from collections.abc import Iterator
from pathlib import Path

_CHUNK = 1 << 20


def walk_files(root: Path) -> Iterator[Path]:
    """Yield all regular files under root (sorted, deterministic)."""
    root = Path(root)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            p = Path(dirpath) / name
            if p.is_file() or p.is_symlink():
                yield p


def sha256_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while chunk := f.read(_CHUNK):
            h.update(chunk)
    return h.hexdigest()


def _native_hasher():
    try:
        from lambdipy_tpu import _native  # C extension, optional

        return _native.xxh64_file
    except Exception:
        return None


def hash_file(path: Path, algo: str | None = None) -> str:
    """Fast content hash for manifests: native xxh64 when built, sha256
    otherwise. ``algo`` pins the algorithm (used when re-verifying a
    manifest whose hashes were produced elsewhere)."""
    if algo == "sha256":
        return f"sha256:{sha256_file(path)}"
    native = _native_hasher()
    if algo == "xxh64":
        if native is None:
            raise RuntimeError("manifest uses xxh64 but the native extension is not built; "
                               "run: python setup_native.py build_ext --inplace")
        return f"xxh64:{native(str(path)):016x}"
    if native is not None:
        return f"xxh64:{native(str(path)):016x}"
    return f"sha256:{sha256_file(path)}"


def dir_size(root: Path) -> int:
    return sum(p.stat().st_size for p in walk_files(root) if p.is_file())


def copy_tree(src: Path, dst: Path, *, symlinks: bool = True) -> None:
    shutil.copytree(src, dst, symlinks=symlinks, dirs_exist_ok=True)


def atomic_write_text(path: Path, text: str) -> None:
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(text)
    os.replace(tmp, path)


def atomic_write_bytes(path: Path, data: bytes) -> None:
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_bytes(data)
    os.replace(tmp, path)
