"""AOT executable store: ship compiled programs inside the bundle.

Cold start on TPU is interpreter + PJRT init + trace/lower/compile
(BASELINE.md: ~10 s floor; SURVEY.md §9.6 names AOT as the make-or-break
weapon). The persistent compile cache (loader.attach_compile_cache) already
turns XLA *compilation* into a disk hit, but tracing + lowering a real
model is still seconds of Python. This module removes that too, with two
tiers stored under ``<bundle>/aot/``:

- **tier 2 — serialized executable** (``*.exec``): the PJRT-compiled
  program via ``jax.experimental.serialize_executable``. Zero trace, zero
  lower, zero compile at boot. Only valid for the exact (platform, jax,
  jaxlib) that produced it — the key encodes all three, and loading is
  best-effort (some PJRT plugins don't support executable serialization).
- **tier 1 — jax.export StableHLO** (``*.hlo``): portable serialized
  module. Boot skips tracing/lowering; the compile that remains is a
  persistent-cache hit because the builder warmed it.

Misses fall through to plain ``jax.jit`` and (best-effort) write both
artifacts so the *next* boot — or the built bundle, when the builder's
warm subprocess does this — is fast. The reference has no analog: its
"AOT" is shipping pre-built wheels (SURVEY.md §1); this is the same idea
one level down, at the XLA-program level.
"""

from __future__ import annotations

import json
import pickle
from pathlib import Path
from typing import Any, Callable, Sequence

from lambdipy_tpu.utils.fsutil import atomic_write_bytes, atomic_write_text
from lambdipy_tpu.utils.logs import get_logger

log = get_logger("lambdipy.aot")

_SCHEMA = 1


def _env_key() -> dict:
    import jax
    import jaxlib

    return {
        "schema": _SCHEMA,
        "platform": jax.default_backend(),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "n_devices": len(jax.devices()),
    }


class AotStore:
    """Directory of AOT artifacts for one bundle, keyed by entry name and
    the producing environment."""

    def __init__(self, bundle_dir: Path):
        self.dir = Path(bundle_dir) / "aot"

    def _paths(self, name: str) -> dict[str, Path]:
        import jax

        stem = f"{name}.{jax.default_backend()}"
        return {
            "meta": self.dir / f"{stem}.json",
            "hlo": self.dir / f"{stem}.hlo",
            "exec": self.dir / f"{stem}.exec",
        }

    # -- save ---------------------------------------------------------------

    def save(self, name: str, fn: Callable,
             example_args: Sequence[Any]) -> tuple[dict, Callable]:
        """Export ``fn`` at ``example_args``'s shapes; write tier 1 always,
        tier 2 when the backend supports executable serialization.

        Returns ``(meta, jitted)`` — the same ``jax.jit`` object the export
        used, so a miss path can serve from it instead of re-tracing.
        Artifact writes are atomic and the meta (which declares the tiers)
        lands last: a crash mid-save leaves no meta, never a meta pointing
        at a torn tier file.
        """
        import jax

        self.dir.mkdir(parents=True, exist_ok=True)
        paths = self._paths(name)
        meta = _env_key()
        meta["tiers"] = []

        jitted = jax.jit(fn)
        try:
            exported = jax.export.export(jitted)(*example_args)
            atomic_write_bytes(paths["hlo"], bytes(exported.serialize()))
            meta["tiers"].append("hlo")
        except Exception as e:
            log.warning("aot %s: jax.export failed: %s", name, e)

        try:
            from jax.experimental import serialize_executable

            compiled = jitted.lower(*example_args).compile()
            payload = serialize_executable.serialize(compiled)
            atomic_write_bytes(paths["exec"], pickle.dumps(payload))
            meta["tiers"].append("exec")
        except Exception as e:
            log.info("aot %s: executable serialization unavailable: %s", name, e)

        if meta["tiers"]:
            atomic_write_text(paths["meta"], json.dumps(meta, indent=1))
        return meta, jitted

    # -- load ---------------------------------------------------------------

    def load(self, name: str,
             example_args: Sequence[Any] | None = None) -> tuple[Callable, str] | None:
        """Return ``(callable, tier)`` for the best available artifact
        matching the current environment, or None.

        When ``example_args`` is given each candidate tier is probe-invoked
        before being returned — an AOT executable can deserialize fine yet
        fail at call time (observed: XLA:CPU AOT rejects a host whose CPU
        features differ from the compile machine). The probe doubles as the
        warmup invoke, so it costs the boot path nothing.
        """
        paths = self._paths(name)
        if not paths["meta"].is_file():
            return None
        try:
            meta = json.loads(paths["meta"].read_text())
        except Exception:
            return None
        env = _env_key()
        if any(meta.get(k) != env[k]
               for k in ("schema", "platform", "jax", "jaxlib", "n_devices")):
            log.info("aot %s: environment mismatch (%s vs %s), ignoring",
                     name, meta, env)
            return None

        def _probe(fn: Callable) -> bool:
            if example_args is None:
                return True
            import jax

            jax.block_until_ready(fn(*example_args))
            return True

        if "exec" in meta.get("tiers", ()) and paths["exec"].is_file():
            try:
                from jax.experimental import serialize_executable

                payload = pickle.loads(paths["exec"].read_bytes())
                compiled = serialize_executable.deserialize_and_load(*payload)
                _probe(compiled)
                return compiled, "exec"
            except Exception as e:
                log.warning("aot %s: exec tier failed to load: %s", name, e)

        if "hlo" in meta.get("tiers", ()) and paths["hlo"].is_file():
            try:
                import jax

                exported = jax.export.deserialize(
                    bytearray(paths["hlo"].read_bytes()))
                fn = jax.jit(exported.call)
                _probe(fn)
                return fn, "hlo"
            except Exception as e:
                log.warning("aot %s: hlo tier failed to load: %s", name, e)
        return None


def cached_jit(ctx, name: str, fn: Callable,
               example_args: Sequence[Any]) -> tuple[Callable, str]:
    """The handler-facing entry: AOT artifact if present, else ``jax.jit``
    plus a best-effort save so the next boot skips trace/lower/compile.

    ``ctx`` is a HandlerContext (anything with ``bundle_dir``). Artifacts
    are keyed by device count (load rejects a topology mismatch); callers
    should only use this on the single-chip path — meshes re-shard at load
    in _maybe_shard. The returned callable is shape-specialized to
    ``example_args`` on a hit; handlers keep a plain-jit fallback for
    other shapes. Returns ``(callable, source)``, source in
    {"exec", "hlo", "jit"}.
    """
    import jax

    store = AotStore(ctx.bundle_dir)
    hit = store.load(name, example_args)
    if hit is not None:
        return hit
    try:
        _, jitted = store.save(name, fn, example_args)
        return jitted, "jit"
    except Exception as e:  # bundle dir read-only, export unsupported, ...
        log.info("aot %s: save skipped: %s", name, e)
    return jax.jit(fn), "jit"
