"""Build-time bundle warming: pre-populate the persistent compile cache.

Cold start is interpreter + PJRT init + first compile (BASELINE.md: ~10 s
floor measured, first jit 0.67 s for a trivial op, tens of seconds for real
models). The builder runs this module as a subprocess against the freshly
assembled bundle (same interpreter/platform as the serve runtime), so the
XLA compilation cache the bundle ships is already hot and the serve boot's
"first" compile is a cache hit — SURVEY.md §9.6: "persistent compilation
cache shipped *inside* the bundle".

Usage: ``python -m lambdipy_tpu.runtime.warm <bundle_dir>``
(honors LAMBDIPY_PLATFORM like the server).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path


def warm_bundle(bundle_dir: Path) -> dict:
    from lambdipy_tpu.runtime.loader import load_bundle

    t0 = time.monotonic()
    report = load_bundle(Path(bundle_dir), warmup=True)
    out = {
        "warmed": True,
        "wall_s": round(time.monotonic() - t0, 2),
        "stages": report.stages,
        "cache_entries": sum(1 for _ in (Path(bundle_dir) / "compile_cache").rglob("*")
                             if _.is_file()) if (Path(bundle_dir) / "compile_cache").is_dir() else 0,
    }
    return out


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("usage: warm <bundle_dir>", file=sys.stderr)
        return 2
    from lambdipy_tpu.utils.platform import apply_platform_override

    apply_platform_override()
    print(json.dumps(warm_bundle(Path(argv[0]))), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
