"""Serve metrics: invoke latency percentiles + cold-start breakdown.

SURVEY.md §6 metrics row: the reference has stdout echo only; the rebuild
keeps p50/p99 and cold-start stage timings as first-class, exported on
``/metrics`` as JSON. :class:`PrefixCacheStats` is the counter block the
automatic prefix KV cache (runtime/prefixstore.py) publishes under
``handler.prefix_cache``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class LatencyStats:
    """Bounded reservoir of recent latencies (ms) with percentile report."""

    capacity: int = 2048
    samples: list[float] = field(default_factory=list)
    count: int = 0
    errors: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record(self, ms: float) -> None:
        with self._lock:
            # ring position is the PRE-increment count: sample N lands at
            # index N % capacity, so the first wraparound overwrite hits
            # slot 0 (incrementing first skewed the ring by one and made
            # slot 0 immortal)
            if len(self.samples) >= self.capacity:
                self.samples[self.count % self.capacity] = ms
            else:
                self.samples.append(ms)
            self.count += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    @staticmethod
    def _percentile(samples: list[float], q: float) -> float | None:
        if not samples:
            return None
        s = sorted(samples)
        idx = min(len(s) - 1, max(0, round(q / 100.0 * (len(s) - 1))))
        return s[idx]

    def percentile(self, q: float) -> float | None:
        with self._lock:
            samples = list(self.samples)
        return self._percentile(samples, q)

    def report(self) -> dict:
        # one consistent snapshot: count/errors/samples move together, so
        # read them all under the lock and compute percentiles outside it
        with self._lock:
            count, errors = self.count, self.errors
            samples = list(self.samples)
        return {
            "count": count,
            "errors": errors,
            "p50_ms": self._percentile(samples, 50),
            "p90_ms": self._percentile(samples, 90),
            "p99_ms": self._percentile(samples, 99),
        }


@dataclass
class DecodeWindowStats:
    """Counters for length-aware decode (the ``decode.window`` block on
    ``/metrics``): how many KV positions each decode step actually
    ATTENDED vs how many the dispatched program READ vs what the full
    static window would have read. ``savings_ratio`` = read / full —
    < 1 means the window bucketing (or the blocked kernel) cut decode
    KV traffic; 1.0 means every step paid the whole allocated window.
    ``buckets`` histograms the pow-2 windows segments dispatched at."""

    attended_tokens: int = 0   # sum over rows x steps of positions attended
    window_tokens: int = 0     # sum of positions the program actually read
    full_tokens: int = 0       # what the full static window would have read
    segments: int = 0
    buckets: dict = field(default_factory=dict)  # window -> segment count
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record_segment(self, *, attended: int, window_read: int,
                       full_window: int, window: int) -> None:
        with self._lock:
            self.attended_tokens += int(attended)
            self.window_tokens += int(window_read)
            self.full_tokens += int(full_window)
            self.segments += 1
            self.buckets[int(window)] = self.buckets.get(int(window), 0) + 1

    def report(self) -> dict:
        with self._lock:
            full = self.full_tokens
            return {
                "attended_tokens": self.attended_tokens,
                "window_tokens": self.window_tokens,
                "full_tokens": full,
                "savings_ratio": (round(self.window_tokens / full, 4)
                                  if full else 1.0),
                "attended_ratio": (round(self.attended_tokens / full, 4)
                                   if full else 1.0),
                "segments": self.segments,
                "buckets": {str(w): n
                            for w, n in sorted(self.buckets.items())},
            }


@dataclass
class MeshStats:
    """Gauges + counters for tensor-parallel sharded serving (the
    ``batching.mesh`` block on ``/metrics``). ``shape`` is the serving
    mesh ({axis: size}, size-1 axes omitted) over ``devices`` chips.
    The byte gauges are refreshed from the LIVE engine state at scrape
    time (host-only shard metadata, no device reads):
    ``kv_bytes_per_device`` is the busiest device's share of the
    engine's KV residency (B-slot carry, or the paged arena) vs
    ``kv_bytes_replicated`` — the same object's single-device
    footprint; ``hbm_savings`` is their ratio (~1/tp when the head
    sharding holds, 1.0 means the mesh is paying collectives for
    nothing). ``param_bytes_per_device`` / ``param_bytes_total`` track
    the weights the same way. ``collectives_per_segment`` is the
    analytic Megatron-layout count for one engine segment — per decoded
    token, one all-reduce for the vocab-sharded embedding lookup, one
    after the row-parallel o_proj and one after down_proj per layer,
    plus one lm_head logits all-gather per select — i.e.
    ``segment * (2 * layers + 2)``; 0 on a tp-less mesh.
    ``segments_sharded`` counts segments dispatched over the mesh."""

    shape: dict = field(default_factory=dict)
    devices: int = 1
    kv_bytes_per_device: int = 0
    kv_bytes_replicated: int = 0
    param_bytes_per_device: int = 0
    param_bytes_total: int = 0
    collectives_per_segment: int = 0
    segments_sharded: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def set_layout(self, *, shape: dict, devices: int,
                   collectives_per_segment: int) -> None:
        with self._lock:
            self.shape = {str(a): int(n) for a, n in shape.items()}
            self.devices = int(devices)
            self.collectives_per_segment = int(collectives_per_segment)

    def set_kv_bytes(self, per_device: int, replicated: int) -> None:
        with self._lock:
            self.kv_bytes_per_device = int(per_device)
            self.kv_bytes_replicated = int(replicated)

    def set_param_bytes(self, per_device: int, total: int) -> None:
        with self._lock:
            self.param_bytes_per_device = int(per_device)
            self.param_bytes_total = int(total)

    def record_segment(self, n: int = 1) -> None:
        with self._lock:
            self.segments_sharded += int(n)

    def report(self) -> dict:
        with self._lock:
            rep = self.kv_bytes_replicated
            return {
                "shape": dict(self.shape),
                "devices": self.devices,
                "kv_bytes_per_device": self.kv_bytes_per_device,
                "kv_bytes_replicated": rep,
                "hbm_savings": (round(self.kv_bytes_per_device / rep, 4)
                                if rep else 1.0),
                "param_bytes_per_device": self.param_bytes_per_device,
                "param_bytes_total": self.param_bytes_total,
                "param_savings": (
                    round(self.param_bytes_per_device
                          / self.param_bytes_total, 4)
                    if self.param_bytes_total else 1.0),
                "collectives_per_segment": self.collectives_per_segment,
                "segments_sharded": self.segments_sharded,
            }


@dataclass
class PipelineStats:
    """Counters for the continuous engine's pipelined dispatch/collect
    loop (the ``batching.pipeline`` block on ``/metrics``). ``in_flight``
    histograms the pipeline depth at each dispatch (how many segments
    were queued on the device, this one included); ``drains`` counts the
    barrier causes (``joiner`` = a pending joiner forced a bounded drain
    so packing sees host-truth slots, ``complete`` = every live row
    reached its dispatch quota). ``wasted_overdecode_tokens`` are tokens
    fetched for rows that had already finished (EOS observed behind the
    dispatch frontier) and were discarded host-side. ``overlap_ratio`` =
    device-busy / wall: device-busy is the union of each segment's
    [dispatch, fetch-complete] interval, so 1.0 means the device always
    had a segment in flight while the host fetched and booked results —
    the overlap the pipeline exists to create."""

    depth: int = 1             # configured pipeline_depth
    segments: int = 0          # segments collected (host-fetched)
    dispatches: int = 0        # segments dispatched
    wasted_tokens: int = 0     # over-decoded tokens discarded host-side
    inflight: dict = field(default_factory=dict)  # depth -> dispatches
    drains: dict = field(default_factory=dict)    # cause -> count
    device_busy_s: float = 0.0
    fetch_block_s: float = 0.0  # host wall spent blocked in device_get
    wall_s: float = 0.0         # engine-busy wall (idle time excluded)
    _cover_end: float = field(default=0.0, repr=False)
    # monotonic start of the episode currently running, or None when the
    # engine is idle — report() folds the open episode into wall so a
    # mid-episode scrape never divides device_busy_s by a stale wall
    _ep_t0: float | None = field(default=None, repr=False)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record_dispatch(self, inflight_depth: int) -> None:
        with self._lock:
            self.dispatches += 1
            self.inflight[int(inflight_depth)] = \
                self.inflight.get(int(inflight_depth), 0) + 1

    def record_collect(self, dispatch_t: float, ready_t: float, *,
                       fetch_s: float, wasted: int) -> None:
        with self._lock:
            self.segments += 1
            self.wasted_tokens += int(wasted)
            self.fetch_block_s += max(0.0, fetch_s)
            # union of [dispatch, compute-ready] intervals (ready is
            # when block_until_ready returned — BEFORE the fetch RTT,
            # which the device spends idle unless another segment is
            # queued behind it), accumulated incrementally: both
            # endpoints are monotone across segments, so the uncovered
            # part of this interval starts at the later of its own
            # dispatch and the previous cover's end
            self.device_busy_s += max(
                0.0, ready_t - max(dispatch_t, self._cover_end))
            self._cover_end = max(self._cover_end, ready_t)

    def record_drain(self, cause: str) -> None:
        with self._lock:
            self.drains[cause] = self.drains.get(cause, 0) + 1

    def begin_episode(self, t: float) -> None:
        """Mark an engine episode open at monotonic time ``t``."""
        with self._lock:
            self._ep_t0 = t

    def record_wall(self, seconds: float) -> None:
        """Close the open episode, folding its wall into ``wall_s``."""
        with self._lock:
            self.wall_s += max(0.0, seconds)
            self._ep_t0 = None

    def report(self) -> dict:
        with self._lock:
            wall = self.wall_s
            if self._ep_t0 is not None:
                wall += max(0.0, time.monotonic() - self._ep_t0)
            return {
                "depth": self.depth,
                "segments": self.segments,
                "dispatches": self.dispatches,
                "wasted_overdecode_tokens": self.wasted_tokens,
                "in_flight": {str(d): n
                              for d, n in sorted(self.inflight.items())},
                "drains": dict(self.drains),
                "device_busy_s": round(self.device_busy_s, 4),
                "fetch_block_s": round(self.fetch_block_s, 4),
                "wall_s": round(wall, 4),
                "overlap_ratio": (round(self.device_busy_s / wall, 4)
                                  if wall else 0.0),
            }


@dataclass
class EngineFaultStats:
    """Counters + gauges for the continuous engine's fault-isolation
    layer (the ``batching.faults`` block on ``/metrics``). ``failures``
    keys engine failures by site (a ``watchdog:`` prefix marks waits the
    monitor gave up on); ``replays`` track rows transparently requeued
    through a restarted engine and how many of those completed;
    ``cancelled`` counts rows dropped at a drain barrier because their
    waiter went away (closed stream) or their deadline expired.
    ``degrade_level`` is the ladder position (0 = full service, 1 =
    pipeline depth forced to 1, 2 = + window bucketing off, 3 = + prefix
    cache bypassed); ``degrade_steps`` counts entries into each level
    with the site that caused the last step. ``recoveries`` counts the
    first successful device fetch after a failure (the engine is
    demonstrably serving again), ``restores`` the ladder resetting to 0
    after a clean interval. ``wedged`` mirrors what ``/healthz``
    reports."""

    failures: dict = field(default_factory=dict)   # site -> count
    watchdog_trips: int = 0
    replays_attempted: int = 0
    replays_succeeded: int = 0
    cancelled: int = 0
    degrade_level: int = 0                          # gauge
    degrade_steps: dict = field(default_factory=dict)  # level -> entries
    last_degrade_cause: str | None = None
    recoveries: int = 0
    restores: int = 0
    wedged: bool = False                            # gauge
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record_failure(self, site: str, *, watchdog: bool = False) -> None:
        with self._lock:
            self.failures[site] = self.failures.get(site, 0) + 1
            if watchdog:
                self.watchdog_trips += 1

    def record_replays(self, *, attempted: int = 0, succeeded: int = 0
                       ) -> None:
        with self._lock:
            self.replays_attempted += int(attempted)
            self.replays_succeeded += int(succeeded)

    def record_cancelled(self, n: int = 1) -> None:
        with self._lock:
            self.cancelled += int(n)

    def record_degrade(self, level: int, cause: str) -> None:
        with self._lock:
            self.degrade_level = int(level)
            self.degrade_steps[str(level)] = \
                self.degrade_steps.get(str(level), 0) + 1
            self.last_degrade_cause = cause

    def record_restore(self) -> None:
        with self._lock:
            if self.degrade_level:
                self.restores += 1
            self.degrade_level = 0

    def record_recovery(self) -> None:
        with self._lock:
            self.recoveries += 1

    def set_wedged(self, wedged: bool) -> None:
        with self._lock:
            self.wedged = bool(wedged)

    def report(self) -> dict:
        with self._lock:
            return {
                "failures": dict(self.failures),
                "watchdog_trips": self.watchdog_trips,
                "replays": {"attempted": self.replays_attempted,
                            "succeeded": self.replays_succeeded},
                "cancelled": self.cancelled,
                "degrade_level": self.degrade_level,
                "degrade_steps": dict(self.degrade_steps),
                "last_degrade_cause": self.last_degrade_cause,
                "recoveries": self.recoveries,
                "restores": self.restores,
                "wedged": self.wedged,
            }


@dataclass
class SpecDecodeStats:
    """Counters for speculative decoding — the ``batching.spec`` block on
    ``/metrics`` when the continuous engine runs with ``spec_k``, and the
    ``spec`` block for the solo ``"speculative": k`` request path. ONE
    object serves both (``LlamaServer.spec_metrics``; the engine shares
    the server's instance), so operators read acceptance through one
    surface regardless of which path a request took.

    A *step* is one verify dispatch: ``proposed`` draft tokens offered
    (``kb - 1`` per step), ``accepted`` of them matched the target
    chain, ``emitted`` tokens delivered (accepted + the always-correct
    corrected/pending token). ``acceptance_rate`` = accepted/proposed;
    ``tokens_per_step`` = emitted/steps — the speedup's direct proxy
    (decode is weight-bytes-bound, so tokens/step ~ tok/s multiplier).
    ``wasted_verify_tokens`` are proposed-but-rejected positions: the
    verify FLOPs burned for nothing (each rejected position still paid
    its slice of the chunk forward). ``draft_hits``/``draft_misses``
    split steps by whether prompt-lookup found an n-gram match or fell
    back (repeat-last-token / unknown pending); ``hist`` buckets steps
    by tokens emitted (1..kb — a mass at 1 means drafts never land).
    ``fallback_rows`` counts whole requests that degraded to plain
    decode (no room for a verify chunk near the context boundary).
    ``row_fallbacks`` keys those by reason. ``sp_standdown`` mirrors
    the sequence-parallel decode stand-down counter
    (:func:`lambdipy_tpu.parallel.spdecode.standdown_count`) so the
    silently-degraded long-context condition is visible next to the
    speculation counters it gates."""

    steps: int = 0
    emitted_tokens: int = 0
    proposed_tokens: int = 0
    accepted_tokens: int = 0
    wasted_verify_tokens: int = 0
    draft_hits: int = 0
    draft_misses: int = 0
    fallback_rows: int = 0
    row_fallbacks: dict = field(default_factory=dict)  # reason -> rows
    hist: dict = field(default_factory=dict)           # emitted -> steps
    # -- draft tier (batching.spec.draft) -- per-PROVIDER step counters +
    # acceptance EWMA (model / lookup / aux), the dispatched-k histogram
    # (adaptive-k convergence is readable straight off it: mass at the
    # cap means rows grew, mass at 2 means they collapsed), and the
    # per-row provider-demotion counts ("model->lookup", "lookup->off")
    providers: dict = field(default_factory=dict)      # name -> counters
    k_hist: dict = field(default_factory=dict)         # k -> steps
    draft_fallbacks: dict = field(default_factory=dict)  # edge -> rows
    draft_ewma_alpha: float = 0.2
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record_step(self, *, proposed: int, accepted: int, emitted: int,
                    hit: bool, provider: str = "lookup",
                    k: int | None = None) -> None:
        with self._lock:
            self.steps += 1
            self.proposed_tokens += int(proposed)
            self.accepted_tokens += int(accepted)
            self.emitted_tokens += int(emitted)
            self.wasted_verify_tokens += max(0, int(proposed) - int(accepted))
            if hit:
                self.draft_hits += 1
            else:
                self.draft_misses += 1
            self.hist[int(emitted)] = self.hist.get(int(emitted), 0) + 1
            p = self.providers.setdefault(
                str(provider), {"steps": 0, "proposed": 0, "accepted": 0,
                                "ewma": None})
            p["steps"] += 1
            p["proposed"] += int(proposed)
            p["accepted"] += int(accepted)
            if proposed > 0:
                frac = int(accepted) / float(proposed)
                a = self.draft_ewma_alpha
                p["ewma"] = (frac if p["ewma"] is None
                             else (1.0 - a) * p["ewma"] + a * frac)
            if k is not None:
                self.k_hist[int(k)] = self.k_hist.get(int(k), 0) + 1

    def record_fallback(self, reason: str = "plain") -> None:
        with self._lock:
            self.fallback_rows += 1
            self.row_fallbacks[str(reason)] = \
                self.row_fallbacks.get(str(reason), 0) + 1

    def record_draft_fallback(self, edge: str) -> None:
        """One row demoted along the provider chain (edge like
        ``"model->lookup"``) by the engine's per-row adaptive-k
        controller."""
        with self._lock:
            self.draft_fallbacks[str(edge)] = \
                self.draft_fallbacks.get(str(edge), 0) + 1

    def report(self) -> dict:
        try:
            from lambdipy_tpu.parallel.spdecode import standdown_stats
            sd = standdown_stats()
            standdowns, sd_reasons = sd["spec_standdown"], sd["reasons"]
        except Exception:  # pragma: no cover — observability only
            standdowns, sd_reasons = 0, {}
        with self._lock:
            steps, proposed = self.steps, self.proposed_tokens
            return {
                "steps": steps,
                "emitted_tokens": self.emitted_tokens,
                "proposed_tokens": proposed,
                "accepted_tokens": self.accepted_tokens,
                "acceptance_rate": (round(self.accepted_tokens / proposed, 4)
                                    if proposed else 0.0),
                "tokens_per_step": (round(self.emitted_tokens / steps, 3)
                                    if steps else 0.0),
                "wasted_verify_tokens": self.wasted_verify_tokens,
                "draft_hits": self.draft_hits,
                "draft_misses": self.draft_misses,
                "draft_hit_rate": (round(self.draft_hits / steps, 4)
                                   if steps else 0.0),
                "fallback_rows": self.fallback_rows,
                "row_fallbacks": dict(self.row_fallbacks),
                "tokens_per_step_hist": {str(n): c for n, c in
                                         sorted(self.hist.items())},
                # the draft-tier block the fleet controller reads:
                # per-provider acceptance EWMA (policy demotes
                # draft_mode when the model provider's collapses), the
                # adaptive-k histogram, and provider-demotion counts
                "draft": {
                    "providers": {
                        name: {"steps": p["steps"],
                               "proposed": p["proposed"],
                               "accepted": p["accepted"],
                               "acceptance_ewma": (
                                   round(p["ewma"], 4)
                                   if p["ewma"] is not None else None)}
                        for name, p in sorted(self.providers.items())},
                    "k_hist": {str(n): c for n, c in
                               sorted(self.k_hist.items())},
                    "fallbacks": dict(self.draft_fallbacks),
                },
                "sp_standdown": standdowns,
                # keyed by reason so a fleet can tell "blocked backend
                # under an sp mesh" from "spec chunk under ring" at the
                # router — the aggregated /metrics sums these per reason
                "sp_standdown_reasons": dict(sd_reasons),
            }


@dataclass
class PagePoolStats:
    """Counters for the paged KV memory manager (the
    ``batching.page_pool`` block on ``/metrics``; gauges — pages
    free/live/shared, fragmentation, refcount histogram, capacity rows —
    ride on :meth:`lambdipy_tpu.runtime.pagepool.PagePool.stats`, which
    merges this report in). ``allocs``/``alloc_pages`` count allocation
    calls and pages taken, ``releases``/``release_pages`` pages actually
    returned to the free list (a release of a still-shared page is a
    refcount drop, not a free), ``shares`` refcount bumps (each one is a
    prefix-cache hit's zero-copy page reuse), and ``sheds`` admissions
    refused with :class:`~lambdipy_tpu.runtime.pagepool.PagesExhausted`
    (priced 503s, not errors)."""

    allocs: int = 0
    alloc_pages: int = 0
    releases: int = 0
    release_pages: int = 0
    shares: int = 0
    sheds: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record_alloc(self, pages: int) -> None:
        with self._lock:
            self.allocs += 1
            self.alloc_pages += int(pages)

    def record_release(self, pages: int) -> None:
        with self._lock:
            self.releases += 1
            self.release_pages += int(pages)

    def record_share(self, pages: int = 1) -> None:
        with self._lock:
            self.shares += int(pages)

    def record_shed(self) -> None:
        with self._lock:
            self.sheds += 1

    def report(self) -> dict:
        with self._lock:
            return {
                "allocs": self.allocs,
                "alloc_pages": self.alloc_pages,
                "releases": self.releases,
                "release_pages": self.release_pages,
                "shares": self.shares,
                "sheds": self.sheds,
            }


@dataclass
class KvOffloadStats:
    """Counters for the paged-KV host-offload tier (the ``kv.offload``
    block on ``/metrics``; residency gauges ride on
    :meth:`lambdipy_tpu.runtime.offload.OffloadArena.gauges`, merged
    into the pool's stats). ``spills``/``spill_pages`` count spill calls
    and pages moved to host RAM, ``reonlines``/``reonline_pages`` the
    batched fetch-and-write round trips back into the device arena
    (``reonline_batches`` meters how well the prefetcher coalesces
    them — one frame decode per batch, not per page), and
    ``template_encodes`` every derivation of the kvwire leaf template
    from live arrays — the hot loop must keep it at its attach-time
    value (one), which ``tests/test_long_context.py`` asserts.
    ``prefetch_hits`` are pages the decode-cursor prefetcher had
    already re-onlined when attention demanded them; ``demand_misses``
    stalled the dispatch (``stall_s`` accumulates that wait).
    ``recomputes`` count failed re-onlines degraded to prefill
    recompute — counted work, never a wrong token."""

    spills: int = 0
    spill_pages: int = 0
    spill_bytes: int = 0
    reonlines: int = 0
    reonline_pages: int = 0
    reonline_batches: int = 0
    frame_decodes: int = 0
    template_encodes: int = 0
    prefetch_hits: int = 0
    demand_misses: int = 0
    stall_s: float = 0.0
    stalls: int = 0
    recomputes: int = 0
    drops: int = 0
    spill_refusals: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record_spill(self, pages: int, nbytes: int) -> None:
        with self._lock:
            self.spills += 1
            self.spill_pages += int(pages)
            self.spill_bytes += int(nbytes)

    def record_spill_refusal(self) -> None:
        with self._lock:
            self.spill_refusals += 1

    def record_reonline(self, pages: int, *, batches: int = 1,
                        decodes: int = 1) -> None:
        with self._lock:
            self.reonlines += 1
            self.reonline_pages += int(pages)
            self.reonline_batches += int(batches)
            self.frame_decodes += int(decodes)

    def record_template_encode(self) -> None:
        with self._lock:
            self.template_encodes += 1

    def record_prefetch(self, hits: int, misses: int) -> None:
        with self._lock:
            self.prefetch_hits += int(hits)
            self.demand_misses += int(misses)

    def record_stall(self, seconds: float) -> None:
        with self._lock:
            self.stalls += 1
            self.stall_s += float(seconds)

    def record_recompute(self, pages: int = 1) -> None:
        with self._lock:
            self.recomputes += int(pages)

    def record_drop(self, pages: int = 1) -> None:
        with self._lock:
            self.drops += int(pages)

    def report(self) -> dict:
        with self._lock:
            demanded = self.prefetch_hits + self.demand_misses
            return {
                "spills": self.spills,
                "spill_pages": self.spill_pages,
                "spill_bytes": self.spill_bytes,
                "reonlines": self.reonlines,
                "reonline_pages": self.reonline_pages,
                "reonline_batches": self.reonline_batches,
                "frame_decodes": self.frame_decodes,
                "template_encodes": self.template_encodes,
                "prefetch_hits": self.prefetch_hits,
                "demand_misses": self.demand_misses,
                "prefetch_hit_rate": (
                    round(self.prefetch_hits / demanded, 4)
                    if demanded else 1.0),
                "stalls": self.stalls,
                "stall_s": round(self.stall_s, 6),
                "recomputes": self.recomputes,
                "drops": self.drops,
                "spill_refusals": self.spill_refusals,
            }


@dataclass
class PrefillStats:
    """Counters for the cold-prefill tier (the ``batching.prefill``
    block on ``/metrics``), shared by the continuous engine's prefill
    paths and the prefix store's cold walks. A ROUND is one program
    dispatch on the TTFT critical path; under ``prefill_mode=sp`` a
    round carries up to ``sp`` chunk-widths of the prompt (shard
    occupancy = chunks / (rounds x sp)), under ``chunked`` every round
    is one chunk. ``ring_collectives`` counts the modeled ring hops of
    sharded first-round programs (layers x sp ppermute steps each).
    ``critical_path_s`` is host wall time over whole walks — with
    device time modeled through the ``prefix_walk`` delay site (the
    --disagg / --sp-prefill bench idiom) it IS the modeled TTFT
    critical path; ``serial_equiv_s`` scales each walk's wall by its
    chunks/rounds ratio, the chunked-equivalent cost the sharded
    schedule avoided. ``standdowns`` mirrors the counted reasons a
    requested sp prefill ran chunked (no sp mesh axis, pool pressure,
    window not divisible)."""

    mode: str = "chunked"
    sp: int = 0
    rounds: int = 0
    chunks: int = 0
    sharded_chunks: int = 0
    ring_collectives: int = 0
    walks: int = 0
    critical_path_s: float = 0.0
    serial_equiv_s: float = 0.0
    standdowns: dict = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def configure(self, mode: str, sp: int) -> None:
        with self._lock:
            self.mode = str(mode)
            self.sp = int(sp)

    def record_round(self, chunks: int, sp: int, *,
                     ring_hops: int = 0) -> None:
        with self._lock:
            self.rounds += 1
            self.chunks += int(chunks)
            if sp >= 2:
                self.sharded_chunks += int(chunks)
            self.ring_collectives += int(ring_hops)

    def record_walk(self, wall_s: float, chunks: int, rounds: int) -> None:
        with self._lock:
            self.walks += 1
            self.critical_path_s += float(wall_s)
            self.serial_equiv_s += float(wall_s) * (
                int(chunks) / max(1, int(rounds)))

    def record_standdown(self, reason: str) -> None:
        with self._lock:
            self.standdowns[reason] = self.standdowns.get(reason, 0) + 1

    def report(self) -> dict:
        with self._lock:
            slots = self.rounds * max(1, self.sp)
            return {
                "mode": self.mode,
                "sp": self.sp,
                "rounds": self.rounds,
                "chunks": self.chunks,
                "sharded_chunks": self.sharded_chunks,
                "shard_occupancy": (
                    round(self.chunks / slots, 4) if self.rounds else 0.0),
                "ring_collectives": self.ring_collectives,
                "walks": self.walks,
                "critical_path_s": round(self.critical_path_s, 6),
                "serial_equiv_s": round(self.serial_equiv_s, 6),
                "standdowns": dict(self.standdowns),
            }


@dataclass
class KvShipStats:
    """Replica-side counters for the disaggregated-serving KV ship
    surface (the ``batching.disagg`` block on ``/metrics``). Exports are
    ``/v1/kv/export`` frames served (a prefill-class replica's output);
    imports are ``/v1/kv/import`` frames registered in the radix tree.
    ``import_blocks_present`` counts blocks an import found already
    cached (the router's dedup missed, or two ships raced — the import
    is idempotent); ``imports_zero_copy`` vs ``imports_assembled``
    splits imports by how a later hit CONSUMES them: paged-mode imports
    land in arena pages (a hit is an ``acquire_pages`` refcount bump,
    zero copies), dense-mode imports are tree slices (a hit pays a
    ``concat_cache_blocks`` assembly). ``import_backpressure`` counts
    imports refused because the page arena was full — the priced-shed
    path the router's fallback-to-mixed rides.

    The ``*_stream``/``*_chunk`` counters cover the PIPELINED (chunked)
    ship: streamed exports/imports are the subset that rode the
    ``LKVS``/``LKVC`` frame stream, chunk counters are the wire frames
    flushed/received, and ``import_stream_aborts`` counts chunked
    imports that rolled their staged pages back (truncated stream,
    garbage chunk, dead relay) — an abort touches nothing, so it is a
    wasted transfer, never a corrupt tree."""

    exports: int = 0
    export_bytes: int = 0
    export_tokens: int = 0
    export_streams: int = 0
    export_chunks: int = 0
    imports: int = 0
    import_bytes: int = 0
    import_tokens: int = 0
    import_streams: int = 0
    import_chunks: int = 0
    import_stream_aborts: int = 0
    import_blocks_inserted: int = 0
    import_blocks_present: int = 0
    imports_zero_copy: int = 0
    imports_assembled: int = 0
    import_backpressure: int = 0
    import_rejected: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record_export(self, *, tokens: int, nbytes: int,
                      chunks: int = 0) -> None:
        with self._lock:
            self.exports += 1
            self.export_tokens += int(tokens)
            self.export_bytes += int(nbytes)
            if chunks:
                self.export_streams += 1
                self.export_chunks += int(chunks)

    def record_import(self, *, tokens: int, nbytes: int, inserted: int,
                      present: int, mode: str, chunks: int = 0) -> None:
        with self._lock:
            self.imports += 1
            self.import_tokens += int(tokens)
            self.import_bytes += int(nbytes)
            self.import_blocks_inserted += int(inserted)
            self.import_blocks_present += int(present)
            if chunks:
                self.import_streams += 1
                self.import_chunks += int(chunks)
            if mode == "paged":
                self.imports_zero_copy += 1
            else:
                self.imports_assembled += 1

    def record_backpressure(self) -> None:
        with self._lock:
            self.import_backpressure += 1

    def record_rejected(self) -> None:
        with self._lock:
            self.import_rejected += 1

    def record_stream_abort(self) -> None:
        with self._lock:
            self.import_stream_aborts += 1

    def report(self) -> dict:
        with self._lock:
            return {
                "exports": self.exports,
                "export_bytes": self.export_bytes,
                "export_tokens": self.export_tokens,
                "export_streams": self.export_streams,
                "export_chunks": self.export_chunks,
                "imports": self.imports,
                "import_bytes": self.import_bytes,
                "import_tokens": self.import_tokens,
                "import_streams": self.import_streams,
                "import_chunks": self.import_chunks,
                "import_stream_aborts": self.import_stream_aborts,
                "import_blocks": {
                    "inserted": self.import_blocks_inserted,
                    "present": self.import_blocks_present,
                },
                "imports_zero_copy": self.imports_zero_copy,
                "imports_assembled": self.imports_assembled,
                "import_backpressure": self.import_backpressure,
                "import_rejected": self.import_rejected,
            }


@dataclass
class DisaggStats:
    """Router-side counters for phase-split (disaggregated) serving —
    the ``fleet.disagg`` block on the fleet ``/metrics``.

    ``prefill_dispatches`` counts export legs that completed on a
    prefill-class replica; ``decode_dispatches`` counts full ships
    (export + import both landed, so the decode replica serves the
    request from shipped KV). ``ship_skips`` are requests whose prefix
    the router already shipped to that decode replica (the per-replica
    shipped-key LRU). ``fallbacks`` keys every path back to MIXED-mode
    local prefill by reason — a fallback is a slower request, never a
    lost one. The byte/latency EWMAs (alpha 0.2) price the transfer the
    way the page pool prices its backpressure.

    PIPELINED shipping: ``ships_pipelined`` counts ships that rode the
    chunked relay (export frames pumped to the import leg while later
    prefill chunks were still running), ``chunks_relayed`` the ``LKVC``
    frames pumped, and ``mid_stream_failures`` ships that died AFTER
    the stream opened (truncated export, dead import leg, injected
    ``kv_ship_chunk`` fault) — every one also lands in ``fallbacks``
    by reason, because a mid-stream death degrades to mixed-mode like
    any other ship failure.

    ``util`` is the per-replica-class busy-fraction EWMA (alpha 0.3)
    the router folds from pool occupancy at scrape time — the
    observability basis for sizing the prefill pool: a prefill class
    pinned near 1.0 while decode idles wants more prefill replicas
    (and vice versa)."""

    prefill_dispatches: int = 0
    decode_dispatches: int = 0
    ships: int = 0
    ships_pipelined: int = 0
    chunks_relayed: int = 0
    mid_stream_failures: int = 0
    ship_skips: int = 0
    ship_bytes_total: int = 0
    ship_bytes_ewma: float = 0.0
    ship_ms_ewma: float = 0.0
    import_blocks_inserted: int = 0
    import_blocks_present: int = 0
    imports_zero_copy: int = 0
    imports_assembled: int = 0
    fallbacks: dict = field(default_factory=dict)  # reason -> n
    util: dict = field(default_factory=dict)       # class -> busy EWMA
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def count(self, counter: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, counter, getattr(self, counter) + n)

    def record_fallback(self, reason: str) -> None:
        with self._lock:
            self.fallbacks[str(reason)] = \
                self.fallbacks.get(str(reason), 0) + 1

    def record_ship(self, *, nbytes: int, ms: float, chunks: int = 0,
                    pipelined: bool = False) -> None:
        with self._lock:
            self.ships += 1
            if chunks:
                self.chunks_relayed += int(chunks)
            if pipelined:
                # explicitly flagged, NOT inferred from chunks: the
                # blocking buffer-then-relay baseline ships chunk
                # frames too but overlaps nothing
                self.ships_pipelined += 1
            self.ship_bytes_total += int(nbytes)
            a = 0.2
            if self.ships == 1:
                self.ship_bytes_ewma = float(nbytes)
                self.ship_ms_ewma = float(ms)
            else:
                self.ship_bytes_ewma = ((1 - a) * self.ship_bytes_ewma
                                        + a * float(nbytes))
                self.ship_ms_ewma = ((1 - a) * self.ship_ms_ewma
                                     + a * float(ms))

    def record_util(self, cls: str, busy_frac: float) -> None:
        """Fold one busy-fraction sample (0..1) for a replica class
        into its EWMA — called by the router at scrape time from the
        pool's time-weighted occupancy accounting."""
        frac = min(1.0, max(0.0, float(busy_frac)))
        with self._lock:
            prev = self.util.get(str(cls))
            self.util[str(cls)] = (frac if prev is None
                                   else 0.7 * prev + 0.3 * frac)

    def record_import_result(self, *, inserted: int, present: int,
                             mode: str) -> None:
        with self._lock:
            self.import_blocks_inserted += int(inserted)
            self.import_blocks_present += int(present)
            if mode == "paged":
                self.imports_zero_copy += 1
            else:
                self.imports_assembled += 1

    def report(self) -> dict:
        with self._lock:
            return {
                "prefill_dispatches": self.prefill_dispatches,
                "decode_dispatches": self.decode_dispatches,
                "ships": self.ships,
                "ships_pipelined": self.ships_pipelined,
                "chunks_relayed": self.chunks_relayed,
                "mid_stream_failures": self.mid_stream_failures,
                "ship_skips": self.ship_skips,
                "ship_bytes_total": self.ship_bytes_total,
                "ship_bytes_ewma": round(self.ship_bytes_ewma, 1),
                "ship_ms_ewma": round(self.ship_ms_ewma, 3),
                "util": {cls: round(v, 4)
                         for cls, v in sorted(self.util.items())},
                "import_blocks": {
                    "inserted": self.import_blocks_inserted,
                    "present": self.import_blocks_present,
                },
                "imports_zero_copy": self.imports_zero_copy,
                "imports_assembled": self.imports_assembled,
                "fallbacks": dict(self.fallbacks),
            }


@dataclass
class SessionStats:
    """Router-side counters for sticky multi-turn sessions — the
    ``fleet.sessions`` block on the fleet ``/metrics``.

    ``opened`` counts session ids first seen; ``sticky_hits`` turns that
    landed on their recorded home replica, ``sticky_misses`` pick
    attempts whose preferred home was unusable at pick time — a
    saturation spill (the home past the outstanding threshold), or the
    home vanishing between the sticky check and the pick. The turn
    still serves and re-homes; under retries/spill a single turn can
    count more than one miss, so hits/misses are attempt-level, not
    turn-level.
    ``failovers`` counts re-homings off a dead/drained home; ``reships``
    the subset whose whole-block KV head was successfully re-shipped to
    the new home (export from the old home → import on the new one), and
    ``reship_fallbacks`` keys the rest by reason — the common SIGKILL
    case is ``old_home_unreachable``: the KV died with the worker, so
    the new home's counted local re-prefill IS the recovery path.
    ``deletes`` counts explicit ``DELETE /v1/sessions/{id}`` closes.
    ``drain_reships`` counts PROACTIVE re-ships fired by a home
    replica's ``begin_drain`` (the session's pinned head moves to its
    rendezvous successor BEFORE the next turn arrives, so the turn
    after a rolling restart pays a sticky hit, not a failover
    re-prefill); their failures land in ``reship_fallbacks`` like
    turn-time ones. ``record_expiries`` counts sticky records swept by
    the router's idle TTL — replica-side pin leases expire on their
    own, and without the sweep the router's session gauge drifted
    arbitrarily far from the fleet's real pinned state (a chaos-soak
    find)."""

    opened: int = 0
    sticky_hits: int = 0
    sticky_misses: int = 0
    failovers: int = 0
    reships: int = 0
    drain_reships: int = 0
    deletes: int = 0
    record_expiries: int = 0
    reship_fallbacks: dict = field(default_factory=dict)  # reason -> n
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def count(self, counter: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, counter, getattr(self, counter) + n)

    def record_fallback(self, reason: str) -> None:
        with self._lock:
            self.reship_fallbacks[str(reason)] = \
                self.reship_fallbacks.get(str(reason), 0) + 1

    def report(self) -> dict:
        with self._lock:
            return {
                "opened": self.opened,
                "sticky_hits": self.sticky_hits,
                "sticky_misses": self.sticky_misses,
                "failovers": self.failovers,
                "reships": self.reships,
                "drain_reships": self.drain_reships,
                "deletes": self.deletes,
                "record_expiries": self.record_expiries,
                "reship_fallbacks": dict(self.reship_fallbacks),
            }


@dataclass
class RouterStats:
    """Counters for the fleet front-door (fleet/router.py), exported on
    the router's ``/metrics`` under ``router``. ``retries`` counts
    re-sends after a retryable failure (connection loss or a 429/503
    shed), ``failovers`` the subset caused by a dead connection;
    ``hedges``/``hedge_wins`` track duplicate sends for slow requests
    and how often the duplicate answered first. The ``affinity_*``
    counters measure prefix-affinity routing: a hit means the request
    reached its rendezvous-hash target; fallbacks record why it did not
    (target ejected/busy). ``latency`` is the router-observed end-to-end
    distribution — the P9x basis for the hedging threshold.

    The ``spill_*`` counters track the router's fleet-wide-overload
    parking lot (fleet/spill.py): ``spilled`` = requests parked at
    least once, ``spill_drained`` = grants back into the retry loop,
    ``spill_expired``/``spill_overflow`` = the queue's own sheds (the
    live depth/wait gauges ride on the spill queue's report in the
    router ``/metrics``). ``retry_budget_denied`` counts re-sends the
    fleet-wide retry budget refused; ``warmed_prefixes`` counts hot
    radix prefixes replayed into a readmitted/attached replica's
    cache."""

    requests: int = 0
    completed: int = 0
    errors: int = 0
    retries: int = 0
    failovers: int = 0
    hedges: int = 0
    hedge_wins: int = 0
    no_replica: int = 0
    spilled: int = 0
    spill_drained: int = 0
    spill_expired: int = 0
    spill_overflow: int = 0
    retry_budget_denied: int = 0
    warmed_prefixes: int = 0
    affinity_requests: int = 0
    affinity_hits: int = 0
    affinity_fallbacks: dict = field(default_factory=dict)  # reason -> n
    latency: LatencyStats = field(default_factory=LatencyStats)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def count(self, counter: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, counter, getattr(self, counter) + n)

    def count_affinity(self, outcome: str) -> None:
        """``outcome``: 'hit', or a fallback reason ('saturated',
        'ejected', ...). Every call is one affinity-keyed request."""
        with self._lock:
            self.affinity_requests += 1
            if outcome == "hit":
                self.affinity_hits += 1
            else:
                self.affinity_fallbacks[outcome] = \
                    self.affinity_fallbacks.get(outcome, 0) + 1

    def report(self) -> dict:
        with self._lock:
            aff = dict(self.affinity_fallbacks)
            out = {
                "requests": self.requests,
                "completed": self.completed,
                "errors": self.errors,
                "retries": self.retries,
                "failovers": self.failovers,
                "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
                "no_replica": self.no_replica,
                "spill": {
                    "spilled": self.spilled,
                    "drained": self.spill_drained,
                    "expired": self.spill_expired,
                    "overflow": self.spill_overflow,
                },
                "retry_budget_denied": self.retry_budget_denied,
                "warmed_prefixes": self.warmed_prefixes,
                "affinity": {
                    "requests": self.affinity_requests,
                    "hits": self.affinity_hits,
                    "hit_rate": (round(self.affinity_hits
                                       / self.affinity_requests, 4)
                                 if self.affinity_requests else 0.0),
                    "fallbacks": aff,
                },
            }
        out["latency"] = self.latency.report()
        return out


@dataclass
class ControllerStats:
    """Counters for the elastic fleet control loop
    (fleet/controller.py) — the ``fleet.controller`` block on the
    fleet ``/metrics``.

    ``actions`` counts APPLIED actions by kind (promote/demote/spawn/
    retire/set_knob); ``intents`` counts decisions that were logged but
    NOT applied — every decision in dry-run mode, plus live decisions
    whose actuator refused (e.g. a spawn with no spawner wired).
    ``last_decision`` is the most recent non-empty decision trace
    (tick time, the signal values that drove it, the rendered
    actions) so an operator can answer "why did the fleet just
    resize" from one scrape. ``targets`` echoes the loop's current
    goal posts (SLO, bands, dry_run) — the knobs the controller is
    steering TOWARD, as opposed to the per-replica knobs it steers."""

    ticks: int = 0
    errors: int = 0
    actions: dict = field(default_factory=dict)   # kind -> applied n
    intents: dict = field(default_factory=dict)   # kind -> logged-only n
    last_decision: dict = field(default_factory=dict)
    targets: dict = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def count(self, counter: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, counter, getattr(self, counter) + n)

    def record_action(self, kind: str, *, applied: bool) -> None:
        with self._lock:
            book = self.actions if applied else self.intents
            book[str(kind)] = book.get(str(kind), 0) + 1

    def record_decision(self, trace: dict) -> None:
        with self._lock:
            self.last_decision = dict(trace)

    def set_targets(self, **targets) -> None:
        with self._lock:
            self.targets.update(targets)

    def report(self) -> dict:
        with self._lock:
            return {
                "ticks": self.ticks,
                "errors": self.errors,
                "actions": dict(sorted(self.actions.items())),
                "intents": dict(sorted(self.intents.items())),
                "last_decision": dict(self.last_decision),
                "targets": dict(sorted(self.targets.items())),
            }


@dataclass
class PrefixCacheStats:
    """Counters for the automatic cross-request prefix KV cache: a
    request whose prompt longest-prefix-matches the radix tree is a hit
    (``hit_tokens`` = prompt tokens whose prefill was skipped), one with
    cacheable length but no match is a miss. ``bytes``/``blocks`` track
    what the store currently holds against its HBM budget; ``evictions``
    counts blocks dropped by the budget's LRU sweep.
    ``assembly_bytes_peak`` is the largest single full-window cache the
    store has ASSEMBLED (``concat_cache_blocks``) for a hit — the copy +
    peak-HBM spike the paged path eliminates, reported explicitly (always
    present, 0 on the paged path) so "no assembly happened" is an
    observable fact rather than a missing key."""

    hits: int = 0
    misses: int = 0
    hit_tokens: int = 0
    evictions: int = 0
    bytes: int = 0
    blocks: int = 0
    assembly_bytes_peak: int = 0
    assemblies: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def record_request(self, matched_tokens: int) -> None:
        with self._lock:
            if matched_tokens > 0:
                self.hits += 1
                self.hit_tokens += matched_tokens
            else:
                self.misses += 1

    def record_insert(self, n_blocks: int, nbytes: int) -> None:
        with self._lock:
            self.blocks += n_blocks
            self.bytes += nbytes

    def record_evict(self, n_blocks: int, nbytes: int) -> None:
        with self._lock:
            self.blocks -= n_blocks
            self.bytes -= nbytes
            self.evictions += n_blocks

    def record_assembly(self, nbytes: int) -> None:
        with self._lock:
            self.assemblies += 1
            self.assembly_bytes_peak = max(self.assembly_bytes_peak,
                                           int(nbytes))

    def report(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else 0.0,
                "hit_tokens": self.hit_tokens,
                "evictions": self.evictions,
                "bytes": self.bytes,
                "blocks": self.blocks,
                "assemblies": self.assemblies,
                "assembly_bytes_peak": self.assembly_bytes_peak,
            }
