"""The long-context tier: serve 8-32x the compiled window through a
sliding block-table view + paged-KV host offload.

The compiled programs never grow: decode runs the ``models/llama.py
_lpaged_seg_fn`` family at the bundle's compiled ``window``, and the
block table maps a LOGICAL view of a far larger session — slot 0 of the
gathered window is logical token ``base``, the carry's cursor stays in
the LOCAL frame (cache writes, validity mask) while RoPE sees
``local + base``, the token's true logical position. When the cursor
reaches the view's edge the host slides the view forward by whole pages:
the evicted head pages spill to the :class:`~lambdipy_tpu.runtime
.offload.OffloadArena` (host RAM, kvwire bytes — the failover re-ship
and prefix-reuse read them back), their pool pages recycle into the
view's tail, and the device carry shifts frames with one exact int32
subtract. A 128k-token session runs over a 4k compiled window in a
FIXED page budget; with ``base = 0`` (any context that fits the window)
the programs compute bitwise what the plain paged path computes.

Attention is therefore windowed past the compiled width (each token
attends the most recent ``window``-ish logical positions — the page-
granular slide schedule is deterministic in the lengths alone), which is
the explicit contract of the tier: capacity beyond the window trades
global attention for a sliding window, never for shed.

Prefill is CHUNKED through the same view (``_lpaged_continue_fn``):
half-window chunks land at the cursor, the view sliding between chunks,
so TTFT grows linearly in prompt length instead of cliffing at the
window. With ``long_prefill=True`` and a ring-attention bundle
(``attn_backend="ring"`` over an ``sp`` mesh axis, ``parallel/ring.py``)
each chunk's attention is additionally sequence-sharded across the mesh
— the opt-in long-prefill mode; requesting it without a ring mesh stands
down counted (``note_standdown``), never silently.

``resident_cap`` is the pressure-yield mode: between segments the
runner spills the view's coldest already-full pages past the cap
(:class:`~lambdipy_tpu.runtime.offload.PageTemperature` picks victims)
and re-onlines them through the :class:`~lambdipy_tpu.runtime.offload
.Prefetcher` state machine keyed off the decode cursor — the prefetch
fetch+write is issued right after the (async) segment dispatch, so the
host frame decode hides under device compute and the next dispatch's
demand check finds the pages resident. A demand miss is a TIMED stall
(``kv.offload.stall_s``); a FAILED re-online (``offload_stall`` fault,
or a page the arena refused under budget) aborts the pass and the run
REPLAYS from scratch with yielding disabled — the schedule is
deterministic, so the replay emits identical tokens: a recompute
(counted), never a wrong token.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from lambdipy_tpu.runtime.metrics import KvOffloadStats
from lambdipy_tpu.runtime.offload import (
    OffloadArena,
    OffloadMiss,
    PageTemperature,
    Prefetcher,
)
from lambdipy_tpu.utils.logs import get_logger

log = get_logger("lambdipy.longctx")


class ReonlineFailed(RuntimeError):
    """A spilled page could not come back (injected fault or budget
    drop). Carries the original cause; the runner's replay path eats
    this up to ``max_replays`` times."""

    def __init__(self, cause: BaseException, pages: int):
        super().__init__(f"re-online of {pages} page(s) failed: {cause!r}")
        self.cause = cause
        self.pages = pages


class LongContextRunner:
    """Solo long-context decode over a shared page pool.

    One request at a time per runner call (the continuous engine routes
    over-window rows here the way it routes them to ``server.generate``
    today — the runner IS the solo fallback for the long tier). All
    device work runs under ``pool.arena_lock`` for enqueue time only,
    advancing the pool's functional arena chain exactly like the engine
    and the prefix store do, so a runner coexists with both on one
    pool."""

    def __init__(self, server: Any, pool: Any, offload: OffloadArena
                 | None = None, *, window: int | None = None,
                 segment: int = 16, max_logical_ctx: int = 0,
                 resident_cap: int | None = None,
                 long_prefill: bool = False, faults: Any = None,
                 max_replays: int = 2,
                 stats: KvOffloadStats | None = None,
                 prefill_mode: str = "chunked",
                 prefill_stats: Any = None):
        import itertools

        cfg = server.model.cfg
        self.server = server
        self.pool = pool
        self.window = int(window) if window else int(cfg.max_len)
        if self.window % pool.page or self.window < 2 * pool.page:
            raise ValueError(
                f"window {self.window} must be >= 2 whole {pool.page}-"
                f"token pages")
        self.n_view = self.window // pool.page
        self.segment = max(1, int(segment))
        self.max_logical_ctx = int(max_logical_ctx) \
            if max_logical_ctx else 32 * self.window
        # the boot-time cap: a fleet controller stepping max_logical_ctx
        # down on offload stalls restores toward this, never past it
        self.boot_logical_ctx = self.max_logical_ctx
        self.resident_cap = resident_cap
        self.max_replays = max(0, int(max_replays))
        self.stats = stats if stats is not None else KvOffloadStats()
        if offload is None:
            # share the pool's attached arena (the prefix store's host
            # tier) when one exists — one host budget, one stats block
            # on /metrics; runner keys are ("lc", run, page#) tuples, so
            # they can never collide with the store's token-path keys
            offload = getattr(pool, "offload", None)
            if offload is not None:
                self.stats = getattr(offload, "stats", self.stats)
        self.offload = offload if offload is not None else OffloadArena(
            page=pool.page, layers=cfg.layers, stats=self.stats,
            faults=faults)
        # one runner, one stats stream: an injected offload arena keeps
        # its own counters wired to the same block only if the caller
        # passed a shared KvOffloadStats
        if getattr(pool, "offload", None) is None:
            # surface kv_offload gauges through batching.page_pool even
            # when only the long-context tier spills
            pool.attach_offload(self.offload)
        self.temp = PageTemperature()
        # whole-prompt sp prefill (prefill_mode="sp"): the serial
        # window/2 slide chain collapses to rounds of sp chunks, each
        # round ONE sharded program (_lsp_round_fn); sp resolves per run
        # against the live mesh so a bundle swap can't strand the knob
        self.prefill_mode = prefill_mode
        self.prefill_stats = prefill_stats
        self.long_prefill = bool(long_prefill)
        self._ring_ok = self._probe_ring() if self.long_prefill else False
        if self.long_prefill and not self._ring_ok:
            from lambdipy_tpu.parallel.spdecode import note_standdown

            note_standdown("long_prefill_without_ring_mesh")
            log.warning(
                "long_prefill requested but the bundle is not a ring-"
                "attention sp-mesh configuration; chunked prefill runs "
                "unsharded (counted stand-down)")
        self._run_ids = itertools.count(1)
        self._lock = threading.Lock()  # one run at a time per runner

    def _probe_ring(self) -> bool:
        cfg = self.server.model.cfg
        mesh = getattr(self.server, "mesh", None)
        return (getattr(cfg, "attn_backend", "dense") == "ring"
                and mesh is not None
                and dict(getattr(mesh, "shape", {})).get("sp", 1) > 1)

    def _sp_standdown(self, reason: str) -> int:
        from lambdipy_tpu.parallel.spdecode import note_standdown

        note_standdown(reason)
        if self.prefill_stats is not None:
            self.prefill_stats.record_standdown(reason)
        return 0

    def _sp_factor(self, s: int) -> int:
        """Shard count for THIS run's prefill, or 0 for the serial
        chain. Every refusal is a counted stand-down, never silent:
        prompts of one chunk or less gain nothing from sharding, an odd
        page count makes the half-window non-page-aligned (the slide
        schedule the rounds must reproduce moves ``n_view // 2`` whole
        pages), and a round needs ``(sp + 1) * n_view / 2`` free pages
        at peak (fresh round pages + the carried prior half-window)."""
        from lambdipy_tpu.models.llama import resolve_sp_prefill

        sp = resolve_sp_prefill(self.prefill_mode,
                                getattr(self.server, "mesh", None))
        if sp < 2:
            if sp != 0 or self.prefill_mode != "sp":
                return 0
            if self.prefill_stats is not None:
                self.prefill_stats.record_standdown(
                    "sp_prefill_without_sp_mesh")
            return 0
        if s <= self.window // 2:
            return 0  # one serial chunk already; not a degradation
        if self.n_view % 2:
            return self._sp_standdown("sp_prefill_window_not_divisible")
        need = (sp + 1) * (self.n_view // 2)
        if self.pool.free_count() < need:
            return self._sp_standdown("sp_prefill_pool_pressure")
        return sp

    def _spill_history(self, st: dict, pids: list, lpi0: int) -> None:
        """Spill already-attended prefill pages (logical pages ``lpi0 +
        j``) to the offload arena under the run's ``("lc", ...)`` keys
        and recycle their pool pages — the sp-round twin of the eviction
        half of :meth:`_slide`. Decode never re-reads them; the spill
        keeps the run's offload history identical to the serial
        schedule's (budget refusals land in ``st["lost"]`` the same
        way)."""
        from lambdipy_tpu.models.llama import arena_page_slices

        if not pids:
            return
        pool, page = self.pool, self.pool.page
        with pool.arena_lock:
            arena = pool.ensure_arena()
        for j, pid in enumerate(pids):
            lpi = lpi0 + j
            key = ("lc", st["run_id"], lpi)
            toks = st["tokens"][lpi * page:(lpi + 1) * page]
            block = arena_page_slices(arena, pid, page)
            if self.offload.spill(key, toks, block):
                st["off"][lpi] = key
            else:
                st["lost"].add(lpi)
        pool.release(pids)

    def _sp_prefill(self, st: dict, row, s: int, knobs, sp: int):
        """Whole-prompt sequence-parallel prefill: run the serial
        window/2 slide schedule as ``ceil(s / (sp * window/2))`` ROUNDS
        of ``sp`` chunks each, every round one sharded program
        (``server._lsp_round_fn``). The round's union view is [prior
        half-window][sp fresh chunks]; ``band = window/2`` gives every
        query exactly the keys its serial chunk would have had resident,
        so the tokens match the serial chain's. Between rounds the
        union's head retires through :meth:`_spill_history` and the last
        half-window carries forward as the next prior. Returns the final
        round's carry with the cursor already translated into the decode
        view's frame; ``st`` leaves with the table/base/local the serial
        chain would have produced."""
        import jax.numpy as jnp

        from lambdipy_tpu.runtime.pagepool import NULL_PAGE

        server, pool = self.server, self.pool
        page, window, n_view = self.pool.page, self.window, self.n_view
        w2 = window // 2
        rbs = sp * w2
        rpages = rbs // page
        ppages = w2 // page
        t_op, k_op, p_op, keys0, eos_op = knobs
        rnd = server._lsp_round_fn(sp, pool.n_pages, page, window, sp)
        n_rounds = -(-s // rbs)
        layers = int(getattr(server.model.cfg, "layers", 0))
        t0 = time.monotonic()
        prior: list = []
        prior_len = 0
        carry = None
        fresh: list = []
        live: set = set()  # alloc'd pages not yet retired or handed off
        for r in range(n_rounds):
            c0 = r * rbs
            rlen = min(rbs, s - c0)
            try:
                fresh = pool.alloc(rpages, tokens=rlen,
                                   record_shed=False)
            except BaseException:
                pool.release(sorted(live))
                raise
            live |= set(fresh)
            prior_len = w2 if r else 0
            # round 0 has no prior: the head slots point at the null
            # page, whose gathered bits sit beyond the cache index and
            # scatter back bitwise-unchanged
            tbl_list = (prior + fresh) if r else \
                (fresh + [NULL_PAGE] * ppages)
            suffix_op, _ = server._pad_rows([row[c0:c0 + rlen]], [rlen],
                                            1, rbs)
            tbl = jnp.asarray(tbl_list, jnp.int32)[None, :]
            with pool.arena_lock:
                pool.ensure_arena()
                with server._mesh_ctx():
                    first, lp0, new_arena, start_c, done_c, keys = rnd(
                        server.params, pool.arena, tbl,
                        jnp.int32(prior_len), jnp.int32(c0), suffix_op,
                        jnp.int32(rlen), t_op, k_op, p_op, keys0,
                        eos_op)
                pool.arena = new_arena
            if self.prefill_stats is not None:
                self.prefill_stats.record_round(-(-rlen // w2), sp,
                                                ring_hops=layers * sp)
            # like the serial chain: only the FINAL round's selection is
            # the request's first token (same rng operand every round)
            carry = (first, lp0, start_c, done_c, keys)
            if r < n_rounds - 1:
                gs = c0 - prior_len
                evict = prior + fresh[:-ppages]
                self._spill_history(st, evict, gs // page)
                live -= set(evict)
                prior = fresh[-ppages:]
        # -- hand off to the decode view: the exact (base, local, table)
        # the serial slide schedule ends on --------------------------------
        gs = (n_rounds - 1) * rbs - prior_len
        union = (prior + fresh) if prior_len else \
            (fresh + [NULL_PAGE] * ppages)
        base = max(0, -(-(s - window) // w2)) * w2
        local = s - base
        off0 = (base - gs) // page
        self._spill_history(st, union[:off0], gs // page)
        st["table"] = union[off0:off0 + n_view]
        assert len(st["table"]) == n_view \
            and NULL_PAGE not in st["table"]  # covered: base >= gs and
        # base + window <= gs + union tokens, both multiples of the page
        # a RAGGED last round can leave union pages past the decode view
        # (tokens >= base + window >= s: pure padding) — plain release,
        # nothing in them is history worth spilling
        tail = [p for p in union[off0 + n_view:] if p != NULL_PAGE]
        if tail:
            self.pool.release(tail)
        st["base"], st["local"] = base, local
        self.temp.touch([("lc", st["run_id"], base // page + j)
                         for j in range(local // page)])
        if self.prefill_stats is not None:
            self.prefill_stats.record_walk(time.monotonic() - t0,
                                           -(-s // w2), n_rounds)
        first, lp0, start_c, done_c, keys = carry
        # union-frame cursor (prior_len + rlen) -> decode-view frame
        start_c = start_c - jnp.int32(base - gs)
        return first, lp0, start_c, done_c, keys

    # -- public --------------------------------------------------------------

    def fits(self, s: int, max_new_tokens: int) -> bool:
        return 0 < s + max_new_tokens <= self.max_logical_ctx

    def generate(self, prompt_row, *, max_new_tokens: int,
                 temperature: float = 0.0, top_k=None, top_p=None,
                 seed: int = 0, eos_id=None, return_logprobs: bool = False):
        """``server.generate``'s single-row contract over the logical
        window: ``[1, max_new_tokens]`` tokens (+ logprobs when asked),
        eos-latched with eos filler. Deterministic in the request alone
        — a replay after a failed re-online re-emits the same stream."""
        import numpy as np

        with self._lock:
            replays = 0
            while True:
                try:
                    toks, lps = self._run(
                        prompt_row, max_new_tokens, temperature, top_k,
                        top_p, seed, eos_id,
                        resident_cap=(self.resident_cap if replays == 0
                                      else None))
                    break
                except ReonlineFailed as exc:
                    # the lost page's KV is recomputed by replaying the
                    # whole deterministic schedule with yielding OFF —
                    # under a permanently-armed fault the replay makes
                    # progress because it never fetches
                    self.stats.record_recompute(exc.pages)
                    replays += 1
                    if replays > self.max_replays:
                        raise exc.cause
                    log.warning(
                        "long-context re-online failed (%s); replaying "
                        "run from scratch (%d/%d)", exc, replays,
                        self.max_replays)
        out = np.asarray([toks[:max_new_tokens]], np.int32)
        if return_logprobs:
            return out, np.asarray([lps[:max_new_tokens]], np.float32)
        return out

    # -- internals -----------------------------------------------------------

    def _slide(self, st: dict, k_pages: int) -> int:
        """Advance the view by ``k_pages`` whole pages: spill the evicted
        head pages (full of already-attended tokens) to the offload
        arena, recycle their pool pages into the view's tail, shift the
        frame. Returns the token delta (the caller shifts the device
        carry's local cursor by exactly this, int32-exact). Spill bytes
        come off the PRE-slide arena value — the functional arena chain
        means later writes can never alter it."""
        import jax.numpy as jnp  # noqa: F401 — device libs load lazily

        from lambdipy_tpu.models.llama import arena_page_slices

        pool, page = self.pool, self.pool.page
        evict = st["table"][:k_pages]
        with pool.arena_lock:
            arena = pool.ensure_arena()
        base_page = st["base"] // page
        for j, pid in enumerate(evict):
            lpi = base_page + j
            if pid is None:
                # already spilled by the pressure-yield pass: its bytes
                # are in the offload arena under st["off"][lpi]
                continue
            key = ("lc", st["run_id"], lpi)
            toks = st["tokens"][lpi * page:(lpi + 1) * page]
            block = arena_page_slices(arena, pid, page)
            if self.offload.spill(key, toks, block):
                st["off"][lpi] = key
            else:
                # budget refusal: the page is LOST to history (failover
                # re-ship of this run will recompute it) but decode
                # never needs it again — the view has moved past it
                st["lost"].add(lpi)
        gone = [("lc", st["run_id"], base_page + j) for j in range(k_pages)]
        self.temp.forget(gone)
        st["prefetch"].forget(gone)
        pool.release([pid for pid in evict if pid is not None])
        fresh = pool.alloc(k_pages, tokens=0, record_shed=False)
        st["table"] = st["table"][k_pages:] + list(fresh)
        st["base"] += k_pages * page
        st["local"] -= k_pages * page
        return k_pages * page

    def _reonline(self, st: dict, slots: list, *, timed: bool) -> None:
        """Fetch the offloaded pages for view ``slots`` in ONE batched
        frame decode and write them into freshly allocated arena pages
        through the page-write program (the same validated-insert path
        every kvwire import takes). ``timed`` marks a demand miss — the
        wall clock it burns is the re-online stall the bench bounds."""
        import jax.numpy as jnp

        if not slots:
            return
        pool, server = self.pool, self.server
        base_page = st["base"] // pool.page
        keys = [("lc", st["run_id"], base_page + j) for j in slots]
        t0 = time.monotonic() if timed else 0.0
        try:
            blocks = self.offload.fetch_many(keys)
        except (OffloadMiss, Exception) as exc:  # noqa: B014 — fault kinds vary
            raise ReonlineFailed(exc, len(keys)) from exc
        pids = pool.alloc(len(slots), tokens=0, record_shed=False)
        write = server._page_write_fn(pool.n_pages, pool.page)
        with pool.arena_lock:
            arena = pool.ensure_arena()
            with server._mesh_ctx():
                for pid, block in zip(pids, blocks):
                    arena = write(arena, jnp.int32(pid), block)
            pool.arena = arena
        for j, pid in zip(slots, pids):
            st["table"][j] = pid
            st["off"].pop(base_page + j, None)
        self.offload.drop(keys)
        if timed:
            # a demand-missed page already scored its miss; take it out
            # of the tracker so later segments don't re-score it
            st["prefetch"].forget(keys)
            self.stats.record_stall(time.monotonic() - t0)
        else:
            st["prefetch"].complete(keys)
        self.temp.touch(keys)

    def _yield_cold(self, st: dict, arena_before) -> None:
        """Pressure-yield (``resident_cap``): spill the view's coldest
        FULL pages past the cap back to host RAM and release their pool
        pages — capacity other sessions can use between this row's
        segments. Runs right after an async dispatch, reading the
        pre-dispatch arena value (bitwise the values the in-flight
        segment attends: decode only writes the cursor page, which is
        never a victim)."""
        from lambdipy_tpu.models.llama import arena_page_slices

        pool, page = self.pool, self.pool.page
        cap = self.resident_cap
        base_page = st["base"] // page
        # victims: whole pages strictly below the cursor page (full,
        # read-only for the in-flight segment), never the write region
        full = [j for j in range(self.n_view)
                if (j + 1) * page <= st["local"]
                and st["table"][j] is not None]
        excess = len([j for j in range(self.n_view)
                      if st["table"][j] is not None]) - cap
        if excess <= 0 or not full:
            return
        victims = self.temp.coldest(
            [("lc", st["run_id"], base_page + j) for j in full],
            min(excess, len(full)))
        for *_, lpi in victims:
            j = lpi - base_page
            pid = st["table"][j]
            key = ("lc", st["run_id"], lpi)
            toks = st["tokens"][lpi * page:(lpi + 1) * page]
            block = arena_page_slices(arena_before, pid, page)
            if not self.offload.spill(key, toks, block):
                continue  # refusal: keep it resident, nothing lost
            st["off"][lpi] = key
            st["prefetch"].spill([key])
            pool.release([pid])
            st["table"][j] = None

    def _view_table(self, st: dict):
        """The dispatch operand: every slot must be resident (a None
        slot here is a programming error — demand re-onlines first)."""
        import jax.numpy as jnp

        assert all(pid is not None for pid in st["table"])
        return jnp.asarray(st["table"], jnp.int32)[None, :]

    def _offloaded_slots(self, st: dict) -> list:
        return [j for j in range(self.n_view) if st["table"][j] is None]

    def _run(self, prompt_row, max_new_tokens, temperature, top_k, top_p,
             seed, eos_id, *, resident_cap):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from lambdipy_tpu.models.llama import _next_bucket

        server, pool = self.server, self.pool
        page, window, n_view = pool.page, self.window, self.n_view
        rows, lengths = server._normalize_prompts(prompt_row)
        if len(rows) != 1:
            raise ValueError("the long-context tier is single-row")
        row, s = rows[0], lengths[0]
        total = s + max_new_tokens
        if not self.fits(s, max_new_tokens):
            raise ValueError(
                f"{total} tokens exceed max_logical_ctx="
                f"{self.max_logical_ctx}")
        yield_cap = resident_cap if resident_cap \
            and resident_cap < n_view else None
        sp = self._sp_factor(s)
        st = {"run_id": next(self._run_ids), "base": 0, "local": 0,
              "tokens": list(row), "off": {}, "lost": set(),
              "table": [] if sp else list(pool.alloc(n_view, tokens=0,
                                                     record_shed=False)),
              "prefetch": Prefetcher(self.stats)}
        knobs = server._knob_operands(temperature, top_k, top_p, seed,
                                      eos_id, b=1)
        t_op, k_op, p_op, keys0, eos_op = knobs
        out_toks: list = []
        out_lps: list = []
        try:
            if sp:
                # -- whole-prompt sp prefill: sharded rounds ------------------
                first, lp0, start_c, done_c, keys = \
                    self._sp_prefill(st, row, s, knobs, sp)
            else:
                # -- chunked prefill through the sliding view -----------------
                t_pf = time.monotonic()
                chunk = window // 2
                carry = None
                for c0 in range(0, s, chunk):
                    clen = min(chunk, s - c0)
                    while st["local"] + clen > window:
                        self._slide(st, n_view // 2)
                    sbs = min(_next_bucket(clen, server.min_bucket),
                              window - st["local"])
                    cont = server._lpaged_continue_fn(sbs, pool.n_pages,
                                                      page, window)
                    suffix_op, _ = server._pad_rows([row[c0:c0 + clen]],
                                                    [clen], 1, sbs)
                    tbl = self._view_table(st)
                    with pool.arena_lock:
                        pool.ensure_arena()
                        with server._mesh_ctx():
                            first, lp0, new_arena, start_c, done_c, keys = \
                                cont(server.params, pool.arena, tbl,
                                     jnp.int32(st["local"]),
                                     jnp.int32(st["base"]), suffix_op,
                                     jnp.int32(clen), t_op, k_op, p_op,
                                     keys0, eos_op)
                        pool.arena = new_arena
                    st["local"] += clen
                    self.temp.touch(
                        [("lc", st["run_id"], st["base"] // page + j)
                         for j in range(st["local"] // page)])
                    if self.prefill_stats is not None:
                        self.prefill_stats.record_round(1, 1)
                    # only the FINAL chunk's selection is the request's
                    # first token; mid-chunk selections are discarded (the
                    # rng operand is the same each chunk, so the final
                    # split matches a single whole-prompt prefill's)
                    carry = (first, lp0, start_c, done_c, keys)
                first, lp0, start_c, done_c, keys = carry
                if self.prefill_stats is not None:
                    n_chunks = -(-s // chunk)
                    self.prefill_stats.record_walk(
                        time.monotonic() - t_pf, n_chunks, n_chunks)
            # -- segment decode over the sliding view -------------------------
            seg_len = self.segment
            seg_fn = server._lpaged_seg_fn(1, pool.n_pages, page, window,
                                           seg_len)
            eos_seen = False
            while len(out_toks) < max_new_tokens and not eos_seen:
                while st["local"] + seg_len > window:
                    delta = self._slide(st, n_view // 2)
                    start_c = start_c - jnp.int32(delta)
                # demand: every view slot must be resident at dispatch.
                # The check covers ALL view pages so a page the prefetch
                # already brought home is COUNTED as a hit (only pages
                # with spill history score; always-resident ones don't);
                # stragglers re-online now — a timed stall
                base_page = st["base"] // page
                miss = st["prefetch"].demand(
                    [("lc", st["run_id"], base_page + j) for j in range(n_view)])
                self._reonline(st, sorted(k[2] - base_page for k in miss),
                               timed=True)
                tbl = self._view_table(st)
                base_op = jnp.broadcast_to(jnp.int32(st["base"]), (1,))
                with pool.arena_lock:
                    arena_before = pool.ensure_arena()
                    with server._mesh_ctx():
                        (toks, lps), (first, lp0, new_arena, start_c,
                                      done_c, keys) = seg_fn(
                            server.params, t_op, k_op, p_op, first, lp0,
                            pool.arena, tbl, start_c, base_op, done_c,
                            keys, eos_op)
                    pool.arena = new_arena
                # dispatch is async: the yield + prefetch below run on
                # the host while the device chews the segment, so the
                # re-online frame decode hides under the previous step
                if yield_cap is not None:
                    self._yield_cold(st, arena_before)
                    planned = st["prefetch"].plan(
                        [("lc", st["run_id"], st["base"] // page + j)
                         for j in self._offloaded_slots(st)])
                    if planned:
                        base_page = st["base"] // page
                        self._reonline(
                            st, [k[2] - base_page for k in planned],
                            timed=False)
                chunk_t = np.asarray(jax.device_get(toks))[0]
                chunk_l = np.asarray(jax.device_get(lps))[0]
                take = min(seg_len, max_new_tokens - len(out_toks))
                for i in range(take):
                    tok = int(chunk_t[i])
                    out_toks.append(tok)
                    out_lps.append(float(chunk_l[i]))
                    st["tokens"].append(tok)
                    if eos_id is not None and tok == int(eos_id):
                        eos_seen = True
                        break
                st["local"] += seg_len
                self.temp.touch([("lc", st["run_id"], st["base"] // page + j)
                                 for j in range(min(st["local"], window)
                                                // page)])
            if eos_id is not None and eos_seen:
                pad = max_new_tokens - len(out_toks)
                out_toks += [int(eos_id)] * pad
                out_lps += [0.0] * pad
            else:
                out_toks = out_toks[:max_new_tokens]
                out_lps = out_lps[:max_new_tokens]
            return out_toks, out_lps
        finally:
            pool.release([pid for pid in st["table"] if pid is not None])
            self.offload.drop(list(st["off"].values()))
            self.temp.forget(list(st["off"].values()))

    def report(self) -> dict:
        return {"window": self.window, "segment": self.segment,
                "max_logical_ctx": self.max_logical_ctx,
                "boot_logical_ctx": self.boot_logical_ctx,
                "resident_cap": self.resident_cap,
                "long_prefill": self.long_prefill,
                "ring_active": self._ring_ok,
                "prefill_mode": self.prefill_mode,
                **self.offload.gauges(), **self.stats.report()}
