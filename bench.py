"""Driver benchmark: flagship serving latency on the real chip.

Measures ResNet-50 bf16 batch-1 forward p50 (the BASELINE.json north-star
metric: <15 ms p50 on v5e-1) and prints ONE JSON line; ``vs_baseline`` is
the speedup vs the 15 ms target (>1 = beating it).

Robustness: the measurement runs in a subprocess because this image's TPU
tunnel can wedge ``jax.devices()`` indefinitely (observed; see
tests/conftest.py for the related sitecustomize hang). On timeout the
orchestrator retries on CPU so the driver always gets a valid JSON line,
with ``platform`` recording what was actually measured.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_P50_MS = 15.0  # BASELINE.json north star for ResNet-50 on v5e-1
DEVICE_TIMEOUT_S = float(os.environ.get("LAMBDIPY_BENCH_TIMEOUT", "1500"))


def _inner() -> int:
    import statistics

    t0 = time.monotonic()
    platform_override = os.environ.get("LAMBDIPY_PLATFORM")
    import jax

    if platform_override:
        jax.config.update("jax_platforms", platform_override)
    import jax.numpy as jnp

    from lambdipy_tpu.models import registry

    devices = jax.devices()
    platform = devices[0].platform
    init_s = time.monotonic() - t0

    adapter = registry.get("resnet50").build(dtype="bfloat16")
    params = adapter.init_params(seed=0, batch_size=1)
    x = jnp.zeros((1, 224, 224, 3), jnp.bfloat16)
    fwd = jax.jit(adapter.forward)

    t1 = time.monotonic()
    jax.block_until_ready(fwd(params, x))
    compile_s = time.monotonic() - t1

    for _ in range(5):
        jax.block_until_ready(fwd(params, x))
    times = []
    iters = 50 if platform != "cpu" else 10
    for _ in range(iters):
        t = time.monotonic()
        jax.block_until_ready(fwd(params, x))
        times.append((time.monotonic() - t) * 1000.0)
    p50 = statistics.median(times)

    print(json.dumps({
        "metric": "resnet50_b1_fwd_p50",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(BASELINE_P50_MS / p50, 3),
        "platform": platform,
        "n_devices": len(devices),
        "init_s": round(init_s, 2),
        "first_compile_s": round(compile_s, 2),
    }))
    return 0


def main() -> int:
    if "--inner" in sys.argv:
        return _inner()
    here = os.path.dirname(os.path.abspath(__file__))
    base_env = dict(os.environ)
    base_env["PYTHONPATH"] = os.pathsep.join(
        [here] + [p for p in base_env.get("PYTHONPATH", "").split(os.pathsep) if p])
    attempts = [({}, DEVICE_TIMEOUT_S)]
    if not os.environ.get("LAMBDIPY_PLATFORM"):
        attempts.append(({"LAMBDIPY_PLATFORM": "cpu"}, 600.0))
    last_err = ""
    for extra_env, timeout in attempts:
        env = dict(base_env)
        env.update(extra_env)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(here, "bench.py"), "--inner"],
                capture_output=True, text=True, env=env, timeout=timeout)
        except subprocess.TimeoutExpired:
            last_err = f"timeout after {timeout}s (device unreachable?)"
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            print(proc.stdout.strip().splitlines()[-1])
            return 0
        last_err = proc.stderr.strip()[-500:]
    print(json.dumps({
        "metric": "resnet50_b1_fwd_p50",
        "value": -1.0,
        "unit": "ms",
        "vs_baseline": 0.0,
        "error": last_err,
    }))
    return 1


if __name__ == "__main__":
    sys.exit(main())
