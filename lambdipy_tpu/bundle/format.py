"""Bundle manifest: schema, writer, loader, verifier.

The manifest is the bundle's single source of truth — provenance (the
pattern of the TPU image exemplar's post-build manifest, SURVEY.md §3.4),
base-layer contract, payload description, and a per-file content-hash list
used for integrity checks and registry dedup.
"""

from __future__ import annotations

import json
from pathlib import Path

from lambdipy_tpu.utils.fsutil import atomic_write_text, hash_file, walk_files

BUNDLE_SCHEMA_VERSION = 1
MANIFEST_NAME = "manifest.json"


class BundleError(RuntimeError):
    pass


def file_table(bundle_dir: Path) -> list[dict]:
    bundle_dir = Path(bundle_dir)
    table = []
    for path in walk_files(bundle_dir):
        rel = path.relative_to(bundle_dir).as_posix()
        if rel == MANIFEST_NAME or not path.is_file():
            continue  # is_file() is False for dangling symlinks
        table.append({
            "path": rel,
            "size": path.stat().st_size,
            "hash": hash_file(path),
        })
    return table


def write_manifest(bundle_dir: Path, *, artifact_id: str, provenance: dict,
                   base_layer: dict, payload: dict | None,
                   runtime: dict | None = None) -> dict:
    bundle_dir = Path(bundle_dir)
    manifest = {
        "schema": BUNDLE_SCHEMA_VERSION,
        "artifact_id": artifact_id,
        "provenance": provenance,
        "base_layer": base_layer,
        "payload": payload,
        "runtime": runtime or {},
        "files": file_table(bundle_dir),
    }
    atomic_write_text(bundle_dir / MANIFEST_NAME,
                      json.dumps(manifest, indent=1, sort_keys=True))
    return manifest


def update_manifest(bundle_dir: Path, **fields) -> dict:
    """Merge top-level fields into an existing manifest (e.g. the build-time
    ``warm`` record, written after assembly). The file table is not
    re-computed — it never includes the manifest itself."""
    manifest = load_manifest(bundle_dir)
    manifest.update(fields)
    atomic_write_text(Path(bundle_dir) / MANIFEST_NAME,
                      json.dumps(manifest, indent=1, sort_keys=True))
    return manifest


def load_manifest(bundle_dir: Path) -> dict:
    path = Path(bundle_dir) / MANIFEST_NAME
    if not path.exists():
        raise BundleError(f"{bundle_dir} is not a bundle (no {MANIFEST_NAME})")
    manifest = json.loads(path.read_text())
    if manifest.get("schema") != BUNDLE_SCHEMA_VERSION:
        raise BundleError(
            f"unsupported bundle schema {manifest.get('schema')!r} in {bundle_dir}")
    return manifest


def verify_files(bundle_dir: Path, manifest: dict | None = None) -> list[str]:
    """Integrity check: returns a list of problems (empty = ok)."""
    bundle_dir = Path(bundle_dir)
    manifest = manifest or load_manifest(bundle_dir)
    problems = []
    for entry in manifest["files"]:
        path = bundle_dir / entry["path"]
        if not path.is_file():
            problems.append(f"missing: {entry['path']}")
            continue
        if path.stat().st_size != entry["size"]:
            problems.append(f"size mismatch: {entry['path']}")
        else:
            algo = entry["hash"].split(":", 1)[0]
            try:
                recomputed = hash_file(path, algo=algo)
            except RuntimeError as e:  # algo unavailable (native ext not built)
                problems.append(f"unverifiable ({e}): {entry['path']}")
                continue
            if recomputed != entry["hash"]:
                problems.append(f"hash mismatch: {entry['path']}")
    return problems
