"""End-to-end staged configs (BASELINE.json configs 1-2) through the real
CLI + deploy surface — the 'minimum end-to-end slice' of SURVEY.md §9.5,
exercised exactly as a user would: build -> registry -> deploy -> invoke."""

import json
from pathlib import Path

import pytest
from click.testing import CliRunner

from lambdipy_tpu.cli import main
from lambdipy_tpu.runtime.deploy import LocalRuntime

pytestmark = pytest.mark.slow

CPU_ENV = {
    "LAMBDIPY_PLATFORM": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


def _build_and_deploy(recipe, tmp_path, request_payload, deploy_name):
    runner = CliRunner()
    reg = str(tmp_path / "registry")
    r = runner.invoke(main, ["build", recipe, "--registry", reg])
    assert r.exit_code == 0, r.output
    rt = LocalRuntime(tmp_path / "deployments.json")
    from lambdipy_tpu.cli import _resolve_bundle

    bundle = _resolve_bundle(recipe, reg)
    dep = rt.deploy(deploy_name, bundle, env=CPU_ENV)
    try:
        health = rt.health(deploy_name)
        assert health["ok"]
        out = rt.invoke(deploy_name, request_payload)
        assert out["ok"], out
        return health, out
    finally:
        rt.stop(deploy_name)


def test_config1_hello_numpy_bundle(tmp_path):
    """Config 1: numpy+scipy hello-world handler (CPU baseline)."""
    health, out = _build_and_deploy(
        "hello-numpy", tmp_path, {"n": 32, "seed": 3}, "hello1")
    assert isinstance(out["logdet"], float)
    assert out["numpy"].startswith("2.")
    # cold-start stages were reported through the readiness line
    assert "init" in health["cold_start"]


def test_config2_tabular_bundle_degrades_without_xgboost(tmp_path):
    """Config 2: sklearn tabular inference; xgboost (absent offline) is
    recorded as the degraded optional, not an error."""
    _, out = _build_and_deploy(
        "tabular-sklearn", tmp_path,
        {"instances": [[0.0] * 16]}, "tab1")
    assert out["predictions"] and out["probabilities"]
    assert out["degraded"] == ["xgboost"]
