"""HF weight import: logits + greedy-decode parity against transformers'
own forward pass, path/state-dict sources, and the bundle params hook."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from lambdipy_tpu.models.convert import import_hf_llama, save_hf_params
from lambdipy_tpu.models.llama import LlamaModel, greedy_generate


@pytest.fixture(scope="module")
def hf_llama():
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-5,
        attention_bias=False, tie_word_embeddings=False)
    torch.manual_seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model


def _tokens(shape, seed=0):
    return np.random.default_rng(seed).integers(1, 128, shape)


def test_hf_llama_logits_parity(hf_llama):
    """Converted weights reproduce transformers' logits (fp32)."""
    cfg, params = import_hf_llama(hf_llama,
                                  config_overrides={"dtype": jnp.float32})
    assert cfg.layers == 2 and cfg.heads == 4 and cfg.kv_heads == 2

    toks = _tokens((2, 10))
    with torch.no_grad():
        ref = hf_llama(torch.tensor(toks)).logits.numpy()
    ours, _ = LlamaModel(cfg).apply(params, jnp.asarray(toks, jnp.int32))
    np.testing.assert_allclose(ref, np.asarray(ours), rtol=1e-3, atol=2e-3)


def test_hf_llama_greedy_decode_parity(hf_llama):
    """Greedy generations agree token-for-token with transformers."""
    cfg, params = import_hf_llama(hf_llama,
                                  config_overrides={"dtype": jnp.float32})
    toks = _tokens((1, 6), seed=3)
    with torch.no_grad():
        ref = hf_llama.generate(
            torch.tensor(toks), max_new_tokens=6, do_sample=False,
            pad_token_id=0).numpy()[:, toks.shape[1]:]
    ours = greedy_generate(LlamaModel(cfg), params,
                           jnp.asarray(toks, jnp.int32), max_new_tokens=6)
    np.testing.assert_array_equal(ref, np.asarray(ours))


def test_hf_llama_from_local_path(hf_llama, tmp_path):
    hf_llama.save_pretrained(tmp_path / "ckpt")
    cfg, params = import_hf_llama(tmp_path / "ckpt",
                                  config_overrides={"dtype": jnp.float32})
    toks = _tokens((1, 8), seed=1)
    with torch.no_grad():
        ref = hf_llama(torch.tensor(toks)).logits.numpy()
    ours, _ = LlamaModel(cfg).apply(params, jnp.asarray(toks, jnp.int32))
    np.testing.assert_allclose(ref, np.asarray(ours), rtol=1e-3, atol=2e-3)


def test_hf_llama_tied_embeddings():
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=True)
    torch.manual_seed(1)
    model = LlamaForCausalLM(cfg)
    model.eval()
    our_cfg, params = import_hf_llama(model,
                                      config_overrides={"dtype": jnp.float32})
    emb = params["params"]["embed"]["embedding"]
    np.testing.assert_array_equal(np.asarray(params["params"]["lm_head"]["kernel"]),
                                  np.asarray(emb).T)
    toks = _tokens((1, 5), seed=2) % 96
    with torch.no_grad():
        ref = model(torch.tensor(toks)).logits.numpy()
    ours, _ = LlamaModel(our_cfg).apply(params, jnp.asarray(toks, jnp.int32))
    np.testing.assert_allclose(ref, np.asarray(ours), rtol=1e-3, atol=2e-3)


def test_save_hf_params_and_registry_roundtrip(hf_llama, tmp_path):
    """save_hf_params -> orbax -> llama-hf registry adapter serves it."""
    from lambdipy_tpu.models import registry

    hf_llama.save_pretrained(tmp_path / "ckpt")
    info = save_hf_params(tmp_path / "ckpt", tmp_path / "params")
    assert info["source"] == "hf" and info["n_params"] > 0
    # the recorded architecture must be complete: defaulted norm_eps or
    # max_len would silently change serve-side numerics/limits
    assert info["config"]["norm_eps"] == pytest.approx(1e-5)
    assert info["config"]["max_len"] == 64

    adapter = registry.get("llama-hf").build(dtype="float32",
                                             extra=info["config"])
    params = registry.load_params("llama-hf", tmp_path / "params")
    toks = _tokens((1, 7), seed=4)
    logits = adapter.forward(params, jnp.asarray(toks, jnp.int32))
    with torch.no_grad():
        ref = hf_llama(torch.tensor(toks)).logits.numpy()
    np.testing.assert_allclose(ref, np.asarray(logits), rtol=1e-3, atol=2e-3)


def _tiny_tokenizer(save_dir):
    """A real (WordLevel) HF tokenizer built offline. The vocab covers the
    model's whole 128-id range: random-weight generation produces ids
    anywhere in the model vocab, and the streaming-text assertions need
    them to decode to something."""
    from tokenizers import Tokenizer, models, pre_tokenizers
    from transformers import PreTrainedTokenizerFast

    words = ["hello", "world", "the", "cat", "sat", "on", "mat", "a"]
    vocab = {"[UNK]": 0, "[EOS]": 1}
    vocab.update({w: i + 2 for i, w in enumerate(words)})
    vocab.update({f"w{i}": i for i in range(len(vocab), 128)})
    tok = Tokenizer(models.WordLevel(vocab=vocab, unk_token="[UNK]"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    fast = PreTrainedTokenizerFast(tokenizer_object=tok, unk_token="[UNK]",
                                   eos_token="[EOS]")
    fast.save_pretrained(save_dir)
    return fast


@pytest.mark.slow
def test_hf_bundle_text_serving(hf_llama, tmp_path):
    """Full migration path: local HF checkpoint + tokenizer -> recipe with
    params='hf' -> bundle -> deploy -> text-in/text-out invoke."""
    from click.testing import CliRunner

    from lambdipy_tpu.cli import main, _resolve_bundle
    from lambdipy_tpu.runtime.deploy import LocalRuntime

    import shutil

    hf_llama.save_pretrained(tmp_path / "ckpt")
    _tiny_tokenizer(tmp_path / "tok")

    rdir = tmp_path / "recipes"
    rdir.mkdir()
    (rdir / "hf-llama.toml").write_text(f'''
schema = 1
name = "hf-llama"
version = "0.1"
device = "any"
base_layer = "jax-tpu"
requires = []

[payload]
model = "llama-hf"
handler = "lambdipy_tpu.runtime.handlers:generate_handler"
params = "hf"
dtype = "float32"
batch_size = 1

[payload.extra]
hf_path = "{tmp_path / 'ckpt'}"
tokenizer_path = "{tmp_path / 'tok'}"
max_new_tokens = 4
''')
    reg = str(tmp_path / "registry")
    r = CliRunner().invoke(main, ["build", "hf-llama", "--registry", reg,
                                  "--recipe-dir", str(rdir)])
    assert r.exit_code == 0, r.output
    # portability: the bundle must carry the tokenizer itself — deploying
    # on a machine without the build-host path has to keep working
    shutil.rmtree(tmp_path / "tok")

    rt = LocalRuntime(tmp_path / "deployments.json")
    env = {"LAMBDIPY_PLATFORM": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    rt.deploy("hf1", _resolve_bundle("hf-llama", reg), env=env)
    try:
        health = rt.health("hf1")
        assert health["handler_meta"]["tokenizer"] is True, health
        out = rt.invoke("hf1", {"text": "the cat sat", "max_new_tokens": 4})
        assert out["ok"], out
        assert isinstance(out["completion"], str)
        # token API still works on the same deployment
        out2 = rt.invoke("hf1", {"tokens": [2, 3, 4], "max_new_tokens": 3})
        assert out2["ok"] and len(out2["tokens"][0]) == 3
        # degenerate prompts get clean API errors, not XLA tracebacks
        bad = rt.invoke("hf1", {"text": ""})
        assert bad["ok"] is False and "zero tokens" in bad["error"]
        bad2 = rt.invoke("hf1", {"tokens": []})
        assert bad2["ok"] is False
        # SSE /v1/completions with a STRING prompt streams INCREMENTAL
        # text: chunks carry deltas whose concatenation (with the final
        # tail event) equals the non-streamed completion exactly once
        # (ADVICE r3: clients rendering choices[0].text incrementally saw
        # nothing until the stream ended)
        import json as _json
        import urllib.request

        # eos_id -1 disables eos latching: the random-weight model may
        # emit [EOS] immediately, which would make the completion empty
        # and the incremental-text assertion vacuous
        ref = rt.invoke("hf1", {"text": "the cat sat", "max_new_tokens": 4,
                                "eos_id": -1})
        req = urllib.request.Request(
            f"{rt.get('hf1').url}/v1/completions",
            data=_json.dumps({"prompt": "the cat sat", "max_tokens": 4,
                              "temperature": 0, "stream": True,
                              "segment": 4, "eos_id": -1}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=120) as resp:
            events = [ln.decode().strip()[len("data: "):] for ln in resp
                      if ln.strip().startswith(b"data: ")]
        assert events[-1] == "[DONE]"
        parsed = [_json.loads(e) for e in events[:-1]]
        streamed = "".join(p["choices"][0]["text"] for p in parsed)
        assert streamed == ref["completion"]
        assert any(p["choices"][0]["text"] and p["choices"][0]["tokens"]
                   for p in parsed), "no non-final chunk carried text"
    finally:
        rt.stop("hf1")


def test_hf_import_preserves_bf16():
    """A bf16 checkpoint stays bf16 through conversion (no fp32 doubling)."""
    import ml_dtypes
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32)
    torch.manual_seed(2)
    model = LlamaForCausalLM(cfg).to(torch.bfloat16)
    _, params = import_hf_llama(model)
    kernel = params["params"]["layer_0"]["q_proj"]["kernel"]
    assert kernel.dtype == ml_dtypes.bfloat16, kernel.dtype


def test_hf_llama31_rope_scaling_parity():
    """A Llama-3.1-style checkpoint (llama3 rope_scaling) reproduces
    transformers' logits — previously the scaling was silently dropped,
    producing wrong logits with no error (VERDICT r2 missing #6)."""
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0,
        rope_scaling={"rope_type": "llama3", "factor": 2.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32})
    torch.manual_seed(4)
    model = LlamaForCausalLM(cfg)
    model.eval()
    our_cfg, params = import_hf_llama(model,
                                      config_overrides={"dtype": jnp.float32})
    assert our_cfg.rope_scaling == ("llama3", 2.0, 1.0, 4.0, 32.0)
    toks = _tokens((2, 40), seed=5)  # deep enough to exercise scaled freqs
    with torch.no_grad():
        ref = model(torch.tensor(toks)).logits.numpy()
    ours, _ = LlamaModel(our_cfg).apply(params, jnp.asarray(toks, jnp.int32))
    np.testing.assert_allclose(ref, np.asarray(ours), rtol=1e-3, atol=2e-3)


def test_hf_llama_linear_rope_scaling_parity():
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=64,
        rope_scaling={"rope_type": "linear", "factor": 4.0})
    torch.manual_seed(5)
    model = LlamaForCausalLM(cfg)
    model.eval()
    our_cfg, params = import_hf_llama(model,
                                      config_overrides={"dtype": jnp.float32})
    assert our_cfg.rope_scaling == ("linear", 4.0)
    toks = _tokens((1, 24), seed=6) % 96
    with torch.no_grad():
        ref = model(torch.tensor(toks)).logits.numpy()
    ours, _ = LlamaModel(our_cfg).apply(params, jnp.asarray(toks, jnp.int32))
    np.testing.assert_allclose(ref, np.asarray(ours), rtol=1e-3, atol=2e-3)


def test_hf_unsupported_fields_raise():
    """Unsupported architecture fields fail loudly, never silently."""
    from lambdipy_tpu.models.convert import llama_config_from_hf

    base = {"vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 1, "num_attention_heads": 2}
    with pytest.raises(ValueError, match="attention_bias"):
        llama_config_from_hf({**base, "attention_bias": True})
    with pytest.raises(ValueError, match="mlp_bias"):
        llama_config_from_hf({**base, "mlp_bias": True})
    with pytest.raises(ValueError, match="head_dim"):
        llama_config_from_hf({**base, "head_dim": 8})
    with pytest.raises(ValueError, match="rope_scaling"):
        llama_config_from_hf({**base, "rope_scaling": {
            "rope_type": "yarn", "factor": 2.0}})
    # explicit head_dim that MATCHES the derived value is fine
    assert llama_config_from_hf({**base, "head_dim": 16}).head_dim == 16


def test_hf_rope_scaling_roundtrips_through_bundle(tmp_path):
    """save_hf_params records rope_scaling; the llama-hf adapter restores
    it as the hashable tuple the module needs."""
    import json

    from transformers import LlamaConfig, LlamaForCausalLM

    from lambdipy_tpu.models import registry

    cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=64,
        rope_scaling={"rope_type": "llama3", "factor": 2.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32})
    torch.manual_seed(6)
    model = LlamaForCausalLM(cfg)
    model.save_pretrained(tmp_path / "ckpt")
    info = save_hf_params(tmp_path / "ckpt", tmp_path / "params")
    # survives a JSON round-trip (the manifest is JSON on disk)
    info_config = json.loads(json.dumps(info["config"]))
    adapter = registry.get("llama-hf").build(dtype="float32",
                                             extra=info_config)
    assert adapter.config.rope_scaling == ("llama3", 2.0, 1.0, 4.0, 32.0)
