"""Engine fault isolation (runtime/faults.py + the recovery machinery in
runtime/continuous.py): deterministic injection grammar, replay-on-restart
bitwise parity, the watchdog wedging a hung engine and aborting its
waiters, drain-barrier cancellation (closed streams / expired deadlines),
the degradation ladder, wedged-aware fleet health (stub replicas — no
device), and — marked ``slow`` — the real-bundle-server e2e: /healthz
flipping wedged and admission 503ing the accept hole. The full site x
{exception, delay, hang} chaos matrix lives in ``bench.py --chaos``
(run_tier1 phase 7); these tests pin the individual contracts."""

import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from lambdipy_tpu.runtime.continuous import ContinuousBatcher, RequestCancelled
from lambdipy_tpu.runtime.faults import (
    HANG_CAP_S,
    EngineWatchdogTimeout,
    FaultPlan,
    InjectedFault,
)

# tiny_server: the session-scoped shared LlamaServer from conftest.py
# (one compiled-program cache across the continuous-engine modules)


# -- spec grammar (pure) -----------------------------------------------------


def test_fault_plan_parsing():
    p = FaultPlan.from_spec("segment_fetch:hang@seg=3")
    assert p.describe() == ["segment_fetch:hang@seg=3,n=inf"]
    p = FaultPlan.from_spec(
        "transport:delay@ms=200,n=2; group_prefill:exception")
    assert p.describe() == ["transport:delay@seg=1,n=2,ms=200",
                            "group_prefill:exception@seg=1,n=1"]
    # aliases normalize; empty/None specs are inert no-op plans
    assert FaultPlan.from_spec("segment_fetch:raise").rules[0].kind \
        == "exception"
    assert not FaultPlan.from_spec(None).active()
    assert not FaultPlan.from_spec("  ").active()
    # a typo must fail the run loudly, not silently test nothing
    for bad in ("nosuchsite:hang", "segment_fetch:explode",
                "segment_fetch", "segment_fetch:hang@seg=x",
                "segment_fetch:hang@bogus=1"):
        with pytest.raises(ValueError):
            FaultPlan.from_spec(bad)


def test_fault_plan_deterministic_firing_window():
    """Rules key on per-site call counts: seg=N is where firing starts,
    n=K how many calls fire — bitwise-identical run after run."""
    plan = FaultPlan.from_spec("segment_fetch:exception@seg=2,n=2")
    plan.check("segment_fetch")            # call 1: before the window
    for _ in range(2):                     # calls 2-3: inside it
        with pytest.raises(InjectedFault):
            plan.check("segment_fetch")
    plan.check("segment_fetch")            # call 4: window exhausted
    plan.check("transport")                # other sites never match
    assert plan.counts() == {"segment_fetch": 4, "transport": 1}


def test_fault_plan_hang_releases_and_raises():
    """A released (or watchdog-aborted) hang still raises: a wait the
    system gave up on must not look like a success to its caller."""
    plan = FaultPlan.from_spec("transport:hang")
    out = {}

    def hangs():
        try:
            plan.check("transport")
            out["r"] = "returned"
        except InjectedFault as e:
            out["r"] = e.fault_kind

    t = threading.Thread(target=hangs, daemon=True)
    t.start()
    t.join(timeout=0.2)
    assert t.is_alive()          # genuinely blocked, far under the cap
    assert HANG_CAP_S >= 60      # the leak net is generous, not a timer
    plan.release()
    t.join(timeout=5.0)
    assert not t.is_alive() and out["r"] == "hang"
    # the interrupt event (the watchdog's abort path) unblocks the same
    # way, and still raises
    plan2 = FaultPlan.from_spec("transport:hang")
    aborted = threading.Event()
    aborted.set()
    with pytest.raises(InjectedFault):
        plan2.check("transport", interrupt=aborted)


def test_fault_plan_accepts_page_alloc_site():
    p = FaultPlan.from_spec("page_alloc:exception@seg=2,n=3")
    assert p.describe() == ["page_alloc:exception@seg=2,n=3"]
    p.check("page_alloc")                       # seg 1: clean
    with pytest.raises(InjectedFault) as exc:
        p.check("page_alloc")
    assert exc.value.fault_site == "page_alloc"


def test_injected_page_alloc_failure_sheds_one_row_only(tiny_server):
    """A page_alloc fault mid-admission sheds THAT row as priced
    backpressure (PagesExhausted, retry_after_s attached) while rows
    already in flight finish bitwise and later admissions serve — no
    engine wedge, no lost rows, failure attributed under ``page_alloc``
    in the fault stats."""
    from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
    from lambdipy_tpu.runtime.pagepool import (PagePool, PagesExhausted,
                                               page_width)

    cfg = tiny_server.model.cfg
    page = page_width(cfg.max_len, 16)
    n_pages = 4 * (cfg.max_len // page) + 1
    pool = PagePool(n_pages=n_pages, page=page,
                    page_bytes=page_kv_bytes(cfg, page),
                    make_arena=lambda: init_page_arena(cfg, n_pages,
                                                       page))
    # the 2nd allocator call fails: the in-flight first row must not
    # notice; the engine's armed plan drives the pool site (ctor wiring)
    eng = ContinuousBatcher(
        tiny_server, slots=4, segment=8, page_pool=pool,
        faults=FaultPlan.from_spec("page_alloc:exception@seg=2"))
    assert pool.faults is eng.faults
    rows = [[1, 2, 3], [4, 5, 6], [7, 8, 9]]
    solo = [tiny_server.generate(r, max_new_tokens=16) for r in rows]

    out0 = {}
    started = threading.Event()

    def first():
        started.set()
        out0["v"] = eng.generate(rows[0], max_new_tokens=16)

    t = threading.Thread(target=first)
    t.start()
    started.wait()
    time.sleep(0.05)        # let row 0 enter the engine
    with pytest.raises(PagesExhausted) as exc:
        eng.generate(rows[1], max_new_tokens=16)
    assert exc.value.retry_after_s > 0
    t.join()
    np.testing.assert_array_equal(out0["v"], solo[0])   # no lost row
    # the engine never wedged and keeps serving
    assert not eng.wedged
    np.testing.assert_array_equal(
        eng.generate(rows[2], max_new_tokens=16), solo[2])
    rep = eng.fault_stats.report()
    assert rep["failures"].get("page_alloc") == 1, rep
    with eng._lock:
        while eng._engine_running:
            eng._lock.wait(0.05)
    pool.check_invariants()
    st = pool.stats()
    assert st["pages_free"] == st["pages_total"], st


# -- replay-on-restart (the acceptance-criteria parity claim) ----------------


def test_injected_fetch_fault_replays_bitwise(tiny_server):
    """A request whose first attempt dies at an injected segment_fetch
    exception is transparently requeued and replayed — the caller sees
    only its bitwise solo output. Greedy AND seeded-sampled rows (the
    sampled row is the stronger claim: its per-row PRNG chain must
    restart bitwise)."""
    reqs = [dict(prompt=[1, 2, 3, 4], kw={}),
            dict(prompt=[9, 8, 7], kw=dict(temperature=0.8, seed=7))]
    solo = [tiny_server.generate(r["prompt"], max_new_tokens=12, **r["kw"])
            for r in reqs]
    cb = ContinuousBatcher(
        tiny_server, slots=4, segment=4,
        faults=FaultPlan.from_spec("segment_fetch:exception@seg=1"))
    with ThreadPoolExecutor(max_workers=2) as ex:
        futs = [ex.submit(cb.generate, r["prompt"], max_new_tokens=12,
                          **r["kw"]) for r in reqs]
        for f, ref in zip(futs, solo):
            np.testing.assert_array_equal(f.result(), ref)
    faults = cb.stats()["faults"]
    assert faults["failures"].get("segment_fetch") == 1
    # whichever rows were in flight at the failure replayed — and every
    # replay delivered (arrival timing decides whether the second row
    # was already admitted when the fault fired)
    assert faults["replays"]["attempted"] >= 1
    assert faults["replays"]["succeeded"] == faults["replays"]["attempted"]
    assert faults["recoveries"] == 1
    assert not cb.wedged


def test_replay_budget_exhausts_to_explicit_error(tiny_server):
    """Past max_replays the row errors explicitly — never silently lost,
    never an infinite requeue loop against a persistent fault."""
    cb = ContinuousBatcher(
        tiny_server, slots=2, segment=4, max_replays=1,
        faults=FaultPlan.from_spec("segment_fetch:exception@seg=1,n=2"))
    with pytest.raises(InjectedFault):
        cb.generate([1, 2, 3], max_new_tokens=8)
    faults = cb.stats()["faults"]
    assert faults["replays"] == {"attempted": 1, "succeeded": 0}
    # the engine itself recovers: the next request serves bitwise
    np.testing.assert_array_equal(
        cb.generate([1, 2, 3], max_new_tokens=8),
        tiny_server.generate([1, 2, 3], max_new_tokens=8))


def test_long_prompt_row_replays_through_chunked_path(tiny_server):
    """A replayed row whose prompt exceeds group_prefill_max must NOT
    re-prefill through the ragged group program — that shape was never
    compiled or warmed, and under a production watchdog the fresh
    compile would trip mid-recovery and burn the replay budget. The
    replay re-runs the same chunked/solo prefill path the row was
    admitted with (already-compiled programs), bitwise the fault-free
    run."""
    prompt = list(range(1, 13))   # 12 tokens > group_prefill_max=4
    solo = tiny_server.generate(prompt, max_new_tokens=8)
    cb = ContinuousBatcher(
        tiny_server, slots=2, segment=4, group_prefill_max=4,
        faults=FaultPlan.from_spec("segment_fetch:exception@seg=1"))
    np.testing.assert_array_equal(cb.generate(prompt, max_new_tokens=8),
                                  solo)
    faults = cb.stats()["faults"]
    assert faults["replays"] == {"attempted": 1, "succeeded": 1}
    # the replay prefilled the row solo — the ragged group program
    # (never compiled for this length) was not touched
    assert cb.prefill_groups == 0


def test_done_but_undrained_row_survives_engine_error(tiny_server):
    """The PR 5 preservation path, now exercised deterministically: a
    row that completed mid-pipeline (done=True, slot held as garbage
    until the drain barrier) keeps its bitwise result through an engine
    failure injected UNDER it — only the unfinished neighbor replays."""
    short, long_ = [5, 6, 7], [1, 2, 3, 4]
    solo_short = tiny_server.generate(short, max_new_tokens=4)
    solo_long = tiny_server.generate(long_, max_new_tokens=12)
    # segment 4, depth 2: fetch #1 (slowed 120 ms by the transport
    # delay, so the long row reliably arrives while it is in flight)
    # completes the short row mid-pipeline; fetch #2 fails. At failure
    # time the short row is done-but-undrained, the long row mid-decode.
    cb = ContinuousBatcher(
        tiny_server, slots=2, segment=4, pipeline_depth=2,
        faults=FaultPlan.from_spec(
            "transport:delay@ms=120,n=2;segment_fetch:exception@seg=2"))
    with ThreadPoolExecutor(max_workers=2) as ex:
        f_short = ex.submit(cb.generate, short, max_new_tokens=4)
        time.sleep(0.05)  # the short row packs first and is in flight
        f_long = ex.submit(cb.generate, long_, max_new_tokens=12)
        np.testing.assert_array_equal(f_short.result(), solo_short)
        np.testing.assert_array_equal(f_long.result(), solo_long)
    faults = cb.stats()["faults"]
    # exactly one row replayed: the finished one kept its result
    assert faults["replays"]["attempted"] == 1
    assert faults["replays"]["succeeded"] == 1
    assert faults["failures"].get("segment_fetch") == 1


def test_streamed_row_with_delivered_bytes_errors_not_replays(tiny_server):
    """Once bytes reached the client a replay could splice a restarted
    decode onto the open stream — the row must surface the error as a
    terminal event instead (and the stream must not hang)."""
    # the transport delay before the failing fetch gives the consumer
    # 150 ms to latch entry["streamed"] after chunk #1 is booked —
    # deterministic ordering, not a scheduler race
    cb = ContinuousBatcher(
        tiny_server, slots=2, segment=4,
        faults=FaultPlan.from_spec(
            "transport:delay@seg=2,ms=150;segment_fetch:exception@seg=2"))
    chunks = []
    with pytest.raises(InjectedFault):
        for chunk in cb.generate_stream([1, 2, 3], max_new_tokens=16):
            chunks.append(chunk)
    assert chunks, "the first segment should have streamed before the fault"
    assert cb.stats()["faults"]["replays"]["attempted"] == 0


# -- watchdog ----------------------------------------------------------------


def test_watchdog_wedges_hung_engine_and_aborts_waiters(tiny_server):
    """A hung device wait (the BENCH_r04/r05 transport wedge, injected)
    trips the watchdog within its bound: with no replay budget every
    waiter gets an explicit error instead of blocking forever, the
    engine reports wedged on its O(1) fault surface, and nothing is
    silently lost."""
    plan = FaultPlan.from_spec("segment_fetch:hang@seg=1,n=1")
    cb = ContinuousBatcher(tiny_server, slots=2, segment=4,
                           faults=plan, watchdog_s=0.4, max_replays=0)
    t0 = time.monotonic()
    try:
        with pytest.raises(EngineWatchdogTimeout):
            cb.generate([1, 2, 3], max_new_tokens=8)
        elapsed = time.monotonic() - t0
        assert elapsed < 8.0, f"waiter outlived the bound: {elapsed:.1f}s"
        assert cb.wedged
        state = cb.fault_state()
        assert state["wedged"] and not state["restarting"]
        faults = cb.stats()["faults"]
        assert faults["watchdog_trips"] >= 1
        assert faults["failures"].get("watchdog:segment_fetch", 0) >= 1
    finally:
        plan.release()
    # a clean request IS the recovery probe: serving again clears the
    # wedge and counts the recovery
    np.testing.assert_array_equal(
        cb.generate([1, 2, 3], max_new_tokens=8),
        tiny_server.generate([1, 2, 3], max_new_tokens=8))
    assert not cb.wedged
    assert cb.stats()["faults"]["recoveries"] >= 1


def test_watchdog_bounded_hang_recovers_via_replay(tiny_server):
    """A one-shot hang (transient transport stall) trips the watchdog,
    which requeues the rows; the replay through the restarted engine is
    bitwise and the wedge clears on the first successful fetch."""
    cb = ContinuousBatcher(
        tiny_server, slots=2, segment=4, watchdog_s=0.4,
        faults=FaultPlan.from_spec("segment_fetch:hang@seg=1,n=1"))
    np.testing.assert_array_equal(
        cb.generate([4, 2, 1], max_new_tokens=8),
        tiny_server.generate([4, 2, 1], max_new_tokens=8))
    faults = cb.stats()["faults"]
    assert faults["watchdog_trips"] >= 1
    assert faults["replays"]["succeeded"] == 1
    assert not cb.wedged


def test_tripped_wait_does_not_block_wedged_self_probe(tiny_server):
    """A REAL (non-injected) permanent hang never returns, so its wait
    record lingers in the registry forever — the finally-pop can't run.
    The monitor must treat a tripped record as disowned: the wedged-idle
    self-probe still fires and clears the wedge once the transport
    answers again (here: immediately, the CPU device is fine)."""
    cb = ContinuousBatcher(tiny_server, slots=2, segment=4,
                           watchdog_s=0.3, max_replays=0)
    release = threading.Event()
    gen0 = cb._gen

    def waiter():
        try:
            # a genuine hang: blocks regardless of the watchdog's abort
            cb._device_wait("segment_fetch", gen0, release.wait, 30)
        except Exception:  # noqa: BLE001 — post-release unwind
            pass

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not cb.wedged:
            time.sleep(0.02)
        assert cb.wedged
        # the hung record is tripped but still registered — the hang
        # is real, nothing will ever pop it
        assert any(rec["tripped"] for rec in cb._waits.values())
        # the self-probe fires despite it (base cadence 2x watchdog)
        # and clears the wedge
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and cb.wedged:
            time.sleep(0.05)
        assert not cb.wedged
        assert cb.stats()["faults"]["recoveries"] >= 1
    finally:
        release.set()
        t.join(timeout=5)


# -- drain-barrier cancellation ----------------------------------------------


def test_expired_deadline_cancels_at_barrier(tiny_server):
    """A queued row whose x-deadline-ms expired cancels at the next
    drain barrier instead of burning a slot on an answer nobody can
    use. The single-slot engine is kept busy (transport delays) past
    the second request's deadline, so the cancellation is
    deterministic."""
    from lambdipy_tpu.sched import clear_request_context, set_request_context

    cb = ContinuousBatcher(
        tiny_server, slots=1, segment=4,
        faults=FaultPlan.from_spec("transport:delay@ms=120,n=2"))
    solo = tiny_server.generate([7, 7], max_new_tokens=32)
    results = {}

    def busy():
        results["a"] = cb.generate([7, 7], max_new_tokens=32)

    ta = threading.Thread(target=busy, daemon=True)
    ta.start()
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:          # A holds the only slot
        if cb.stats()["active_rows"] >= 1:
            break
        time.sleep(0.005)
    assert cb.stats()["active_rows"] >= 1
    set_request_context(cls="interactive", deadline_ms=50.0)
    try:
        with pytest.raises(RequestCancelled):
            cb.generate([1, 2, 3], max_new_tokens=8)
    finally:
        clear_request_context()
    ta.join(timeout=60)
    np.testing.assert_array_equal(results["a"], solo)  # A unaffected
    assert cb.stats()["faults"]["cancelled"] == 1


def test_abandoned_stream_cancels_and_frees_slot(tiny_server):
    """Closing a stream mid-decode (client disconnect) flags the row;
    the next drain barrier (forced here by a joiner — the churn case the
    satellite is about) cancels it instead of decoding its remaining
    ~100 tokens for nobody, and the neighbor's output is untouched."""
    cb = ContinuousBatcher(tiny_server, slots=2, segment=4)
    stream = cb.generate_stream([1, 2, 3], max_new_tokens=100)
    next(stream)          # first chunk delivered, decode is in flight
    stream.close()        # client went away
    # a joiner forces the bounded drain + barrier where the abandoned
    # row is cancelled, then decodes normally in the freed engine
    np.testing.assert_array_equal(
        cb.generate([9, 8], max_new_tokens=8),
        tiny_server.generate([9, 8], max_new_tokens=8))
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        stats = cb.stats()
        if stats["faults"]["cancelled"] and not stats["active_rows"]:
            break
        time.sleep(0.05)
    stats = cb.stats()
    assert stats["faults"]["cancelled"] == 1
    assert stats["active_rows"] == 0


# -- degradation ladder ------------------------------------------------------


def test_degradation_ladder_steps_and_restores(tiny_server):
    """Two failures inside the window step the ladder (level 1 forces
    the synchronous depth-1 loop); a clean interval restores level 0 and
    counts the restore."""
    cb = ContinuousBatcher(
        tiny_server, slots=2, segment=4, pipeline_depth=2, max_replays=2,
        degrade_window_s=60.0, degrade_clean_s=1.0,
        faults=FaultPlan.from_spec("segment_fetch:exception@seg=1,n=2"))
    # attempt 1 fails (failure #1), replay 1 fails (failure #2 -> level
    # 1), replay 2 runs clean through the degraded engine — bitwise
    np.testing.assert_array_equal(
        cb.generate([3, 1, 4], max_new_tokens=8),
        tiny_server.generate([3, 1, 4], max_new_tokens=8))
    faults = cb.stats()["faults"]
    assert faults["degrade_level"] == 1
    assert faults["degrade_steps"] == {"1": 1}
    assert faults["last_degrade_cause"] == "segment_fetch"
    time.sleep(1.2)  # a clean interval passes with no failures
    np.testing.assert_array_equal(
        cb.generate([3, 1, 4], max_new_tokens=8),
        tiny_server.generate([3, 1, 4], max_new_tokens=8))
    faults = cb.stats()["faults"]
    assert faults["degrade_level"] == 0
    assert faults["restores"] == 1


# -- wedged-aware fleet health (stub replicas, no device) --------------------


class _WedgeableStub:
    """Minimal bundle-server stand-in speaking the /healthz + /invoke
    contract, with a flip-able wedged flag — the fleet-side view of a
    replica whose engine watchdog declared the device transport dead."""

    def __init__(self):
        self.cfg = {"wedged": False}
        self.invokes = 0
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    w = stub.cfg["wedged"]
                    self._send(200, {"ok": True, "ready": not w,
                                     "wedged": w, "pid": 1000})
                elif self.path == "/metrics":
                    self._send(200, {"count": stub.invokes})
                else:
                    self._send(404, {"ok": False})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if stub.cfg["wedged"]:
                    # a wedged engine's admission gate sheds — the stub
                    # stands in for server.py's accept-hole 503
                    self._send(503, {"ok": False, "shed": True,
                                     "reason": "wedged",
                                     "retry_after_s": 2.0})
                    return
                stub.invokes += 1
                self._send(200, {"ok": True, "echo": body.get("tokens")})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def kill(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_pool_ejects_wedged_replica_and_router_routes_around():
    """The watchdog e2e acceptance check, fleet side: a replica whose
    /healthz reports wedged:true is EJECTED at probe speed (a liveness
    200 notwithstanding), never offered as a warming-degraded fallback,
    and concurrent traffic through the router all lands on the healthy
    replica — zero lost requests. Clearing the wedge readmits it through
    the normal consecutive-passes path."""
    from lambdipy_tpu.fleet import EJECTED, READY, FleetRouter, ReplicaPool

    s0, s1 = _WedgeableStub(), _WedgeableStub()
    pool = ReplicaPool(probe_interval=0.1, fail_threshold=1,
                       readmit_passes=2, probe_timeout=2.0)
    pool.attach("r0", s0.url)
    pool.attach("r1", s1.url)
    router = FleetRouter(pool, affinity_on=False, max_retries=2,
                         backoff_s=0.01, backoff_cap_s=0.2)
    router.start_background()
    try:
        pool.probe_all()
        assert {r.name for r in pool.routable()} == {"r0", "r1"}
        s0.cfg["wedged"] = True
        pool.probe_all()
        r0 = pool.replicas["r0"]
        assert r0.state == EJECTED and r0.wedged
        assert [r.name for r in pool.routable()] == ["r1"]
        # wedged-but-live is NOT a brownout fallback: degrading to it
        # would turn fleet-wide warmups into guaranteed timeouts
        assert pool.live_fallback() == []
        # fleet /healthz surfaces which replicas are wedged
        with urllib.request.urlopen(
                f"http://127.0.0.1:{router.port}/healthz", timeout=10) as r:
            h = json.loads(r.read())
        assert h["ok"] and h["wedged"] == ["r0"]

        results = []

        def worker(i):
            req = urllib.request.Request(
                f"http://127.0.0.1:{router.port}/invoke",
                data=json.dumps({"tokens": [i]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                results.append(json.loads(r.read()))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 8 and all(r["ok"] for r in results)
        assert s1.invokes == 8 and s0.invokes == 0

        # recovery: wedge clears -> readmitted after readmit_passes
        s0.cfg["wedged"] = False
        for _ in range(3):
            pool.probe_all()
        assert pool.replicas["r0"].state == READY
        assert {r.name for r in pool.routable()} == {"r0", "r1"}
    finally:
        router.stop()
        pool.close()
        for s in (s0, s1):
            s.kill()


def test_server_maps_request_cancelled_to_shed_503(monkeypatch, tmp_path):
    """A RequestCancelled escaping handler.invoke (the engine cancelled
    the row at a drain barrier: deadline expired / waiter gone) is NOT a
    server fault: /invoke answers shed-style — 503 + Retry-After with a
    shed body — instead of a generic 500, and the shed counter gains a
    ``cancelled`` reason."""
    from pathlib import Path
    from types import SimpleNamespace

    import lambdipy_tpu.runtime.server as server_mod
    from lambdipy_tpu.runtime.loader import BootReport

    def invoke(st, request):
        raise RequestCancelled("cancelled at drain barrier: "
                               "deadline expired")

    def stub_boot(bundle_dir, warmup=True):
        return BootReport(
            bundle_dir=Path(bundle_dir),
            handler=SimpleNamespace(invoke=invoke),
            state=SimpleNamespace(meta={"model": "stub"},
                                  stats=lambda: {"stub": True}),
            stages={"init": 0.0}, manifest={"payload": {"extra": {}}})

    monkeypatch.setattr(server_mod, "load_bundle", stub_boot)
    srv = server_mod.BundleServer(tmp_path, port=0,
                                  warmup=False).start_background()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/invoke",
            data=json.dumps({"tokens": [1, 2], "n": 4}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 503
        assert int(exc.value.headers["Retry-After"]) >= 1
        body = json.loads(exc.value.read())
        assert not body["ok"] and "deadline expired" in body["error"]
        shed = srv.sched.admission.shed_report()
        assert shed["by_reason"].get("cancelled") == 1
        # a cancellation is not an error: record_error() was never hit
        assert srv.stats.report()["errors"] == 0
    finally:
        threading.Thread(target=srv.stop, daemon=True).start()


# -- real-bundle-server e2e (slow: boots a server) ---------------------------


@pytest.mark.slow
def test_server_healthz_wedged_and_admission_accept_hole(tmp_path):
    """End to end on a real bundle server: an injected segment_fetch
    hang flips /healthz to ready:false wedged:true within the watchdog
    bound, admission 503s (the accept hole) while the wedged engine is
    restarting instead of queueing into it, and once the bounded hang
    rule burns out the replay succeeds and the wedge clears."""
    from lambdipy_tpu.runtime.server import BundleServer

    from test_runtime import make_model_bundle

    # the watchdog is sized ABOVE the tiny model's first-use compile
    # wall (the operator contract: a monitor cannot tell a cold XLA
    # compile from a wedge — warmup=False here makes every program
    # cold, including the degraded-ladder variants compiled mid-replay)
    # but far UNDER the injected hang's duration, so only the hang trips
    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"batch_mode": "continuous", "batch_max": "2",
               "batch_segment": "4", "engine_watchdog_s": "3.0",
               "max_replays": "8",
               "fault_spec": "segment_fetch:hang@seg=2,n=5"})
    server = BundleServer(bundle, warmup=False).start_background()
    base = f"http://127.0.0.1:{server.port}"
    try:
        def get(path):
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return json.loads(r.read())

        h = get("/healthz")
        assert h["ok"] and h["ready"] and not h["wedged"]

        # first request: segment fetch #1 succeeds, fetches #2-#6 hang
        # -> the watchdog trips + requeues ~5 times (each trip ~3 s),
        # keeping the engine wedged+restarting for seconds; the 6th
        # attempt's fetch runs clean, so the request ultimately succeeds
        # via transparent replay
        done = {}

        def doomed():
            try:
                with urllib.request.urlopen(urllib.request.Request(
                        base + "/invoke",
                        data=json.dumps({"tokens": [1, 2, 3],
                                         "n": 16}).encode(),
                        headers={"Content-Type": "application/json"}),
                        timeout=120) as r:
                    done["out"] = json.loads(r.read())
            except Exception as e:  # noqa: BLE001 — inspected below
                done["err"] = e

        t = threading.Thread(target=doomed, daemon=True)
        t.start()
        deadline = time.monotonic() + 30.0
        h = {}
        while time.monotonic() < deadline:
            h = get("/healthz")
            if h.get("wedged"):
                break
            time.sleep(0.05)
        assert h.get("wedged") and not h["ready"], h
        assert h["engine"]["wedged"]

        # the accept hole: while wedged AND restarting, new work sheds
        # 503 + Retry-After instead of queueing into a dead engine
        sheds = 0
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and not sheds:
            eng = get("/healthz").get("engine", {})
            if not (eng.get("wedged") and eng.get("restarting")):
                if "out" in done or "err" in done:
                    break  # the recovery already landed — too late
                time.sleep(0.02)
                continue
            try:
                urllib.request.urlopen(urllib.request.Request(
                    base + "/invoke",
                    data=json.dumps({"tokens": [9, 9], "n": 4}).encode(),
                    headers={"Content-Type": "application/json"}),
                    timeout=10).read()
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    body = json.loads(e.read())
                    assert body.get("shed") == "wedged"
                    assert e.headers.get("Retry-After")
                    sheds += 1
        assert sheds, "admission never shed while wedged+restarting"
        t.join(timeout=120)
        assert not t.is_alive(), "doomed request never resolved"
        # the hang was transient (n=5): the replay delivered a real
        # result — transparently, the client never saw the trips
        assert done.get("out", {}).get("ok"), done

        # wedge cleared by the successful fetch; admission open again
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            h = get("/healthz")
            if h["ready"] and not h["wedged"]:
                break
            time.sleep(0.1)
        assert h["ready"] and not h["wedged"], h
        m = get("/metrics")
        faults = m["handler"]["batching"]["faults"]
        assert faults["watchdog_trips"] >= 1
        assert faults["replays"]["succeeded"] >= 1
        assert faults["wedged"] is False
    finally:
        threading.Thread(target=server.stop, daemon=True).start()


# -- the structured site registry (chaos-soak satellite) ---------------------


def test_site_registry_metadata():
    """Every site carries an owner, its arming env var, and a note; the
    tuple view stays in sync; list_sites filters feed the nemesis menu
    and the docs table."""
    from lambdipy_tpu.runtime.faults import REGISTRY, SITES, list_sites

    assert tuple(REGISTRY) == SITES
    for site in REGISTRY.values():
        assert site.owner in ("engine", "store", "pool", "router"), site
        assert site.env in ("LAMBDIPY_FAULT", "LAMBDIPY_FLEET_FAULT")
        assert site.note
        # the env var follows the owner: replica-process sites arm via
        # LAMBDIPY_FAULT, fleet-process sites via LAMBDIPY_FLEET_FAULT
        want = ("LAMBDIPY_FAULT" if site.owner in ("engine", "store")
                else "LAMBDIPY_FLEET_FAULT")
        assert site.env == want, site
    engine = {s.name for s in list_sites(owner="engine")}
    assert "segment_fetch" in engine and "probe" not in engine
    fleet = {s.name for s in list_sites(env="LAMBDIPY_FLEET_FAULT")}
    assert "route_connect" in fleet and "prefix_walk" not in fleet


def test_every_fire_site_in_the_tree_is_registered():
    """Grep-based completeness: every literal fault-site reference in
    lambdipy_tpu/ (``faults.check("x")`` and ``_device_wait("x", ...)``
    call sites) names a registered site, and every registered site has
    at least one call site — a new site cannot silently dodge the
    chaos soak's registry-derived nemesis menu."""
    import re
    from pathlib import Path

    from lambdipy_tpu.runtime.faults import REGISTRY

    root = Path(__file__).resolve().parents[1] / "lambdipy_tpu"
    check_re = re.compile(r"\.check\(\s*[\"']([a-z_]+)[\"']")
    wait_re = re.compile(r"_device_wait\(\s*[\"']([a-z_]+)[\"']")
    found: set = set()
    for path in root.rglob("*.py"):
        text = path.read_text()
        found.update(check_re.findall(text))
        found.update(wait_re.findall(text))
    unregistered = found - set(REGISTRY)
    assert not unregistered, (
        f"fault sites fired in the tree but missing from the "
        f"faults.py REGISTRY: {sorted(unregistered)}")
    unfired = set(REGISTRY) - found
    assert not unfired, (
        f"registered fault sites with no check()/_device_wait() call "
        f"site anywhere in lambdipy_tpu/: {sorted(unfired)}")


# -- runtime arm/clear (the nemesis control surface) -------------------------


def test_fault_plan_runtime_arm_and_clear():
    plan = FaultPlan.empty()
    assert not plan.armed()["active"]
    added = plan.arm("transport:exception@n=1;probe:delay@ms=5,n=2")
    assert len(added) == 2
    with pytest.raises(InjectedFault):
        plan.check("transport")
    assert plan.clear() == 2
    plan.check("transport")  # cleared: no-op fast path, no fire
    # counters survived the clear (the deterministic replay spine)
    assert plan.counts()["transport"] == 1
    # a bad runtime spec touches nothing
    with pytest.raises(ValueError):
        plan.arm("transport:nope")
    assert not plan.armed()["active"]


def test_fault_plan_clear_releases_hangs_without_poisoning_later_ones():
    """clear() resolves in-flight hangs (raising InjectedFault — an
    abandoned wait must not look like success) while hangs armed LATER
    still block: the release event is swapped, not left set."""
    plan = FaultPlan.empty()
    plan.arm("transport:hang")
    results: list = []

    def waiter(tag):
        try:
            plan.check("transport")
            results.append((tag, "passed"))
        except InjectedFault:
            results.append((tag, "released"))

    t1 = threading.Thread(target=waiter, args=("first",), daemon=True)
    t1.start()
    time.sleep(0.15)
    plan.clear()
    t1.join(5.0)
    assert ("first", "released") in results
    # re-arm: the fresh hang must actually block again
    plan.arm("transport:hang")
    t2 = threading.Thread(target=waiter, args=("second",), daemon=True)
    t2.start()
    t2.join(0.4)
    assert t2.is_alive(), "a re-armed hang resolved instantly — the " \
        "released event leaked into the new rule"
    plan.release()
    t2.join(5.0)
    assert ("second", "released") in results
