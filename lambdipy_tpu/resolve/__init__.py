"""Project resolution + artifact/source indexes.

The reference resolves a project's requirements.txt / Pipfile into a pinned
package list, splits it into recipe-covered vs plain deps, and matches the
recipe-covered set against prebuilt artifacts on GitHub Releases (SURVEY.md
§3.1 #2/#4, §4 A). This environment has no network, so the release index
becomes a local content-addressed artifact registry and sources come from a
local source store.
"""

from lambdipy_tpu.resolve.requirements import (
    Requirement,
    ResolutionError,
    parse_requirement,
    parse_requirements_text,
    resolve_project,
    split_by_recipes,
)
from lambdipy_tpu.resolve.registry import ArtifactRegistry
from lambdipy_tpu.resolve.releases import ReleaseFetcher, ReleaseStore
from lambdipy_tpu.resolve.sources import SourceStore

__all__ = [
    "ArtifactRegistry",
    "ReleaseFetcher",
    "ReleaseStore",
    "Requirement",
    "ResolutionError",
    "SourceStore",
    "parse_requirement",
    "parse_requirements_text",
    "resolve_project",
    "split_by_recipes",
]
