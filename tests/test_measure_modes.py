"""Structural regression tests for the 8B measurement modes
(``scripts/measure_8b.py``) at tiny dims on CPU.

Why these exist: the modes only produce value on the chip, and chip
time is scarce — round 5 lost its first on-chip speculative run (~17
min of tunnel time) to a NameError sitting AFTER the measurements in a
code path no test had ever imported. Each mode here runs end-to-end at
toy dims and asserts its record's required keys, so a broken postamble
is caught on CPU before it can burn a measurement window.

Slow tier: each mode compiles several toy programs on one core.
"""

import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "scripts"))

TINY = dict(vocab_size=256, hidden=64, layers=2, heads=4, kv_heads=2,
            mlp=128, max_len=512)


@pytest.fixture()
def tiny_dims(tmp_path, monkeypatch):
    """Point the module at toy dims and an isolated params cache."""
    import measure_8b as m

    monkeypatch.setenv("LAMBDIPY_CACHE_DIR", str(tmp_path / "cache"))
    monkeypatch.setattr(m, "DIMS", dict(m.DIMS, **TINY))
    return m


@pytest.mark.slow
def test_measure_decode_and_prefill_record(tiny_dims):
    r = tiny_dims.measure(batches=(1, 2), n_new=8, prefill_len=64)
    for key in ("b1_decode_tok_s", "b1_roofline_tok_s", "b2_decode_tok_s",
                "weight_upload_s", "d2h_rtt_ms", "prefill_512_net_ms",
                "prefill_512_mfu"):
        assert key in r, (key, r)
    assert r["prefill_step_corrected"] is True


@pytest.mark.slow
def test_measure_speculative_record(tiny_dims):
    r = tiny_dims.measure_speculative(n_new=16, k=4)
    for key in ("plain_tok_s", "spec_tok_s", "speedup_vs_plain",
                "greedy_agreement", "roofline_plain_b1_tok_s"):
        assert key in r, (key, r)
    assert "tokens_per_step" in r["spec_stats"]


@pytest.mark.slow
def test_measure_concurrent_record(tiny_dims):
    r = tiny_dims.measure_concurrent(n_requests=3, n_new=8)
    for key in ("serial_wall_s", "concurrent_wall_s", "speedup_vs_serial",
                "concurrent_tok_s", "rows_bitwise_equal",
                "solo_agreement_min", "solo_agreement_mean", "engine"):
        assert key in r, (key, r)
    # the adapter runs bfloat16 even on CPU, so a staggered join that
    # lands in a different-width group-prefill CAN legally flip a
    # near-tied argmax here too — hold the mode's own agreement floor
    # rather than demanding bitwise equality of every row
    assert r["solo_agreement_mean"] >= 0.9, r


@pytest.mark.slow
def test_measure_kv_quant_record(tiny_dims):
    r = tiny_dims.measure_kv_quant(n_new=32, context=128)
    for key in ("bf16_kv_b1_tok_s", "int8_kv_b1_tok_s",
                "bf16_kv_b8_roofline_tok_s", "bf16_kv_b1_pair_spread_ms",
                "greedy_agreement", "agreeing_prefix"):
        assert key in r, (key, r)


@pytest.mark.slow
def test_measure_prefill_table_record(tiny_dims):
    r = tiny_dims.measure_prefill(lens=(32, 64, 96, 128), flash_len=256,
                                  batch_len=32, batch=2)
    backends = {row["backend"] for row in r["rows"]}
    assert {"dense", "flash", "chunked512"} <= backends, backends
    assert "decode_step_ms" in r
    assert "scaling_fit" in r
    dense = [row for row in r["rows"] if row["backend"] == "dense"]
    assert all("raw_ms" in row for row in dense)
