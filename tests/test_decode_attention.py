"""Length-aware blocked decode attention: reference vs oracle, Pallas
kernel (interpret) vs reference across lengths / window buckets / GQA
group sizes / int8 KV, and the ``attn_backend="blocked"`` model path's
BITWISE on/off parity with the dense decode path — solo, streamed, and
under concurrent continuous-engine traffic (the prefixstore on/off
pattern, applied to the decode side)."""

from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lambdipy_tpu.ops.decode_attention import (blocked_decode_attention,
                                               decode_attention,
                                               decode_attention_reference)


def _rand(shape, seed, dtype=jnp.float32):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape), dtype)


def _masked_mha_oracle(q, k, v, active_len):
    """Independent oracle: broadcast GQA heads, mask by active_len, plain
    softmax attention."""
    b, s, h, d = q.shape
    t, kvh = k.shape[1], k.shape[2]
    kk = jnp.repeat(k, h // kvh, axis=2)
    vv = jnp.repeat(v, h // kvh, axis=2)
    logits = jnp.einsum("bshd,bthd->bhst", q, kk).astype(jnp.float32)
    logits = logits / jnp.sqrt(d).astype(jnp.float32)
    valid = jnp.arange(t)[None, :] < active_len[:, None]
    logits = jnp.where(valid[:, None, None, :], logits, -1e9)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, vv)


@pytest.mark.parametrize("kvh", [1, 2, 4])
def test_reference_matches_masked_mha(kvh):
    b, h, d, t = 3, 4, 32, 96
    q = _rand((b, 1, h, d), 0)
    k = _rand((b, t, kvh, d), 1)
    v = _rand((b, t, kvh, d), 2)
    alen = jnp.asarray([1, 40, 96], jnp.int32)
    out = decode_attention_reference(q, k, v, alen)
    ref = _masked_mha_oracle(q, k, v, alen)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("block_k", [32, 64, 128])
@pytest.mark.parametrize("kvh", [1, 2])
def test_kernel_matches_reference_across_lengths(block_k, kvh):
    """Interpret-mode kernel vs reference at every interesting active
    length: 1, mid-block, exact block boundary, full window — the
    early-exit masking must agree everywhere."""
    b, h, d, t = 4, 4, 32, 256
    q = _rand((b, 1, h, d), 3)
    k = _rand((b, t, kvh, d), 4)
    v = _rand((b, t, kvh, d), 5)
    alen = jnp.asarray([1, block_k // 2 + 1, block_k, t], jnp.int32)
    out = blocked_decode_attention(q, k, v, alen, block_k=block_k,
                                   interpret=True)
    ref = decode_attention_reference(q, k, v, alen)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_int8_kv_matches_dequant_reference():
    b, h, kvh, d, t = 2, 4, 2, 32, 128

    def kvq(x):
        s = jnp.maximum(jnp.max(jnp.abs(x), -1, keepdims=True) / 127.0, 1e-8)
        return jnp.round(x / s).astype(jnp.int8), s.astype(jnp.float32)

    q = _rand((b, 1, h, d), 6)
    k_i8, k_s = kvq(_rand((b, t, kvh, d), 7))
    v_i8, v_s = kvq(_rand((b, t, kvh, d), 8))
    alen = jnp.asarray([33, 128], jnp.int32)
    out = blocked_decode_attention(q, k_i8, v_i8, alen, k_scale=k_s,
                                   v_scale=v_s, block_k=64, interpret=True)
    kd = k_i8.astype(q.dtype) * k_s.astype(q.dtype)
    vd = v_i8.astype(q.dtype) * v_s.astype(q.dtype)
    ref = decode_attention_reference(q, kd, vd, alen)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_untileable_and_multitoken_fall_back_to_reference():
    b, h, kvh, d = 1, 2, 1, 16
    alen = jnp.asarray([7], jnp.int32)
    # t=40 doesn't tile at block_k=16 -> reference, bitwise
    q = _rand((b, 1, h, d), 9)
    k, v = _rand((b, 40, kvh, d), 10), _rand((b, 40, kvh, d), 11)
    out = blocked_decode_attention(q, k, v, alen, block_k=16)
    ref = decode_attention_reference(q, k, v, alen)
    assert (np.asarray(out) == np.asarray(ref)).all()
    # s=2 (a continuation chunk) is not the kernel's job either
    q2 = _rand((b, 2, h, d), 12)
    out2 = blocked_decode_attention(q2, k, v, alen, block_k=8)
    ref2 = decode_attention_reference(q2, k, v, alen)
    assert (np.asarray(out2) == np.asarray(ref2)).all()
    # the dispatcher on CPU routes to the reference outright
    out3 = decode_attention(q, k, v, alen)
    assert (np.asarray(out3) == np.asarray(ref)).all()


# -- paged (block-table) decode attention ------------------------------------


def _paged_layout(k, v, page, seed=0, n_extra=3):
    """Scatter contiguous per-row KV into a shuffled page arena + the
    block tables naming it, with a zeroed null page at id 0 and a few
    garbage distractor pages — the layout the paged engine produces."""
    b, t, kvh, d = k.shape
    nb = t // page
    rng = np.random.default_rng(seed)
    perm = rng.permutation(b * nb) + 1 + n_extra
    n_pages = b * nb + 1 + n_extra
    k_pages = np.array(
        _rand((n_pages, page, kvh, d), seed + 50))   # garbage everywhere
    v_pages = np.array(_rand((n_pages, page, kvh, d), seed + 51))
    k_pages[0] = 0.0
    v_pages[0] = 0.0
    tables = np.zeros((b, nb), np.int32)
    kr = np.asarray(k).reshape(b * nb, page, kvh, d)
    vr = np.asarray(v).reshape(b * nb, page, kvh, d)
    for i in range(b * nb):
        pid = int(perm[i])
        k_pages[pid] = kr[i]
        v_pages[pid] = vr[i]
        tables[i // nb, i % nb] = pid
    return (jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(tables))


@pytest.mark.parametrize("kvh", [1, 2])
def test_paged_reference_bitwise_vs_dense(kvh):
    """The pure-jax paged oracle over a SHUFFLED page layout is bitwise
    the dense reference on the same values: the gather materializes
    exactly the contiguous KV, and masked positions contribute exact
    zeros either way — even when table entries past a row's length
    point at garbage pages."""
    from lambdipy_tpu.ops.decode_attention import (
        paged_decode_attention_reference)

    b, h, d, t, page = 3, 4, 32, 128, 32
    q = _rand((b, 1, h, d), 20)
    k = _rand((b, t, kvh, d), 21)
    v = _rand((b, t, kvh, d), 22)
    alen = jnp.asarray([1, 33, 128], jnp.int32)
    k_pages, v_pages, tables = _paged_layout(k, v, page, seed=23)
    # past-the-length table entries may point ANYWHERE: null them for
    # rows 0/1 to prove masking covers them
    tables = tables.at[0, 1:].set(0).at[1, 2:].set(0)
    out = paged_decode_attention_reference(q, k_pages, v_pages, tables,
                                           alen)
    ref = decode_attention_reference(q, k, v, alen)
    assert (np.asarray(out) == np.asarray(ref)).all()


@pytest.mark.parametrize("page", [32, 64])
def test_paged_kernel_matches_reference(page):
    """Interpret-mode block-table kernel vs the paged oracle across the
    interesting lengths (1, mid-page, page boundary, full window)."""
    from lambdipy_tpu.ops.decode_attention import (
        paged_blocked_decode_attention, paged_decode_attention_reference)

    b, h, kvh, d, t = 4, 4, 2, 32, 256
    q = _rand((b, 1, h, d), 30)
    k = _rand((b, t, kvh, d), 31)
    v = _rand((b, t, kvh, d), 32)
    alen = jnp.asarray([1, page // 2 + 1, page, t], jnp.int32)
    k_pages, v_pages, tables = _paged_layout(k, v, page, seed=33)
    out = paged_blocked_decode_attention(q, k_pages, v_pages, tables,
                                         alen, interpret=True)
    ref = paged_decode_attention_reference(q, k_pages, v_pages, tables,
                                           alen)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_paged_kernel_int8_kv_matches_dequant_reference():
    from lambdipy_tpu.ops.decode_attention import (
        paged_blocked_decode_attention, paged_decode_attention_reference)

    b, h, kvh, d, t, page = 2, 4, 2, 32, 128, 32

    def kvq(x):
        s = jnp.maximum(jnp.max(jnp.abs(x), -1, keepdims=True) / 127.0,
                        1e-8)
        return jnp.round(x / s).astype(jnp.int8), s.astype(jnp.float32)

    q = _rand((b, 1, h, d), 40)
    k_i8, k_s = kvq(_rand((b, t, kvh, d), 41))
    v_i8, v_s = kvq(_rand((b, t, kvh, d), 42))
    alen = jnp.asarray([33, 128], jnp.int32)
    nb = t // page
    kp = k_i8.reshape(b * nb, page, kvh, d)
    vp = v_i8.reshape(b * nb, page, kvh, d)
    ksp = k_s.reshape(b * nb, page, kvh, 1)
    vsp = v_s.reshape(b * nb, page, kvh, 1)
    tables = jnp.arange(b * nb, dtype=jnp.int32).reshape(b, nb)
    out = paged_blocked_decode_attention(
        q, kp, vp, tables, alen, k_scale_pages=ksp, v_scale_pages=vsp,
        interpret=True)
    ref = paged_decode_attention_reference(
        q, kp, vp, tables, alen, k_scale_pages=ksp, v_scale_pages=vsp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_paged_dispatcher_multitoken_falls_back():
    """s > 1 (a continuation chunk) routes to the reference — the
    kernel is single-token by design, like the contiguous dispatcher."""
    from lambdipy_tpu.ops.decode_attention import (
        paged_decode_attention, paged_decode_attention_reference)

    b, h, kvh, d, t, page = 1, 2, 1, 16, 64, 32
    q = _rand((b, 2, h, d), 45)
    k = _rand((b, t, kvh, d), 46)
    v = _rand((b, t, kvh, d), 47)
    alen = jnp.asarray([40], jnp.int32)
    k_pages, v_pages, tables = _paged_layout(k, v, page, seed=48)
    out = paged_decode_attention(q, k_pages, v_pages, tables, alen)
    ref = paged_decode_attention_reference(q, k_pages, v_pages, tables,
                                           alen)
    assert (np.asarray(out) == np.asarray(ref)).all()


# -- model-path on/off parity ------------------------------------------------


@pytest.fixture(scope="module")
def param_servers():
    """(dense server, blocked server) sharing one set of weights, float
    KV — plus an int8-KV pair. One build per module: server construction
    compiles nothing, but params init is the slow part."""
    from lambdipy_tpu.models import registry

    out = {}
    for kv in (None, "int8"):
        extra = {} if kv is None else {"kv_quant": kv}
        dense = registry.get("llama-tiny").build(extra=dict(extra))
        params = dense.init_params(seed=0)
        blocked = registry.get("llama-tiny").build(
            extra=dict(extra, attn_backend="blocked"))
        out[kv] = (dense.make_server(params), blocked.make_server(params))
    return out


@pytest.mark.parametrize("kv", [None, "int8"])
def test_blocked_backend_bitwise_vs_dense(param_servers, kv):
    """The acceptance bar: blocked decode output equals dense decode
    output BITWISE — float and int8 KV (both read the same dequantized
    values through the same masked math on the reference path), greedy
    and seeded-sampled, ragged batches included."""
    dense, blocked = param_servers[kv]
    rows = [list(range(1, 25)), list(range(7, 14))]
    for kw in ({}, dict(temperature=0.9, seed=11, top_k=7, top_p=0.9)):
        off = dense.generate(rows, max_new_tokens=6, **kw)
        on = blocked.generate(rows, max_new_tokens=6, **kw)
        np.testing.assert_array_equal(on, off, err_msg=f"kv={kv} kw={kw}")


def test_blocked_backend_streaming_parity(param_servers):
    dense, blocked = param_servers[None]
    row = list(range(3, 40))
    off = dense.generate(row, max_new_tokens=6)
    chunks = list(blocked.generate_stream(row, max_new_tokens=6, segment=3))
    np.testing.assert_array_equal(np.concatenate(chunks, axis=1), off)


def test_blocked_backend_prefix_cache_parity(param_servers):
    """Blocked decode composes with the prefix-cache continuation: the
    suffix + decode from a cached prefix stays bitwise the dense run."""
    dense, blocked = param_servers[None]
    row = list(range(2, 50))
    off = dense.generate(row, max_new_tokens=6)
    on = blocked.generate(row[32:], prefix=row[:32], max_new_tokens=6)
    np.testing.assert_array_equal(on, off)


# -- windowed continuous engine ---------------------------------------------


def test_windowed_engine_parity_under_concurrent_traffic(param_servers):
    """Window-bucketed segments under concurrent mixed traffic: every
    row's tokens are bitwise its solo dense output, and the engine's
    decode-window counters show it actually read less than the full
    cache."""
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    dense, blocked = param_servers[None]
    cb = ContinuousBatcher(blocked, slots=4, segment=4)
    reqs = [
        dict(row=list(range(1, 20)), kw={}),
        dict(row=list(range(30, 70)), kw={}),
        dict(row=[9, 8, 7], kw=dict(temperature=1.1, top_k=3, seed=3)),
    ]
    solo = [dense.generate(r["row"], max_new_tokens=6, **r["kw"])
            for r in reqs]
    with ThreadPoolExecutor(max_workers=3) as ex:
        futs = [ex.submit(cb.generate, r["row"], max_new_tokens=6,
                          **r["kw"]) for r in reqs]
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(f.result(), solo[i],
                                          err_msg=str(reqs[i]))
    win = cb.stats()["decode_window"]
    assert win["segments"] > 0
    assert win["savings_ratio"] < 1.0
    assert win["window_tokens"] < win["full_tokens"]
    assert win["attended_tokens"] <= win["window_tokens"]


def test_windowed_engine_off_is_full_window(param_servers):
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    dense, _ = param_servers[None]
    cb = ContinuousBatcher(dense, slots=2, segment=4,
                           window_bucketing=False)
    row = list(range(1, 16))
    np.testing.assert_array_equal(
        cb.generate(row, max_new_tokens=6),
        dense.generate(row, max_new_tokens=6))
    win = cb.stats()["decode_window"]
    assert win["savings_ratio"] == 1.0
    assert list(win["buckets"]) == [str(cb.cache_len)]
