"""Flash attention: Pallas TPU kernel + pure-jax reference.

Online-softmax blocked attention (one pass over K/V with running max/sum),
the standard memory-bound formulation: K/V tiles stream through VMEM, the
(s x s) score matrix never materializes in HBM. Grid is
(batch*heads, q_blocks, k_blocks) with the k dimension innermost — TPU grid
execution is sequential, so the f32 scratch accumulators carry across k
steps and are finalized on the last one.

The pure-jax `mha_reference` is the numerics oracle (tests run the kernel
in interpret mode against it) and the CPU fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def mha_reference(q, k, v, *, causal: bool = False, scale: float | None = None):
    """Plain attention. q/k/v: [b, s, h, d] (kv may have fewer heads for GQA
    — they are broadcast). Returns [b, s, h, d]."""
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sk = k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale: float, causal: bool, block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]  # [bq, d]
    k = k_ref[0]  # [bk, d]
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [bq, bk]

    if causal:
        q_idx = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_idx = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_idx >= k_idx, s, NEG_INF)

    m_prev = m_ref[:]  # [bq, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)  # [bq, bk] f32
    alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
    l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[:] = m_new
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def flash_attention(q, k, v, *, causal: bool = False, scale: float | None = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None):
    """Flash attention. q/k/v: [b, s, h, d]; kv heads broadcast for GQA.
    Falls back to the reference when shapes don't tile (tiny test configs).
    ``interpret=None`` auto-selects interpret mode on the CPU backend
    (Mosaic compiles only for TPU)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kvh = k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else d ** -0.5
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        return mha_reference(q, k, v, causal=causal, scale=scale)

    # [b, s, h, d] -> [b*h, s, d]
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    grid = (b * h, sq // block_q, sk // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
