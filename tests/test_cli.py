"""CLI tests (click CliRunner) over the end-to-end build->deploy surface."""

import json

import pytest
from click.testing import CliRunner

from lambdipy_tpu.cli import main


@pytest.fixture()
def tiny_recipe_dir(tmp_path):
    d = tmp_path / "recipes"
    d.mkdir()
    (d / "tiny-llm.toml").write_text(
        'schema = 1\nname = "tiny-llm"\nversion = "0.1"\ndevice = "any"\n'
        'base_layer = "jax-tpu"\nrequires = []\n'
        "[payload]\n"
        'model = "llama-tiny"\n'
        'handler = "lambdipy_tpu.runtime.handlers:generate_handler"\n'
        'params = "init"\ndtype = "float32"\n')
    return d


def test_recipes_listing(tiny_recipe_dir):
    result = CliRunner().invoke(main, ["recipes", "--recipe-dir", str(tiny_recipe_dir)])
    assert result.exit_code == 0, result.output
    assert "jax-resnet50" in result.output and "tiny-llm" in result.output


def test_show_recipe():
    result = CliRunner().invoke(main, ["show", "jax-llama3-8b"])
    assert result.exit_code == 0
    doc = json.loads(result.output)
    assert doc["payload"]["quant"] == "int8"


def test_show_unknown_recipe_fails_cleanly():
    result = CliRunner().invoke(main, ["show", "nope"])
    assert result.exit_code != 0
    assert "no recipe named" in str(result.exception)


def test_build_publish_cache_hit_and_artifacts(tiny_recipe_dir, tmp_path):
    runner = CliRunner()
    reg = str(tmp_path / "registry")
    args = ["build", "tiny-llm", "--recipe-dir", str(tiny_recipe_dir),
            "--registry", reg]
    r1 = runner.invoke(main, args)
    assert r1.exit_code == 0, r1.output
    assert "built + published" in r1.output
    r2 = runner.invoke(main, args)
    assert "cache hit" in r2.output
    r3 = runner.invoke(main, ["artifacts", "--registry", reg])
    assert "tiny-llm-0.1" in r3.output


def test_build_to_out_dir(tiny_recipe_dir, tmp_path):
    out = tmp_path / "bundle"
    r = CliRunner().invoke(main, [
        "build", "tiny-llm", "--recipe-dir", str(tiny_recipe_dir),
        "--out", str(out)])
    assert r.exit_code == 0, r.output
    assert (out / "manifest.json").exists()
    assert (out / "params" / "orbax").exists()
    assert (out / "handler.py").exists()


def test_package_command(tmp_path):
    req = tmp_path / "requirements.txt"
    req.write_text("einops\n")
    out = tmp_path / "build"
    r = CliRunner().invoke(main, ["package", str(req), "--out", str(out)])
    assert r.exit_code == 0, r.output
    assert (out / "site" / "einops").is_dir()


def test_deploy_rejects_unknown_target(tmp_path):
    r = CliRunner().invoke(main, ["deploy", "definitely-missing",
                                  "--registry", str(tmp_path / "reg")])
    assert r.exit_code != 0
    assert "neither a bundle dir" in r.output


@pytest.mark.slow  # >14 s; sibling tests keep this surface in tier-1 (wall budget)
def test_build_records_warm_outcome_in_manifest(tiny_recipe_dir, tmp_path,
                                                monkeypatch):
    """The warm step's outcome is part of the bundle record (VERDICT r2
    weak #5: a failed warm previously shipped silently)."""
    out = tmp_path / "bundle"
    r = CliRunner().invoke(main, [
        "build", "tiny-llm", "--recipe-dir", str(tiny_recipe_dir),
        "--out", str(out)])
    assert r.exit_code == 0, r.output
    manifest = json.loads((out / "manifest.json").read_text())
    assert manifest["warm"]["ok"] is True
    assert manifest["warm"]["cache_entries"] > 0

    # simulated wedge: the warm subprocess times out -> recorded, not silent
    monkeypatch.setenv("LAMBDIPY_WARM_TIMEOUT", "0.01")
    out2 = tmp_path / "bundle2"
    r2 = CliRunner().invoke(main, [
        "build", "tiny-llm", "--recipe-dir", str(tiny_recipe_dir),
        "--out", str(out2)])
    assert r2.exit_code == 0, r2.output
    manifest2 = json.loads((out2 / "manifest.json").read_text())
    assert manifest2["warm"]["ok"] is False
    assert "timeout" in manifest2["warm"]["error"]


def test_doctor_reports_environment(tmp_path, monkeypatch):
    monkeypatch.setenv("LAMBDIPY_PLATFORM", "cpu")
    r = CliRunner().invoke(main, [
        "doctor", "--probe-timeout", "60",
        "--registry", str(tmp_path / "reg"),
        "--state", str(tmp_path / "deployments.json")])
    assert r.exit_code == 0, r.output
    doc = json.loads(r.output)
    assert doc["packages"]["jax"] and doc["packages"]["libtpu"]
    assert doc["device"]["ok"] is True and doc["device"]["platform"] == "cpu"
    assert doc["registry"]["artifacts"] == 0
    assert doc["deployments"] == []


def test_doctor_diagnoses_wedged_device(tmp_path, monkeypatch):
    """A hung device probe is reported as a wedge with a nonzero exit, not
    an indefinite hang (the axon transport has done exactly this)."""
    monkeypatch.delenv("LAMBDIPY_PLATFORM", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    # deterministic wedge: the probe child hangs before touching jax, so
    # the test doesn't depend on the real transport being slow
    monkeypatch.setenv("LAMBDIPY_DOCTOR_WEDGE", "1")
    r = CliRunner().invoke(main, [
        "doctor", "--probe-timeout", "1",
        "--registry", str(tmp_path / "reg"),
        "--state", str(tmp_path / "deployments.json")])
    doc = json.loads(r.output)
    assert doc["device"]["ok"] is False
    assert "wedge" in doc["device"]["error"]
    assert r.exit_code == 1
