"""Test configuration.

Runs the suite on a virtual 8-device CPU mesh (SURVEY.md §5.4): multi-chip
mesh/pjit/collective logic is exercised without TPU hardware and the same
code runs unmodified on a real slice. Environment must be set before jax is
first imported, hence the module-level assignments here.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# keep test compiles fast and deterministic
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_registry(tmp_path):
    from lambdipy_tpu.resolve.registry import ArtifactRegistry

    return ArtifactRegistry(tmp_path / "registry")


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual CPU devices, got {devices}"
    return devices
