"""Test configuration.

Runs the suite on a virtual 8-device CPU mesh (SURVEY.md §5.4): multi-chip
mesh/pjit/collective logic is exercised without TPU hardware and the same
code runs unmodified on a real slice.

Environment quirk (measured, important): this machine's axon sitecustomize
preloads jax and registers the TPU PJRT plugin at *interpreter start*, and
starting the interpreter with ``JAX_PLATFORMS=cpu`` makes that registration
hang. So the env var must NOT be set here (pytest started under the shell's
``JAX_PLATFORMS=axon``); instead the platform is switched to CPU after
startup via ``jax.config.update`` — backends have not initialized yet at
conftest-import time, so the switch is effective and the axon plugin is
never initialized.
"""

import os

# XLA flags are read at first backend initialization, which happens after
# conftest import — safe to set here.
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402  (already preloaded by sitecustomize)

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_asset_cache(tmp_path, monkeypatch):
    """Keep the release-asset download cache out of the real HOME."""
    monkeypatch.setenv("LAMBDIPY_CACHE_DIR", str(tmp_path / "asset-cache"))


@pytest.fixture()
def tmp_registry(tmp_path):
    from lambdipy_tpu.resolve.registry import ArtifactRegistry

    return ArtifactRegistry(tmp_path / "registry")


@pytest.fixture(scope="session")
def tiny_server():
    """One shared llama-tiny LlamaServer for the engine test modules:
    its compiled-program cache is the expensive part, and the continuous
    and pipelined-engine suites exercise the same program families —
    building per-module would recompile them all. Tests that mutate
    server state (prefix registry, custom caps) build their own."""
    from lambdipy_tpu.models import registry

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    return adapter.make_server(params)


@pytest.fixture(scope="session")
def cpu_devices():
    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual CPU devices, got {devices}"
    return devices


@pytest.fixture()
def count_sp_decode(monkeypatch):
    """Counts sp_decode_step TRACES so sp-path tests can assert the
    sequence-parallel decode actually ran (code-review r5: a silently
    dropped backend override once made those tests dense-vs-dense)."""
    import lambdipy_tpu.parallel.spdecode as spd

    calls = {"n": 0}
    real = spd.sp_decode_step

    def counting(*args, **kwargs):
        calls["n"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(spd, "sp_decode_step", counting)
    return calls
