"""Deterministic sharded data loading for LM training.

Design (grain-style, TPU-first):

- **Index-based, not stream-based**: an epoch is a seeded permutation of
  example indices; batch ``t`` is a pure function of ``(seed, epoch, t)``.
  That makes the loader trivially resumable — its entire state is three
  integers — and keeps host work off the device critical path.
- **Multi-host sharding**: each process reads only its
  ``global_batch / process_count`` slice of every batch
  (parallel/distributed.py process_batch_slice contract); jax assembles
  the global array from per-process shards via the dp/sp batch sharding.
- **Static shapes**: fixed ``[batch, seq_len+1]`` windows (the +1 feeds
  the shift-by-one LM objective in train/step.py), partial tail windows
  dropped — no dynamic shapes under jit, ever.
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path

import numpy as np


class TokenSource:
    """A flat token array exposed as fixed-length example windows.

    Accepts an in-memory array or a ``.npy`` / raw binary file (memory-
    mapped, so multi-GB corpora don't load into RAM). Window n is
    ``tokens[n*stride : n*stride + seq_len + 1]``; stride defaults to
    ``seq_len`` (disjoint windows, +1 overlap for the LM target shift).
    """

    def __init__(self, tokens, seq_len: int, *, stride: int | None = None,
                 dtype=np.int32):
        if isinstance(tokens, (str, Path)):
            path = Path(tokens)
            if path.suffix == ".npy":
                self.tokens = np.load(path, mmap_mode="r")
            else:
                self.tokens = np.memmap(path, dtype=dtype, mode="r")
        else:
            self.tokens = np.asarray(tokens)
        if self.tokens.ndim != 1:
            raise ValueError(f"token source must be 1-D, got {self.tokens.shape}")
        self.seq_len = int(seq_len)
        self.stride = int(stride or seq_len)
        window = self.seq_len + 1
        n = (len(self.tokens) - window) // self.stride + 1
        if n <= 0:
            raise ValueError(
                f"{len(self.tokens)} tokens < one window of {window}")
        self.num_examples = n

    def __len__(self) -> int:
        return self.num_examples

    def __getitem__(self, idx: int) -> np.ndarray:
        start = int(idx) * self.stride
        return np.asarray(self.tokens[start:start + self.seq_len + 1],
                          dtype=np.int32)


@dataclass
class LoaderState:
    """The complete resume state — three integers (plus the seed)."""

    seed: int
    epoch: int
    step_in_epoch: int

    def as_dict(self) -> dict:
        return {"seed": self.seed, "epoch": self.epoch,
                "step_in_epoch": self.step_in_epoch}

    @classmethod
    def from_dict(cls, d: dict) -> "LoaderState":
        return cls(seed=int(d["seed"]), epoch=int(d["epoch"]),
                   step_in_epoch=int(d["step_in_epoch"]))


class ShardedLoader:
    """Deterministic epoch-shuffled batches, sharded across processes.

    ``next_batch()`` returns this process's ``[local_batch, seq_len+1]``
    int32 slice of the global batch; :meth:`place` puts it on the mesh with
    the dp/sp sharding so jit sees one global array (multi-host: every
    process places its own shard, jax stitches them).
    """

    def __init__(self, source: TokenSource, global_batch: int, *,
                 seed: int = 0, shuffle: bool = True,
                 process_index: int | None = None,
                 process_count: int | None = None):
        import jax

        from lambdipy_tpu.parallel.distributed import process_batch_slice

        self.source = source
        self.global_batch = int(global_batch)
        self.shuffle = shuffle
        self._pc = process_count if process_count is not None else jax.process_count()
        self._pi = process_index if process_index is not None else jax.process_index()
        # the single source of truth for multi-host slicing
        self.local_batch, self._offset = process_batch_slice(
            self.global_batch, process_index=self._pi, process_count=self._pc)
        if len(source) < self.global_batch:
            raise ValueError(
                f"{len(source)} examples < one global batch of {global_batch}")
        self.state = LoaderState(seed=int(seed), epoch=0, step_in_epoch=0)
        self._perm_epoch: int | None = None
        self._perm: np.ndarray | None = None

    @property
    def steps_per_epoch(self) -> int:
        return len(self.source) // self.global_batch  # partial tail dropped

    def _permutation(self, epoch: int) -> np.ndarray:
        if self._perm_epoch != epoch:
            if self.shuffle:
                rng = np.random.default_rng((self.state.seed, epoch))
                self._perm = rng.permutation(len(self.source))
            else:
                self._perm = np.arange(len(self.source))
            self._perm_epoch = epoch
        return self._perm

    def next_batch(self) -> np.ndarray:
        """This process's shard of the next global batch (advances state)."""
        st = self.state
        if st.step_in_epoch >= self.steps_per_epoch:
            st.epoch += 1
            st.step_in_epoch = 0
        perm = self._permutation(st.epoch)
        base = st.step_in_epoch * self.global_batch + self._offset
        idxs = perm[base:base + self.local_batch]
        st.step_in_epoch += 1
        return np.stack([self.source[i] for i in idxs])

    def place(self, batch: np.ndarray, mesh, batch_sharding=None):
        """Device-put a host shard as (its slice of) the global sharded
        batch. With an explicit ``batch_sharding`` (from
        sharded_train_step) multi-host assembly goes through
        ``make_array_from_process_local_data``; without one it falls back
        to the dp/sp spec."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from lambdipy_tpu.parallel.sharding import _filter_spec

        if batch_sharding is None:
            # shard sequence over sp only when the (seq_len+1) window
            # divides evenly; otherwise keep it replicated on that axis
            sp_ok = (batch.ndim > 1 and "sp" in mesh.axis_names
                     and batch.shape[1] % mesh.shape["sp"] == 0)
            spec = P("dp", "sp") if sp_ok else P("dp")
            batch_sharding = NamedSharding(
                mesh, _filter_spec(spec, mesh, batch.ndim))
        if self._pc == 1:
            return jax.device_put(batch, batch_sharding)
        global_shape = (batch.shape[0] * self._pc,) + batch.shape[1:]
        return jax.make_array_from_process_local_data(
            batch_sharding, batch, global_shape)

    # -- resume -------------------------------------------------------------

    def state_dict(self) -> dict:
        return self.state.as_dict()

    def restore(self, state: dict) -> None:
        self.state = LoaderState.from_dict(state)
        self._perm_epoch = None  # force re-derivation from (seed, epoch)
