"""AOT store: serialized executables / StableHLO shipped in the bundle
(runtime/aot.py). The contract under test: miss -> plain jit + artifacts
written; hit -> identical numerics without re-tracing; any corruption or
environment mismatch -> silent fallback to jit."""

import json
from types import SimpleNamespace

import jax.numpy as jnp
import numpy as np
import pytest

from lambdipy_tpu.models import registry
from lambdipy_tpu.runtime.aot import AotStore, cached_jit


@pytest.fixture()
def tiny_model():
    adapter = registry.get("resnet50-tiny").build(dtype="float32")
    params = adapter.init_params(seed=0, batch_size=1)
    x = adapter.example_batch(1)[0]
    return adapter, params, x


def _ctx(tmp_path):
    return SimpleNamespace(bundle_dir=tmp_path)


def test_miss_jits_and_writes_artifacts(tmp_path, tiny_model):
    adapter, params, x = tiny_model
    fn, src = cached_jit(_ctx(tmp_path), "forward", adapter.forward, (params, x))
    assert src == "jit"
    out = np.asarray(fn(params, x))
    aot_dir = tmp_path / "aot"
    metas = list(aot_dir.glob("forward.*.json"))
    assert metas, "miss should write AOT artifacts for the next boot"
    meta = json.loads(metas[0].read_text())
    assert "hlo" in meta["tiers"]
    assert np.all(np.isfinite(out))


def test_hit_matches_jit_numerics(tmp_path, tiny_model):
    adapter, params, x = tiny_model
    ctx = _ctx(tmp_path)
    fn0, src0 = cached_jit(ctx, "forward", adapter.forward, (params, x))
    expected = np.asarray(fn0(params, x))

    fn1, src1 = cached_jit(ctx, "forward", adapter.forward, (params, x))
    assert src1 in ("exec", "hlo"), f"second boot should hit AOT, got {src1}"
    got = np.asarray(fn1(params, x))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)


def test_env_mismatch_falls_back_to_jit(tmp_path, tiny_model):
    adapter, params, x = tiny_model
    ctx = _ctx(tmp_path)
    cached_jit(ctx, "forward", adapter.forward, (params, x))
    meta_path = next((tmp_path / "aot").glob("forward.*.json"))
    meta = json.loads(meta_path.read_text())
    meta["jaxlib"] = "0.0.0-other"
    meta_path.write_text(json.dumps(meta))

    store = AotStore(tmp_path)
    assert store.load("forward") is None


def test_corrupt_artifact_falls_back(tmp_path, tiny_model):
    adapter, params, x = tiny_model
    ctx = _ctx(tmp_path)
    cached_jit(ctx, "forward", adapter.forward, (params, x))
    for f in (tmp_path / "aot").glob("forward.*"):
        if f.suffix in (".hlo", ".exec"):
            f.write_bytes(b"garbage")
    fn, src = cached_jit(ctx, "forward", adapter.forward, (params, x))
    assert src == "jit"
    assert np.all(np.isfinite(np.asarray(fn(params, x))))


def test_aot_hit_still_serves_other_batch_sizes(tmp_path):
    """An AOT artifact is shape-specialized to the spec's example batch;
    requests with a different batch must still work (plain-jit fallback in
    handlers._aot_or_jit), not 500."""
    from lambdipy_tpu.runtime import handlers

    spec = {"model": "resnet50-tiny", "dtype": "float32", "batch_size": 1}
    ctx = SimpleNamespace(bundle_dir=tmp_path, manifest={}, params_dir=None,
                          spec=spec)
    handlers.image_classify_handler(spec, ctx)  # miss: writes artifacts
    h = handlers.image_classify_handler(spec, ctx)
    assert h.meta["aot"] in ("exec", "hlo")

    adapter = registry.get("resnet50-tiny").build(dtype="float32")
    batch2 = np.asarray(adapter.example_batch(2)[0], dtype=np.float32)
    out = h.invoke({"image": batch2.tolist()})
    assert out["ok"] and len(out["top1"]) == 2
    out1 = h.invoke({"random": True})
    assert out1["ok"] and len(out1["top1"]) == 1


def test_different_dtype_entry_points_coexist(tmp_path):
    adapter = registry.get("resnet50-tiny").build(dtype="bfloat16")
    params = adapter.init_params(seed=0, batch_size=1)
    x = adapter.example_batch(1)[0]
    ctx = _ctx(tmp_path)
    store = AotStore(tmp_path)
    store.save("fwd_bf16", adapter.forward, (params, x))
    hit = store.load("fwd_bf16", (params, x))
    assert hit is not None
    fn, tier = hit
    out = np.asarray(fn(params, x), dtype=np.float32)
    assert out.dtype == np.float32 and np.all(np.isfinite(out))
    assert jnp.asarray(x).dtype == jnp.bfloat16


def test_meshed_payload_aot_hlo_roundtrip(tmp_path, cpu_devices):
    """A meshed payload saves/loads the StableHLO tier keyed by (topology,
    mesh shape): the second boot on the same mesh skips tracing (VERDICT
    r2 missing #4 — meshed bundles previously re-traced every boot)."""
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
    from lambdipy_tpu.parallel.sharding import shard_params

    adapter = registry.get("bert-tiny").build(dtype="float32")
    params = adapter.init_params(seed=0, batch_size=1)
    ids, mask = adapter.example_batch(1)
    mesh = make_mesh({"tp": 2}, devices=cpu_devices[:2])
    with use_mesh(mesh):
        params = shard_params(params, mesh, adapter.tp_rules)
    ctx = _ctx(tmp_path)

    fn0, src0 = cached_jit(ctx, "forward", adapter.forward, (params, ids, mask),
                           mesh=mesh)
    assert src0 == "jit"
    with use_mesh(mesh):
        expected = np.asarray(fn0(params, ids, mask))
    meta = json.loads(next((tmp_path / "aot").glob("forward.*.tp2.json")).read_text())
    assert meta["mesh"] == "tp2" and meta["tiers"] == ["hlo"]  # no exec tier

    fn1, src1 = cached_jit(ctx, "forward", adapter.forward, (params, ids, mask),
                           mesh=mesh)
    assert src1 == "hlo", "second meshed boot should hit the StableHLO tier"
    with use_mesh(mesh):
        got = np.asarray(fn1(params, ids, mask))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)


def test_meshed_aot_rejects_other_mesh_shape(tmp_path, cpu_devices):
    """Artifacts saved for one mesh shape never load for another."""
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
    from lambdipy_tpu.parallel.sharding import shard_params

    adapter = registry.get("bert-tiny").build(dtype="float32")
    params = adapter.init_params(seed=0, batch_size=1)
    ids, mask = adapter.example_batch(1)
    ctx = _ctx(tmp_path)
    tp2 = make_mesh({"tp": 2}, devices=cpu_devices[:2])
    with use_mesh(tp2):
        p2 = shard_params(params, tp2, adapter.tp_rules)
    cached_jit(ctx, "forward", adapter.forward, (p2, ids, mask), mesh=tp2)

    tp4 = make_mesh({"tp": 4}, devices=cpu_devices[:4])
    store = AotStore(tmp_path, mesh=tp4)
    assert store.load("forward") is None


@pytest.mark.slow  # two boots + dual-tier exports on one core
def test_serving_programs_ride_aot_store(tmp_path):
    """The LlamaServer decode/stream programs snapshot into the bundle's
    AOT exec tier at warmup and a SECOND boot loads them instead of
    compiling (the 8B cold start's dominant cost: ~70 s remote compile
    per program)."""
    from tests.test_runtime import make_model_bundle
    from lambdipy_tpu.runtime.loader import load_bundle

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "4", "serve_aot": "1"})
    # assembly does not warm; the FIRST boot compiles + saves.
    r1 = load_bundle(bundle, warmup=True)
    assert r1.warmup_result["ok"]
    srv_artifacts = sorted(p.name for p in (bundle / "aot").glob("srv-*"))
    # the exec tier self-tests at save time and is pruned on platforms
    # where a single-device executable cannot load back (this 8-virtual-
    # device CPU env); the hlo tier must always land
    assert any(n.endswith(".hlo") for n in srv_artifacts), srv_artifacts
    s1 = r1.state.stats()
    assert s1["aot_hits"] == 0, s1

    r2 = load_bundle(bundle, warmup=True)
    s2 = r2.state.stats()
    # fused decode + stream pair (+ any batcher programs) all hit
    assert s2["aot_hits"] >= 2, s2
    # the cold-start overlap's observable (VERDICT r5 #5): the second
    # boot's preload thread deserialized the saved serving programs
    # CONCURRENTLY with the params load, and reports it in the stats the
    # 8B cold-start measurement reads (measure_8b --cold-start)
    assert s2.get("aot_preload", {}).get("programs", 0) >= 1, s2
    assert s2["aot_preload"]["seconds"] is not None
    out = r2.handler.invoke(r2.state, {"tokens": [1, 2, 3]})
    ref = r1.handler.invoke(r1.state, {"tokens": [1, 2, 3]})
    assert out["ok"] and out["tokens"] == ref["tokens"]


@pytest.mark.slow  # dual-tier exports on one core
def test_partial_stream_pair_saves_and_loads(tmp_path):
    """The continuous engine's B-slot ('stream', ...) pair only ever runs
    its SEG half; the pair must still snapshot that half and a later
    boot must load it while jit-building the never-saved prefill half
    (ADVICE r4: all-or-nothing pairs left the most expensive continuous
    compile unsnapshotted)."""
    from lambdipy_tpu.models.llama import LlamaServer
    from lambdipy_tpu.runtime.continuous import ContinuousBatcher

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    store = AotStore(tmp_path, gate_ms=60000)
    server = LlamaServer(adapter.module, params, aot=store)
    cb = ContinuousBatcher(server, slots=4, segment=4)
    ref = cb.generate([1, 2, 3], max_new_tokens=8)
    assert server.aot_save_all() > 0
    key = ("stream", 4, server.min_bucket, cb.cache_len, 4)
    assert key in server.buckets
    from lambdipy_tpu.models.llama import LlamaServer as LS

    name = LS._aot_name(key)
    assert store.has(f"{name}-p1"), "seg half must be snapshotted"
    assert not store.has(f"{name}-p0"), "prefill half never ran"

    server2 = LlamaServer(adapter.module, params,
                          aot=AotStore(tmp_path, gate_ms=60000))
    cb2 = ContinuousBatcher(server2, slots=4, segment=4)
    out = cb2.generate([1, 2, 3], max_new_tokens=8)
    np.testing.assert_array_equal(out, ref)
    assert server2.aot_hits >= 1, "second boot must load the seg half"


@pytest.mark.slow  # dual-tier exports on one core
def test_preload_overlaps_weight_load(tmp_path):
    """Cold-start overlap (VERDICT r5 #5): AotStore.preload deserializes
    serving programs WITHOUT operands (so a boot can run it while the
    weights upload), and load() then consumes the preloaded callable —
    same outputs, counted as AOT hits."""
    from lambdipy_tpu.models.llama import LlamaServer

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    store = AotStore(tmp_path, gate_ms=60000)
    server = LlamaServer(adapter.module, params, aot=store)
    ref = server.generate([1, 2, 3], max_new_tokens=8)
    assert server.aot_save_all() > 0

    store2 = AotStore(tmp_path, gate_ms=60000)
    pre = store2.preload()          # no params anywhere in sight
    assert pre["names"], "saved serving programs must preload"
    assert store2._preloaded
    server2 = LlamaServer(adapter.module, params, aot=store2)
    out = server2.generate([1, 2, 3], max_new_tokens=8)
    np.testing.assert_array_equal(out, ref)
    assert server2.aot_hits >= 1
    # the consumed names came out of the preload dict
    assert len(store2._preloaded) < len(pre["names"])


def test_preload_skips_env_mismatch(tmp_path, tiny_model):
    """preload never hands back an artifact from another environment."""
    import json as _json

    adapter, params, x = tiny_model
    ctx = _ctx(tmp_path)
    cached_jit(ctx, "srv-fake", adapter.forward, (params, x))
    meta_path = next((tmp_path / "aot").glob("srv-fake.*.json"))
    meta = _json.loads(meta_path.read_text())
    meta["jaxlib"] = "0.0.0-other"
    meta_path.write_text(_json.dumps(meta))
    store = AotStore(tmp_path)
    pre = store.preload()
    assert pre["names"] == []


def test_preload_skips_stale_generation(tmp_path, tiny_model):
    """A previous generation's orphaned serving artifacts must not be
    device-loaded by preload (they'd never be consumed)."""
    from lambdipy_tpu.models.llama import LlamaServer

    adapter, params, x = tiny_model
    ctx = _ctx(tmp_path)
    # a fake stale-generation artifact, valid for this environment
    cached_jit(ctx, "srv-g1-dec-1-16-16", adapter.forward, (params, x))
    store = AotStore(tmp_path)
    pre = store.preload(prefix=LlamaServer.aot_prefix())
    assert pre["names"] == []
    # the generic prefix still sees it (the stale skip is the caller's
    # generation-scoped prefix, not a hidden filter)
    assert AotStore(tmp_path).preload()["names"] == ["srv-g1-dec-1-16-16"]
