"""Ring attention (sequence parallel) vs full attention on the 8-device
virtual mesh (SURVEY.md §5.4 pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lambdipy_tpu.ops.attention import mha_reference
from lambdipy_tpu.parallel.mesh import make_mesh
from lambdipy_tpu.parallel.ring import ring_attention


def _rand(shape, seed):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape), jnp.float32)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(cpu_devices, causal):
    b, s, h, d = 2, 64, 2, 16  # s shards 8 ways -> 8 tokens per device
    q, k, v = (_rand((b, s, h, d), i) for i in range(3))
    ref = mha_reference(q, k, v, causal=causal)
    mesh = make_mesh({"sp": 8})
    with mesh:
        out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_gqa(cpu_devices):
    b, s, h, kvh, d = 1, 32, 4, 2, 16
    q = _rand((b, s, h, d), 0)
    k = _rand((b, s, kvh, d), 1)
    v = _rand((b, s, kvh, d), 2)
    ref = mha_reference(q, k, v, causal=True)
    mesh = make_mesh({"sp": 8})
    with mesh:
        out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow  # heavyweight composition parity (tier-1 wall budget); fast siblings cover the mechanism
def test_ring_attention_composes_with_dp(cpu_devices):
    b, s, h, d = 4, 16, 2, 8
    q, k, v = (_rand((b, s, h, d), i) for i in range(3))
    ref = mha_reference(q, k, v, causal=True)
    mesh = make_mesh({"dp": 2, "sp": 4})
    from jax.sharding import NamedSharding, PartitionSpec as P

    with mesh:
        qs = jax.device_put(q, NamedSharding(mesh, P("dp", "sp")))
        ks = jax.device_put(k, NamedSharding(mesh, P("dp", "sp")))
        vs = jax.device_put(v, NamedSharding(mesh, P("dp", "sp")))
        out = ring_attention(qs, ks, vs, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow  # heavyweight parity; subsystem keeps a fast test
def test_llama_ring_backend_matches_dense(cpu_devices):
    """Llama prefill with attn_backend='ring' on an sp mesh must match the
    dense single-device forward — the long-context serving path."""
    import dataclasses

    from lambdipy_tpu.models.llama import LLAMA_TINY, LlamaModel
    from lambdipy_tpu.parallel.mesh import use_mesh

    cfg_dense = dataclasses.replace(LLAMA_TINY, max_len=64)
    cfg_ring = dataclasses.replace(cfg_dense, attn_backend="ring")
    tokens = jnp.asarray(np.random.default_rng(3).integers(0, 500, (1, 32)),
                         jnp.int32)
    model_d = LlamaModel(cfg_dense)
    params = model_d.init(jax.random.PRNGKey(0), tokens)
    ref, _ = model_d.apply(params, tokens)

    model_r = LlamaModel(cfg_ring)
    mesh = make_mesh({"sp": 8})
    with use_mesh(mesh):
        out, _ = model_r.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=5e-4, atol=5e-4)


def test_llama_flash_backend_matches_dense():
    import dataclasses

    from lambdipy_tpu.models.llama import LLAMA_TINY, LlamaModel

    cfg_dense = dataclasses.replace(LLAMA_TINY, max_len=256)
    cfg_flash = dataclasses.replace(cfg_dense, attn_backend="flash")
    tokens = jnp.asarray(np.random.default_rng(4).integers(0, 500, (1, 128)),
                         jnp.int32)
    model_d = LlamaModel(cfg_dense)
    params = model_d.init(jax.random.PRNGKey(0), tokens)
    ref, _ = model_d.apply(params, tokens)
    out, _ = LlamaModel(cfg_flash).apply(params, tokens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=5e-4, atol=5e-4)


def test_ring_attention_respects_padding_mask(cpu_devices):
    """A padded batch attends identically under ring and dense backends —
    the kv mask rides the ring with its k/v block (VERDICT r2 weak #8)."""
    import numpy as np
    from lambdipy_tpu.models.llama import _attend
    from lambdipy_tpu.parallel.mesh import make_mesh
    from lambdipy_tpu.parallel.ring import ring_attention

    rng = np.random.default_rng(0)
    b, s, h, d = 2, 16, 4, 8
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    lengths = np.array([11, 7])
    mask = jnp.asarray(np.arange(s)[None, :] < lengths[:, None])

    causal = jnp.tril(jnp.ones((s, s), dtype=jnp.bool_))
    dense = _attend(q, k, v, mask[:, None, :] & causal[None, :, :])

    mesh = make_mesh({"sp": 4}, devices=cpu_devices[:4])
    ring = ring_attention(q, k, v, mesh, causal=True, kv_mask=mask)
    # compare only valid query rows (pad-row outputs are garbage by design)
    for row, n in enumerate(lengths):
        np.testing.assert_allclose(np.asarray(dense)[row, :n],
                                   np.asarray(ring)[row, :n],
                                   rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------
# sequence-parallel DECODE (parallel/spdecode.py): the long-context decode
# path pairing with ring-attention prefill


def test_sp_decode_step_matches_dense_reference(cpu_devices):
    """One decode step over an sp-sharded cache == write-then-masked
    dense attention, for ragged per-row positions, including the
    updated cache blocks."""
    import jax
    import jax.numpy as jnp

    from lambdipy_tpu.models.llama import _attend
    from lambdipy_tpu.parallel.mesh import make_mesh
    from lambdipy_tpu.parallel.spdecode import sp_decode_step

    rng = np.random.default_rng(0)
    b, T, kvh, d, h = 3, 32, 2, 16, 8
    mesh = make_mesh({"sp": 4}, devices=cpu_devices[:4])
    q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.float32)
    kn = jnp.asarray(rng.standard_normal((b, 1, kvh, d)), jnp.float32)
    vn = jnp.asarray(rng.standard_normal((b, 1, kvh, d)), jnp.float32)
    ck = jnp.asarray(rng.standard_normal((b, T, kvh, d)), jnp.float32)
    cv = jnp.asarray(rng.standard_normal((b, T, kvh, d)), jnp.float32)
    idx = jnp.asarray([5, 17, 31], jnp.int32)
    with mesh:
        out, ncache = jax.jit(
            lambda *a: sp_decode_step(*a, mesh=mesh))(
            q, {"k": kn, "v": vn}, {"k": ck, "v": cv}, idx)
    rows = jnp.arange(b)
    rk = ck.at[rows, idx].set(kn[:, 0])
    rv = cv.at[rows, idx].set(vn[:, 0])
    valid = jnp.arange(T)[None, None, :] <= idx[:, None, None]
    ref = _attend(q, rk, rv, jnp.broadcast_to(valid, (b, 1, T)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(ncache["k"]), np.asarray(rk))
    np.testing.assert_array_equal(np.asarray(ncache["v"]), np.asarray(rv))


@pytest.mark.slow  # three meshed serves (~31 s); the sp_decode_step unit
# parity and the engine-over-sp test keep fast coverage
def test_sp_serve_decode_matches_unsharded(cpu_devices, count_sp_decode):
    """The full serving path with attn_backend='ring' over an sp mesh —
    ring prefill + sequence-sharded flash-decoding steps — produces the
    dense unsharded server's greedy tokens, rectangular and ragged,
    and composes with tp. The sp path is asserted to actually TRACE
    (code-review r5: the builder silently dropped extra and this test
    was dense-vs-dense)."""
    import jax

    from lambdipy_tpu.models import registry
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh
    from lambdipy_tpu.parallel.sharding import shard_params

    calls = count_sp_decode

    adapter = registry.get("llama-tiny").build()
    params = adapter.init_params(seed=0)
    ref_server = adapter.make_server(params)
    ref = ref_server.generate([5, 6, 7, 8], max_new_tokens=8)
    ref_rag = ref_server.generate([[5, 6, 7, 8], [1, 2]],
                                  max_new_tokens=8)
    assert calls["n"] == 0  # the dense reference never touches sp

    ring = registry.get("llama-tiny").build(
        extra={"attn_backend": "ring"})
    assert ring.config.attn_backend == "ring"
    mesh = make_mesh({"sp": 2}, devices=cpu_devices[:2])
    with use_mesh(mesh):
        sp_params = shard_params(params, mesh, ring.tp_rules)
    server = ring.make_server(sp_params, mesh=mesh)
    np.testing.assert_array_equal(
        server.generate([5, 6, 7, 8], max_new_tokens=8), ref)
    assert calls["n"] > 0, "sp decode path never traced"
    np.testing.assert_array_equal(
        server.generate([[5, 6, 7, 8], [1, 2]], max_new_tokens=8),
        ref_rag)

    mesh2 = make_mesh({"sp": 2, "tp": 2}, devices=cpu_devices[:4])
    with use_mesh(mesh2):
        p2 = shard_params(params, mesh2, ring.tp_rules)
    server2 = ring.make_server(p2, mesh=mesh2)
    np.testing.assert_array_equal(
        server2.generate([5, 6, 7, 8], max_new_tokens=8), ref)


def test_sp_decode_strongly_negative_logits_with_empty_shards(cpu_devices):
    """Early decode (only position 0 valid -> most shards empty) with a
    strongly negative max logit: the combine must pmax raw maxima with
    the -inf sentinel, not the zero-filled safe maxima — otherwise the
    rescale underflows and the output collapses to 0/garbage."""
    import jax
    import jax.numpy as jnp

    from lambdipy_tpu.models.llama import _attend
    from lambdipy_tpu.parallel.mesh import make_mesh
    from lambdipy_tpu.parallel.spdecode import sp_decode_step

    b, T, kvh, d, h = 1, 8, 1, 4, 2
    mesh = make_mesh({"sp": 4}, devices=cpu_devices[:4])
    q = jnp.zeros((b, 1, h, d), jnp.float32).at[..., 0].set(100.0)
    ck = jnp.zeros((b, T, kvh, d), jnp.float32)
    cv = jnp.asarray(
        np.arange(b * T * kvh * d, dtype=np.float32).reshape(
            b, T, kvh, d))
    # THIS STEP's key (written at pos 0, the only valid position) is
    # strongly anti-aligned: the one real logit is ~ -5000, far below
    # the 0.0 the zero-filled empty-shard maxima would clamp pmax to
    kn = jnp.zeros((b, 1, kvh, d), jnp.float32).at[..., 0].set(-100.0)
    vn = jnp.full((b, 1, kvh, d), 7.0, jnp.float32)
    idx = jnp.asarray([0], jnp.int32)  # writes pos 0; only pos 0 valid
    with mesh:
        out, _ = jax.jit(
            lambda *a: sp_decode_step(*a, mesh=mesh))(
            q, {"k": kn, "v": vn}, {"k": ck, "v": cv}, idx)
    rows = jnp.arange(b)
    rk = ck.at[rows, idx].set(kn[:, 0])
    rv = cv.at[rows, idx].set(vn[:, 0])
    valid = jnp.arange(T)[None, None, :] <= idx[:, None, None]
    ref = _attend(q, rk, rv, jnp.broadcast_to(valid, (b, 1, T)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow  # heavyweight composition parity (tier-1 wall budget); fast siblings cover the mechanism
def test_sp_decode_int8_kv_matches_replicated_int8(cpu_devices, count_sp_decode):
    """kv_quant='int8' composes with sp decode: the int8 cache leaves
    shard over sp, the sp path traces, and serve outputs match the
    REPLICATED int8-KV server (same quantization, different reduction
    layout)."""
    import dataclasses

    import jax

    from lambdipy_tpu.models.llama import (LLAMA_TINY, LlamaModel,
                                           LlamaServer)
    from lambdipy_tpu.parallel.mesh import make_mesh, use_mesh

    calls = count_sp_decode

    cfg = dataclasses.replace(LLAMA_TINY, kv_quant="int8")
    module = LlamaModel(cfg)
    tokens = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)
    ref = LlamaServer(module, params).generate([5, 6, 7, 8],
                                               max_new_tokens=8)
    assert calls["n"] == 0

    ring_cfg = dataclasses.replace(cfg, attn_backend="ring")
    mesh = make_mesh({"sp": 2}, devices=cpu_devices[:2])
    # params replicated; the server enters the mesh itself
    server = LlamaServer(LlamaModel(ring_cfg), params, mesh=mesh)
    out = server.generate([5, 6, 7, 8], max_new_tokens=8)
    assert calls["n"] > 0, "int8 sp decode never traced"
    np.testing.assert_array_equal(out, ref)
