"""The one place LAMBDIPY_PLATFORM is honored.

JAX_PLATFORMS=cpu at interpreter start hangs this image's axon
sitecustomize (measured; see tests/conftest.py), so every entry point —
CLI, serve runtime, warm subprocess — switches the platform *after*
startup via jax.config, before any backend initializes. All three call
this helper so the behavior (and the warning on failure) stays uniform.
"""

from __future__ import annotations

import os

from lambdipy_tpu.utils.logs import get_logger

log = get_logger("lambdipy.platform")


def apply_platform_override() -> str | None:
    """Switch jax to the platform named by LAMBDIPY_PLATFORM, if set.
    Returns the platform applied, or None. Failure is a warning, not an
    error: the process continues on whatever platform jax picked."""
    platform = os.environ.get("LAMBDIPY_PLATFORM")
    if not platform:
        return None
    try:
        import jax

        jax.config.update("jax_platforms", platform)
        return platform
    except Exception as e:
        log.warning("platform override %r failed: %s", platform, e)
        return None


def prefer_cpu_backend() -> bool:
    """Keep this process off the accelerator: switch jax to CPU if the
    backend hasn't initialized yet (no-op otherwise, returns False).

    Used by build-time steps whose math doesn't need the device (param
    init, weight conversion): on this image the TPU tunnel is effectively
    single-client (measured: a build process holding it starves the warm
    subprocess, which is the step that actually must run on the device to
    populate the bundle's compile cache)."""
    if os.environ.get("LAMBDIPY_PLATFORM"):
        return False  # explicit override wins
    try:
        import jax
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            return False
        jax.config.update("jax_platforms", "cpu")
        return True
    except Exception as e:
        log.warning("cpu preference failed: %s", e)
        return False
