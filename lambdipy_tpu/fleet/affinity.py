"""Prefix-affinity keys and replica selection for the fleet router.

The radix prefix cache (runtime/prefixstore.py) is per-replica: spraying
shared-prefix traffic round-robin across N replicas dilutes every
replica's hit rate to ~1/N of what one process would see. The router
instead hashes each request's LEADING TOKEN BLOCKS — the same fixed
block width the radix tree is keyed by — so all prompts that would
longest-prefix-match each other land on the replica that already holds
their KV.

Two pieces:

- :func:`prefix_key` turns a request body (internal ``/invoke`` shape or
  OpenAI ``/v1/completions`` shape) into a stable bytes key over the
  prompt's leading whole blocks. Prompts shorter than one block key on
  the whole prompt (the radix store cannot cache them, but identical
  short prompts still co-locate); string prompts key on a leading
  character window sized ~4 chars/token so tokenizer-equal prefixes
  agree without tokenizing in the router.
- :func:`pick_replica` is RENDEZVOUS (highest-random-weight) hashing:
  each (key, replica) pair scores independently, so ejecting or draining
  one replica remaps ONLY the keys that were on it — the rest of the
  fleet keeps its warm caches. A plain modulo ring would reshuffle
  nearly every key on any membership change.
"""

from __future__ import annotations

import hashlib
import json

# keep in sync with runtime/prefixstore.py PrefixStore's default block;
# the router's --block flag overrides it to match non-default bundles
DEFAULT_BLOCK = 32

# the key window: only the FIRST key_blocks whole blocks feed the hash.
# Keying on every whole block would give prompts that share a long
# prefix but differ in later blocks (512-token system prompt + distinct
# 100-token user turns) different keys — scattering exactly the traffic
# affinity exists to co-locate. Eight 32-token blocks ≈ a system-prompt
# of shared context; suffix divergence past it cannot split the key.
DEFAULT_KEY_BLOCKS = 8

# string prompts: ~4 characters per BPE token is the usual planning
# number; exactness is irrelevant — both sides of a shared prefix just
# need to produce the SAME key
CHARS_PER_TOKEN = 4


def _flat_int_row(val) -> list | None:
    """First flat int row of a tokens/prompt field, else None."""
    if isinstance(val, (list, tuple)) and val:
        if isinstance(val[0], (list, tuple)):  # batched rows: key on row 0
            val = val[0]
        if isinstance(val, (list, tuple)) and val and \
                all(isinstance(t, int) for t in val):
            return list(val)
    return None


def prefix_key(request: dict, *, block: int = DEFAULT_BLOCK,
               key_blocks: int = DEFAULT_KEY_BLOCKS) -> bytes | None:
    """Stable affinity key from a request's prompt prefix — the leading
    ``min(whole blocks, key_blocks)`` token blocks — or None when the
    body carries nothing routable (the router then falls back to
    least-outstanding)."""
    if not isinstance(request, dict):
        return None
    block = max(1, int(block))
    key_blocks = max(1, int(key_blocks))
    # client-supplied explicit prefix is part of the effective prompt:
    # requests sharing it must co-locate with requests that inline it
    head: list = []
    pref = _flat_int_row(request.get("prefix"))
    if pref:
        # bounded like every other key ingredient: divergence past the
        # key window must not split keys (or bloat them)
        head.extend(pref[: key_blocks * block])
    toks = _flat_int_row(request.get("tokens"))
    if toks is None:
        toks = _flat_int_row(request.get("prompt"))
    if toks is not None:
        seq = head + toks
        n = min(len(seq) // block, key_blocks) * block
        return json.dumps(seq[:n] if n else seq).encode()
    text = request.get("text")
    if text is None and isinstance(request.get("prompt"), str):
        text = request["prompt"]
    if isinstance(text, str) and text:
        n_chars = block * CHARS_PER_TOKEN
        n = min(len(text) // n_chars, key_blocks) * n_chars
        if head:
            # an explicit token prefix IS the reusable KV: requests
            # sharing it must co-locate even with string suffixes, so
            # the key is the prefix plus the text's WHOLE char-blocks
            # (possibly none — short differing suffixes collapse)
            return json.dumps(head).encode() + b"|" + text[:n].encode()
        return text[: n if n else len(text)].encode()
    if head:
        # prefix-only request: same key shape as prefix + sub-block
        # text, so it co-locates with those too
        return json.dumps(head).encode() + b"|"
    return None


def warm_prompt(request: dict, *, block: int = DEFAULT_BLOCK,
                key_blocks: int = DEFAULT_KEY_BLOCKS):
    """The request's leading whole-block prompt head as a REPLAYABLE
    prompt (token list or string) — what an affinity-aware cache warm
    should prefill on a fresh replica so the radix store holds the
    fleet's hot prefixes again. None when the prompt has no whole block
    (nothing the radix store could cache) or when the shape cannot be
    replayed standalone (mixed explicit-prefix + string suffix)."""
    if not isinstance(request, dict):
        return None
    block = max(1, int(block))
    key_blocks = max(1, int(key_blocks))
    head: list = []
    pref = _flat_int_row(request.get("prefix"))
    if pref:
        head.extend(pref[: key_blocks * block])
    toks = _flat_int_row(request.get("tokens"))
    if toks is None:
        toks = _flat_int_row(request.get("prompt"))
    if toks is not None:
        seq = head + toks
        n = min(len(seq) // block, key_blocks) * block
        return seq[:n] if n else None
    text = request.get("text")
    if text is None and isinstance(request.get("prompt"), str):
        text = request["prompt"]
    if isinstance(text, str) and text and not head:
        n_chars = block * CHARS_PER_TOKEN
        n = min(len(text) // n_chars, key_blocks) * n_chars
        return text[:n] if n else None
    if head:
        n = min(len(head) // block, key_blocks) * block
        return head[:n] if n else None
    return None


# the phase-split ship moves KV, not affinity: the 8-block affinity
# window is sized for rendezvous key stability, but a ship clipped to
# it would leave every token past block 8 as a local re-prefill on the
# decode replica — exactly the work the prefill class exists to absorb,
# and (pipelined) exactly the transfer the chunk stream hides under the
# prefill. 64 blocks (2-4k tokens at the default widths) covers the
# window-clamped head of everything this stack serves; the export leg
# clamps to the replica's window server-side either way. The ship-dedup
# key stays the 8-block affinity key — two prompts sharing the window
# but diverging later hit the dedup entry, and the import-miss PROBE
# (which checks the full head) pulls the divergent tail back.
SHIP_KEY_BLOCKS = 64


def ship_prompt(request: dict, *, block: int = DEFAULT_BLOCK,
                key_blocks: int = DEFAULT_KEY_BLOCKS) -> list | None:
    """:func:`warm_prompt` restricted to TOKEN heads — what the
    disaggregated router can actually SHIP: the KV wire frame names
    token ids and the router never tokenizes, so a string head (which
    warm_prompt happily replays as a warm request) cannot key an
    export. None = serve mixed-mode, no ship."""
    head = warm_prompt(request, block=block, key_blocks=key_blocks)
    return head if isinstance(head, list) else None


# sessions re-ship their whole conversation head on failover, so the
# session head is far wider than the affinity key window — but BOUNDED:
# the router keeps one head per live session, and an unbounded head
# would grow router memory with context length. 256 blocks (8-16k
# tokens at the default widths) covers every context window this stack
# serves; a longer conversation's tail simply re-prefills on the new
# home after a failover — the documented degraded path, never a loss.
# (The export leg also clamps to the replica's window server-side.)
SESSION_KEY_BLOCKS = 256


def session_key(session_id) -> bytes:
    """Rendezvous key for session FAILOVER re-targeting: where an open
    session lands when its home replica dies or drains. Deliberately
    namespaced away from prefix keys (two sessions sharing a system
    prompt should spread on failover, not pile onto one survivor).

    NOT for first-turn/unknown-session placement: a session id the
    router has never seen (first turn, or any turn after a router
    restart) must fall back to NORMAL prefix affinity over the request
    body — hashing the bare session id would scatter the first
    post-restart turn away from the replica whose radix cache already
    holds the conversation from before the restart."""
    return b"sess\x00" + str(session_id).encode()


def pick_replica(key: bytes, names) -> str | None:
    """Rendezvous-hash ``key`` onto one of ``names`` (any iterable of
    replica names). Deterministic; removing a name never remaps keys
    held by the others."""
    best_name, best_score = None, b""
    for name in names:
        score = hashlib.blake2b(key + b"\x00" + str(name).encode(),
                                digest_size=8).digest()
        if best_name is None or score > best_score:
            best_name, best_score = name, score
    return best_name
