"""Elastic fleet control loop: published signals -> safe actuators.

:class:`FleetController` closes the loop ROADMAP direction 2 left
open. Every tick it scrapes the fleet's own ``/metrics`` surface (the
router aggregate — nothing here reads private state the operator
cannot see), assembles a pure :class:`~lambdipy_tpu.fleet.policy.Snapshot`,
asks :func:`~lambdipy_tpu.fleet.policy.decide` what to do, and acts
through the existing safe primitives:

====================================  ===================================
decision                              actuator
====================================  ===================================
promote / demote (class flip)         ``pool.set_role`` — transient
                                      drain + proactive session re-ship,
                                      no restart (the class is a
                                      router-side attribute)
spawn                                 the ``spawner`` callback (CLI wires
                                      it to ``pool.spawn`` with the
                                      fleet's bundle + env)
retire                                ``pool.retire`` — drain + stop one
                                      managed replica
``pipeline_depth`` / ``spec_k``       ``POST /v1/debug/knobs`` on the
                                      replica (loopback-only admin
                                      endpoint; both knobs are read
                                      per-dispatch by the engine, so a
                                      live retune is race-free)
``ship_window``                       plain attribute write on the
                                      router (read per-ship)
====================================  ===================================

The controller never invents state: hysteresis, cooldowns, and the
live-floor guard all live in the pure policy, so a recorded snapshot
sequence replays to a byte-identical decision trace (the bench's
determinism gate). In ``dry_run`` mode decisions are fully traced and
counted as INTENTS but no actuator fires — the recommended first step
before trusting the loop in a new deployment.

Applied actions are appended to :attr:`events` in the chaos nemesis's
event grammar (``@T action target [detail]``) so a soak window can
interleave controller-initiated resizes with injected faults in one
timeline and hold the zero-silent-loss bar across both.
"""

from __future__ import annotations

import threading
import time

from lambdipy_tpu.fleet.policy import (DEMOTE, MIXED, PROMOTE, RETIRE, ROUTER,
                                       SET_KNOB, SPAWN, Action, PolicyConfig,
                                       PolicyState, ReplicaView, Snapshot,
                                       decide)
from lambdipy_tpu.runtime.deploy import _http_json
from lambdipy_tpu.runtime.metrics import ControllerStats
from lambdipy_tpu.utils.logs import get_logger, log_event

log = get_logger("lambdipy.fleet.controller")

# decision_log / events are diagnosis surfaces, not history: bound them
# so a long-lived loop cannot grow without limit
_LOG_CAP = 4096


class FleetController:
    def __init__(self, router, *, config: PolicyConfig | None = None,
                 interval_s: float = 5.0, dry_run: bool = False,
                 spawner=None, knob_timeout: float = 5.0):
        self.router = router
        self.pool = router.pool
        self.config = config or PolicyConfig()
        self.state = PolicyState()
        self.stats = ControllerStats()
        self.interval_s = max(0.05, float(interval_s))
        self.dry_run = bool(dry_run)
        # spawner(role) -> replica name; must spawn AND register the
        # replica with the pool (the CLI wires pool.spawn). None means
        # the fleet cannot grow — the policy is told via can_spawn.
        self.spawner = spawner
        self.knob_timeout = float(knob_timeout)
        # nemesis-visible ledger of APPLIED actions, in the soak event
        # grammar: {"t", "action", "target", "event"}
        self.events: list[dict] = []
        # (snapshot, [rendered actions]) pairs — the bench's
        # determinism gate replays decide() over these with a fresh
        # PolicyState and diffs the rendered actions byte-for-byte
        self.decision_log: list[tuple[Snapshot, list[str]]] = []
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats.set_targets(
            slo_p99_ms=self.config.slo_p99_ms,
            slo_class=self.config.slo_class,
            hysteresis=self.config.hysteresis,
            sustain_s=self.config.sustain_s,
            live_floor=self.config.live_floor,
            interval_s=self.interval_s,
            dry_run=self.dry_run,
        )
        # the router exports fleet.controller from this registration
        router.controller = self

    # -- snapshot assembly --------------------------------------------------

    def build_snapshot(self, metrics: dict, *, t: float | None = None
                       ) -> Snapshot:
        """Assemble the policy's input from one router ``/metrics``
        scrape. Missing signals become ``None``/defaults — the policy
        skips what it cannot see rather than acting on a guess."""
        if t is None:
            t = time.monotonic() - self._t0
        fleet = metrics.get("fleet") or {}
        disagg = fleet.get("disagg") or {}
        qw = fleet.get("queue_wait") or {}
        per_replica = metrics.get("replicas") or {}
        views = []
        with self.pool._lock:
            members = [(r.name, r.role, r.routable, r.managed,
                        r.outstanding, r.state)
                       for r in self.pool.replicas.values()]
        for name, role, routable, managed, outstanding, state in \
                sorted(members):
            if state == "stopped":
                continue
            rm = per_replica.get(name) or {}
            batching = ((rm.get("handler") or {}).get("batching") or {})
            pipeline = batching.get("pipeline") or {}
            depth = batching.get("pipeline_depth")
            wall = pipeline.get("wall_s")
            fetch = pipeline.get("fetch_block_s")
            fetch_frac = None
            if isinstance(wall, (int, float)) and wall > 0 \
                    and isinstance(fetch, (int, float)):
                fetch_frac = float(fetch) / float(wall)
            spec = batching.get("spec") or {}
            # draft tier: the engine's provider default plus the MODEL
            # provider's acceptance EWMA (batching.spec.draft) — what
            # the policy's draft_mode demote rule watches
            draft = spec.get("draft") or {}
            dprov = ((draft.get("providers") or {}).get("model") or {})
            # long-context tier (batching.long_context): re-online
            # stall seconds over engine-busy wall (pipeline.wall_s) is
            # the thrash signal the max_logical_ctx retune watches;
            # absent block -> all None
            lc = batching.get("long_context") or {}
            stall = lc.get("stall_s")
            stall_frac = None
            if isinstance(wall, (int, float)) and wall > 0 \
                    and isinstance(stall, (int, float)):
                stall_frac = float(stall) / float(wall)
            views.append(ReplicaView(
                name=name, role=role, routable=routable, managed=managed,
                outstanding=int(outstanding),
                pipeline_depth=int(depth) if isinstance(depth, int) else None,
                overlap_ratio=pipeline.get("overlap_ratio"),
                fetch_frac=fetch_frac,
                spec_k=spec.get("k"),
                acceptance=spec.get("acceptance_rate"),
                draft_mode=spec.get("draft_mode"),
                draft_acceptance=dprov.get("acceptance_ewma"),
                offload_stall_frac=stall_frac,
                prefetch_hit_rate=lc.get("prefetch_hit_rate"),
                max_logical_ctx=lc.get("max_logical_ctx"),
                compiled_window=lc.get("window"),
                boot_logical_ctx=lc.get("boot_logical_ctx"),
            ))
        return Snapshot(
            t=round(float(t), 3),
            replicas=tuple(views),
            queue_wait_p99_ms={
                cls: w.get("p99_ms") for cls, w in qw.items()
                if isinstance(w, dict) and w.get("p99_ms") is not None},
            util=dict(disagg.get("util") or {}),
            ship_ms_ewma=float(disagg.get("ship_ms_ewma") or 0.0),
            ships=int(disagg.get("ships") or 0),
            ship_window=int(getattr(self.router, "ship_window", 0)),
            can_spawn=self.spawner is not None,
        )

    # -- one tick -----------------------------------------------------------

    def tick(self) -> list[Action]:
        """Scrape -> decide -> act (or log intents). Safe to call
        directly (the bench and tests do); the background thread just
        calls it on a timer."""
        self.stats.count("ticks")
        try:
            snap = self.build_snapshot(self.router.metrics())
        except Exception:  # noqa: BLE001 — a failed scrape skips the tick
            self.stats.count("errors")
            log_event(log, "controller scrape failed")
            return []
        actions = decide(snap, self.state, self.config)
        rendered = [a.render() for a in actions]
        with self._lock:
            self.decision_log.append((snap, rendered))
            del self.decision_log[:-_LOG_CAP]
        if actions:
            self.stats.record_decision({
                "t": snap.t,
                "p99_ms": dict(snap.queue_wait_p99_ms),
                "util": {k: round(v, 4) for k, v in sorted(
                    snap.util.items())},
                "actions": rendered,
                "applied": not self.dry_run,
            })
        for a in actions:
            if self.dry_run:
                self.stats.record_action(a.kind, applied=False)
                log_event(log, "controller intent (dry run)",
                          action=a.render())
                continue
            self._apply(a, snap)
        return actions

    def _apply(self, a: Action, snap: Snapshot) -> None:
        try:
            detail = self._act(a)
        except Exception as e:  # noqa: BLE001 — one failed actuation
            #                     must not kill the loop; the next tick
            #                     sees the unchanged fleet and re-decides
            self.stats.count("errors")
            self.stats.record_action(a.kind, applied=False)
            log_event(log, "controller action failed", action=a.render(),
                      error=str(e))
            return
        if detail is None:  # actuator unavailable: intent, not action
            self.stats.record_action(a.kind, applied=False)
            log_event(log, "controller intent (no actuator)",
                      action=a.render())
            return
        self.stats.record_action(a.kind, applied=True)
        target = detail if a.kind == SPAWN else a.target
        spec = f" {a.knob}={a.value}" if a.kind == SET_KNOB else ""
        with self._lock:
            self.events.append({
                "t": snap.t, "action": a.kind, "target": target,
                "event": f"@{snap.t:.1f} {a.kind} {target}{spec}",
            })
            del self.events[:-_LOG_CAP]
        log_event(log, "controller action", action=a.render(),
                  target=target)

    def _act(self, a: Action) -> str | None:
        """Run one actuator; returns a detail string on success, None
        when the actuator is not available (counted as an intent)."""
        if a.kind in (PROMOTE, DEMOTE):
            self.pool.set_role(a.target, a.role or MIXED)
            return a.role or MIXED
        if a.kind == SPAWN:
            if self.spawner is None:
                return None
            return str(self.spawner(a.role or MIXED))
        if a.kind == RETIRE:
            self.pool.retire(a.target)
            return a.target
        if a.kind == SET_KNOB:
            if a.target == ROUTER:
                if a.knob != "ship_window":
                    return None
                self.router.ship_window = int(a.value)
                self.stats.set_targets(ship_window=int(a.value))
                return str(a.value)
            with self.pool._lock:
                r = self.pool.replicas.get(a.target)
                url = r.url if r is not None else None
            if url is None:
                return None
            out = _http_json(f"{url}/v1/debug/knobs",
                             {a.knob: a.value}, timeout=self.knob_timeout)
            if not out.get("ok"):
                raise RuntimeError(
                    f"knob refused: {out.get('error', out)}")
            return str(a.value)
        return None

    # -- loop lifecycle -----------------------------------------------------

    def start(self) -> "FleetController":
        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — the loop never dies
                    self.stats.count("errors")

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="fleet-controller")
        self._thread.start()
        log_event(log, "controller started", interval_s=self.interval_s,
                  dry_run=self.dry_run,
                  slo_p99_ms=self.config.slo_p99_ms)
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- observability ------------------------------------------------------

    def replay_decisions(self) -> bool:
        """Determinism self-check: re-run the pure policy over the
        recorded snapshots with a FRESH state and compare the rendered
        actions byte-for-byte. True means the live trace is exactly
        reproducible from its inputs."""
        with self._lock:
            logged = list(self.decision_log)
        state = PolicyState()
        for snap, rendered in logged:
            again = [a.render() for a in decide(snap, state, self.config)]
            if again != rendered:
                return False
        return True

    def report(self) -> dict:
        out = self.stats.report()
        with self._lock:
            events = [dict(e) for e in self.events[-64:]]
        out["dry_run"] = self.dry_run
        out["events"] = events
        return out
