"""Paged KV engine (runtime/pagepool.py + the continuous engine's
block-table dispatch): bitwise parity with the dense path, zero-copy
prefix hits, and page lifecycle under traffic.

The acceptance bar mirrors PRs 2/3/5: the dense contiguous engine is
the reference and paged outputs must equal it BITWISE — greedy and
seeded-sampled, cold rows and prefix hits, streamed and not, at
pipeline depths 1 and 2, under concurrent traffic."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from lambdipy_tpu.models.llama import init_page_arena, page_kv_bytes
from lambdipy_tpu.runtime.continuous import ContinuousBatcher
from lambdipy_tpu.runtime.pagepool import PagePool, page_width
from lambdipy_tpu.runtime.prefixstore import PrefixStore

# tiny_server: the session-scoped shared LlamaServer from conftest.py


def mk_paged(server, *, slots=4, segment=8, n_windows=None, depth=1,
             block=16, **kw):
    cfg = server.model.cfg
    page = page_width(cfg.max_len, block)
    n_pages = (n_windows or slots) * (cfg.max_len // page) + 1
    pool = PagePool(n_pages=n_pages, page=page,
                    page_bytes=page_kv_bytes(cfg, page),
                    make_arena=lambda: init_page_arena(cfg, n_pages,
                                                       page))
    eng = ContinuousBatcher(server, slots=slots, segment=segment,
                            pipeline_depth=depth, page_pool=pool, **kw)
    return eng, pool


def drain(eng):
    with eng._lock:
        while eng._engine_running:
            eng._lock.wait(0.05)


# -- bitwise parity -----------------------------------------------------------


@pytest.mark.parametrize("depth", [1, 2])
def test_concurrent_paged_matches_solo(tiny_server, depth):
    """Staggered concurrent greedy requests through the paged engine are
    bitwise their solo outputs, rows actually fuse, and every page
    returns to the pool at idle."""
    eng, pool = mk_paged(tiny_server, slots=8, depth=depth)
    prompts = [[1 + i, 2 + i, 3 + i, 5] for i in range(8)]
    solo = [tiny_server.generate(p, max_new_tokens=16) for p in prompts]
    results = [None] * 8

    def run(i):
        time.sleep(0.02 * i)
        results[i] = eng.generate(prompts[i], max_new_tokens=16)

    with ThreadPoolExecutor(max_workers=8) as ex:
        list(ex.map(run, range(8)))
    for i in range(8):
        np.testing.assert_array_equal(results[i], solo[i],
                                      err_msg=f"request {i} diverged")
    if eng.stats()["rows_in_segments"] <= eng.stats()["segments_run"]:
        # heavy machine load can serialize the staggered arrivals so no
        # rows overlapped; an all-at-once burst on the same engine fuses
        # deterministically (admissions outpace the first prefill) — the
        # cumulative counters then prove paged rows really share steps
        with ThreadPoolExecutor(max_workers=8) as ex:
            outs = list(ex.map(
                lambda p: eng.generate(p, max_new_tokens=16), prompts))
        for out, ref in zip(outs, solo):
            np.testing.assert_array_equal(out, ref)
    stats = eng.stats()
    assert stats["rows_in_segments"] > stats["segments_run"], stats
    drain(eng)
    pool.check_invariants()
    st = pool.stats()
    assert st["pages_free"] == st["pages_total"], st
    assert st["alloc_pages"] > 0 and st["release_pages"] == st["alloc_pages"]


def test_sampled_rows_match_solo(tiny_server):
    """Seeded-sampled paged rows reproduce their solo chains exactly
    while sharing the batch with greedy traffic."""
    eng, pool = mk_paged(tiny_server)
    kw = dict(temperature=0.9, top_k=24, seed=13)
    row_s, row_g = [3, 1, 4, 1, 5], [2, 7, 1, 8]
    solo_s = tiny_server.generate(row_s, max_new_tokens=12, **kw)
    solo_g = tiny_server.generate(row_g, max_new_tokens=12)
    with ThreadPoolExecutor(max_workers=2) as ex:
        f1 = ex.submit(eng.generate, row_s, max_new_tokens=12, **kw)
        f2 = ex.submit(eng.generate, row_g, max_new_tokens=12)
        np.testing.assert_array_equal(f1.result(), solo_s)
        np.testing.assert_array_equal(f2.result(), solo_g)
    drain(eng)
    pool.check_invariants()


def test_streamed_paged_matches_nonstreamed(tiny_server):
    eng, pool = mk_paged(tiny_server)
    row = [6, 5, 4, 3]
    solo = tiny_server.generate(row, max_new_tokens=16)
    chunks = list(eng.generate_stream(row, max_new_tokens=16))
    np.testing.assert_array_equal(
        np.concatenate(chunks, axis=1)[:, :16], solo)
    drain(eng)
    assert pool.stats()["pages_free"] == pool.stats()["pages_total"]


def test_solo_prefill_pack_path(tiny_server):
    """group_prefill_max=0 forces the request-thread prefill: the dense
    1-row carry scatters into the joiner's pages bitwise."""
    eng, pool = mk_paged(tiny_server, group_prefill_max=0)
    rows = [[9, 8, 7, 6, 5], [1, 2, 3]]
    solo = [tiny_server.generate(r, max_new_tokens=12) for r in rows]
    with ThreadPoolExecutor(max_workers=2) as ex:
        outs = list(ex.map(
            lambda r: eng.generate(r, max_new_tokens=12), rows))
    for o, s in zip(outs, solo):
        np.testing.assert_array_equal(o, s)
    drain(eng)
    pool.check_invariants()


def test_window_bucketing_off_matches(tiny_server):
    eng, pool = mk_paged(tiny_server, window_bucketing=False)
    row = [4, 4, 2, 1]
    solo = tiny_server.generate(row, max_new_tokens=16)
    np.testing.assert_array_equal(
        eng.generate(row, max_new_tokens=16), solo)
    drain(eng)


def test_eos_truncation_matches(tiny_server):
    """Host-side eos latch parity rides the paged path unchanged."""
    free = tiny_server.generate([5, 6, 7, 8], max_new_tokens=12)[0]
    eos = int(free[3])
    solo = tiny_server.generate([5, 6, 7, 8], max_new_tokens=12,
                                eos_id=eos)
    eng, pool = mk_paged(tiny_server)
    np.testing.assert_array_equal(
        eng.generate([5, 6, 7, 8], max_new_tokens=12, eos_id=eos), solo)
    drain(eng)
    assert pool.stats()["pages_free"] == pool.stats()["pages_total"]


# -- zero-copy prefix hits ----------------------------------------------------


def make_paged_prefix(server, eng, pool, block=16):
    store = PrefixStore(server, budget_mb=64, pool=pool)
    eng.prefix_pages_fn = store.acquire_pages
    return store


def test_prefix_hit_is_zero_copy_and_bitwise(tiny_server):
    """The tentpole claim end to end: a radix hit on the paged engine
    costs refcount bumps (observed > 1 on the live pool), performs NO
    assembly (assembly_bytes_peak stays 0), and the routed outputs are
    bitwise the unrouted solo ones — cold walk and hits alike."""
    eng, pool = mk_paged(tiny_server, slots=4)
    store = make_paged_prefix(tiny_server, eng, pool)
    shared = list(range(1, 33))                     # 2 x 16-token blocks
    prompts = [shared + [50 + i, 60 + i, 70 + i] for i in range(4)]
    solo = [tiny_server.generate(p, max_new_tokens=12) for p in prompts]

    def routed(i):
        row = prompts[i]
        m = store.route(row)
        assert m == 32
        return eng.generate(np.asarray(row[m:], np.int32),
                            max_new_tokens=12,
                            prefix=np.asarray(row[:m], np.int32))

    np.testing.assert_array_equal(routed(0), solo[0])   # cold walk
    max_ref = 1
    done = []

    def burst():
        with ThreadPoolExecutor(max_workers=3) as ex:
            done.extend(ex.map(routed, range(1, 4)))

    t = threading.Thread(target=burst)
    t.start()
    while t.is_alive():
        max_ref = max(max_ref, pool.stats()["max_refcount"])
        time.sleep(0.001)
    t.join()
    for o, s in zip(done, solo[1:]):
        np.testing.assert_array_equal(o, s)

    st = store.stats()
    assert st["paged"] and st["hits"] == 3 and st["blocks"] == 2
    assert st["assemblies"] == 0 and st["assembly_bytes_peak"] == 0
    ps = pool.stats()
    assert ps["shares"] >= 8        # 2 pages x (3 hits + cold acquire)
    drain(eng)
    pool.check_invariants()
    # idle: only the store's 2 prefix pages stay live, everything else
    # returned to the free list
    ps = pool.stats()
    assert ps["pages_live"] == 2 and ps["refcount_histogram"] == {"1": 2}
    if max_ref <= 1:
        # polling may miss the decode window on a fast machine — prove
        # sharing deterministically: store ref + an explicit acquire
        acq = store.acquire_pages(shared)
        assert acq is not None and acq[1] == 32
        assert pool.stats()["max_refcount"] == 2
        pool.release(acq[0])
    else:
        assert max_ref > 1


def test_concurrent_cold_burst_dedups_without_double_free(tiny_server):
    """Regression (caught by the serve drive): N concurrent COLD
    requests for the same prefix collapse to one walk via the inflight
    dedup — the waiter threads must NOT strip the store's page refs on
    their re-match (that freed live pages under the store and corrupted
    later admissions). All outputs bitwise, invariants hold, a second
    wave hits the now-cached pages, and at idle only the store's refs
    remain."""
    eng, pool = mk_paged(tiny_server, slots=4)
    store = make_paged_prefix(tiny_server, eng, pool)
    shared = list(range(61, 93))                    # 2 x 16-token blocks
    prompts = [shared + [10 + i, 20 + i] for i in range(4)]
    solo = [tiny_server.generate(p, max_new_tokens=10) for p in prompts]

    def routed(i):
        row = prompts[i]
        m = store.route(row)
        assert m == 32
        return eng.generate(np.asarray(row[m:], np.int32),
                            max_new_tokens=10,
                            prefix=np.asarray(row[:m], np.int32))

    with ThreadPoolExecutor(max_workers=4) as ex:
        outs = list(ex.map(routed, range(4)))
    for o, s in zip(outs, solo):
        np.testing.assert_array_equal(o, s)
    pool.check_invariants()
    st = store.stats()
    # arrival timing decides how many of the 4 raced the cold walk vs
    # matched after it, but dedup means exactly ONE walk inserted blocks
    assert st["hits"] + st["misses"] == 4 and st["blocks"] == 2, st
    # second wave: the cached pages serve as hits now
    with ThreadPoolExecutor(max_workers=4) as ex:
        outs = list(ex.map(routed, range(4)))
    for o, s in zip(outs, solo):
        np.testing.assert_array_equal(o, s)
    assert store.stats()["hits"] >= 4
    drain(eng)
    pool.check_invariants()
    ps = pool.stats()
    assert ps["pages_live"] == 2 and ps["refcount_histogram"] == {"1": 2}


def test_prefix_hit_sampled_and_streamed(tiny_server):
    eng, pool = mk_paged(tiny_server)
    store = make_paged_prefix(tiny_server, eng, pool)
    shared = list(range(101, 117))                  # one block
    row = shared + [7, 8, 9]
    kw = dict(temperature=0.7, top_k=16, seed=5)
    solo_s = tiny_server.generate(row, max_new_tokens=10, **kw)
    solo_g = tiny_server.generate(row, max_new_tokens=10)
    m = store.route(row)
    assert m == 16
    pfx, suf = np.asarray(row[:m], np.int32), np.asarray(row[m:], np.int32)
    np.testing.assert_array_equal(
        eng.generate(suf, max_new_tokens=10, prefix=pfx, **kw), solo_s)
    chunks = list(eng.generate_stream(suf, max_new_tokens=10, prefix=pfx))
    np.testing.assert_array_equal(
        np.concatenate(chunks, axis=1)[:, :10], solo_g)
    assert store.stats()["assembly_bytes_peak"] == 0
    drain(eng)
    pool.check_invariants()


def test_acquire_pages_unknown_prefix_falls_back(tiny_server):
    """An explicit client prefix that never walked the paged tree (or
    was evicted) serves through the dense fallback — acquire returns
    None, the engine declines, and the request still completes with
    parity through server.generate."""
    eng, pool = mk_paged(tiny_server)
    store = make_paged_prefix(tiny_server, eng, pool)
    prefix = list(range(1, 17))
    row = prefix + [2, 3]
    assert store.acquire_pages(prefix) is None
    solo = tiny_server.generate(row, max_new_tokens=8)
    out = eng.generate(np.asarray(row[16:], np.int32), max_new_tokens=8,
                       prefix=np.asarray(prefix, np.int32))
    np.testing.assert_array_equal(out, solo)


def test_refcount_aware_eviction(tiny_server):
    """The LRU sweep only releases pages the store alone holds: a page a
    live acquisition shares survives the sweep; releasing the share
    makes it evictable."""
    eng, pool = mk_paged(tiny_server)
    store = make_paged_prefix(tiny_server, eng, pool)
    rowA = list(range(1, 17)) + [99]
    rowB = list(range(201, 217)) + [98]
    assert store.route(rowA) == 16
    assert store.route(rowB) == 16
    acq = store.acquire_pages(rowA[:16])
    assert acq is not None
    # squeeze the budget to zero: only B's (unshared) page may release
    store.budget_bytes = 0
    with store._lock:
        store._evict_locked()
    assert store.acquire_pages(rowB[:16]) is None       # evicted
    held = store.acquire_pages(rowA[:16])                # survived
    assert held is not None
    pool.release(held[0])
    pool.release(acq[0])
    # now A is unshared -> the sweep can release it
    with store._lock:
        store._evict_locked()
    assert store.acquire_pages(rowA[:16]) is None
    pool.check_invariants()
    assert pool.stats()["pages_live"] == 0


def test_paged_prefix_row_replays_bitwise_after_engine_failure(
        tiny_server):
    """Fault isolation composes with paged prefixes: an engine failure
    mid-decode resets the arena (on an async backend the published
    arena may be the failed computation's own output) and the replayed
    prefix-hit row transparently re-prefills as a FULL cold row through
    its kept pages — the caller still sees its bitwise solo output. The
    store's tree flushes on the generation bump, so afterwards the
    arena drains to fully free and a re-route walks cold again."""
    from lambdipy_tpu.runtime.faults import FaultPlan

    eng, pool = mk_paged(tiny_server, slots=4, segment=4)
    eng.faults = FaultPlan.from_spec("segment_fetch:exception@seg=1")
    store = make_paged_prefix(tiny_server, eng, pool)
    shared = list(range(1, 33))
    row = shared + [41, 42, 43]
    solo = tiny_server.generate(row, max_new_tokens=12)
    m = store.route(row)
    assert m == 32
    gen0 = pool.arena_generation
    out = eng.generate(np.asarray(row[m:], np.int32), max_new_tokens=12,
                       prefix=np.asarray(row[:m], np.int32))
    np.testing.assert_array_equal(out, solo)
    faults = eng.stats()["faults"]
    assert faults["failures"].get("segment_fetch") == 1
    assert faults["replays"]["succeeded"] >= 1
    assert pool.arena_generation > gen0        # failure reset the arena
    assert store.stats()["assembly_bytes_peak"] == 0
    drain(eng)
    pool.check_invariants()
    # the store flushed its stale pages and the replayed row released
    # its own: nothing stays live
    assert store.acquire_pages(shared) is None
    assert pool.stats()["pages_live"] == 0, pool.stats()
    # the store serves again against the fresh arena, bitwise
    assert store.route(row) == 32
    out2 = eng.generate(np.asarray(row[m:], np.int32),
                        max_new_tokens=12,
                        prefix=np.asarray(row[:m], np.int32))
    np.testing.assert_array_equal(out2, solo)
    drain(eng)
    pool.check_invariants()


@pytest.mark.slow  # bundle build + boot (~25 s); the engine/store logic
# is covered non-slow above — this is the kv_paged wiring proof
def test_handler_wires_kv_paged(tmp_path):
    """End-to-end through the generate handler: ``kv_paged`` builds the
    pool, the continuous engine and the prefix store share it (hits via
    acquire_pages), /metrics exports ``batching.page_pool``, the
    response is bitwise the unrouted multi-row reference, and
    ``assembly_bytes_peak`` stays 0."""
    from lambdipy_tpu.runtime.loader import load_bundle

    from tests.test_runtime import make_model_bundle

    bundle = make_model_bundle(
        tmp_path, model="llama-tiny",
        handler="lambdipy_tpu.runtime.handlers:generate_handler",
        extra={"max_new_tokens": "8", "batch_mode": "continuous",
               "batch_max": "2", "kv_paged": "1", "prefix_block": "16",
               "prefix_cache_mb": "8"})
    r = load_bundle(bundle, warmup=True)
    assert r.state.meta["kv_paged"] is True
    assert r.state.meta["prefix_cache"] is True
    row = list(range(1, 44))
    ref = r.state.invoke({"tokens": [row, row]})   # unrouted reference
    first = r.state.invoke({"tokens": row})
    second = r.state.invoke({"tokens": row})
    assert first["ok"] and second["ok"]
    assert first["prefix_cached"] and second["prefix_cached"]
    assert first["tokens"][0] == ref["tokens"][0]
    assert second["tokens"] == first["tokens"]
    st = r.state.stats()
    pp = st["batching"]["page_pool"]
    assert pp["pages_total"] > 0 and pp["shares"] > 0, pp
    pc = st["prefix_cache"]
    assert pc["paged"] and pc["hits"] >= 1
    assert pc["assembly_bytes_peak"] == 0 and pc["assemblies"] == 0


def test_admission_reclaims_cold_store_pages(tiny_server):
    """A cache must never starve admission: when the free list is short
    the pool's reclaim hook releases the store's cold UNSHARED pages,
    so the admission that would have shed serves instead — while pages
    a live acquisition shares survive the reclaim."""
    cfg = tiny_server.model.cfg
    page = page_width(cfg.max_len, 16)
    # room for the store's 2 prefix blocks + 2 pages of slack: an
    # admission needing 3 pages MUST reclaim store pages to fit
    pool = PagePool(n_pages=5, page=page,
                    page_bytes=page_kv_bytes(cfg, page),
                    make_arena=lambda: init_page_arena(cfg, 5, page))
    eng = ContinuousBatcher(tiny_server, slots=2, segment=8,
                            page_pool=pool)
    store = make_paged_prefix(tiny_server, eng, pool)
    rowA = list(range(1, 17)) + [99, 98]
    assert store.route(rowA) == 16          # store holds 1 page
    rowB = list(range(201, 217)) + [77, 76]
    assert store.route(rowB) == 16          # store holds 2 pages
    assert pool.free_count() == 2
    cold = [5, 4, 3]
    solo = tiny_server.generate(cold, max_new_tokens=30)
    # 3 + 30 tokens -> 3 pages: sheds unless a store page reclaims.
    # Pin A's page first: only B's (colder or not, unshared) may go...
    held = store.acquire_pages(rowA[:16])
    assert held is not None
    out = eng.generate(cold, max_new_tokens=30)
    np.testing.assert_array_equal(out, solo)
    pool.check_invariants()
    st = store.stats()
    assert st["evictions"] >= 1, st
    # the PINNED page survived the reclaim (still live and shared);
    # the unshared one was the victim
    assert pool.refcount(held[0][0]) >= 2, pool.stats()
    assert store.acquire_pages(rowB[:16]) is None
    pool.release(held[0])


def test_page_pool_on_metrics_surface(tiny_server):
    """engine.stats() exports the pool under ``page_pool`` (the
    ``batching.page_pool`` block on /metrics) with the gauges the issue
    names: totals, sharing, fragmentation, capacity rows, counters."""
    eng, pool = mk_paged(tiny_server)
    eng.generate([1, 2, 3], max_new_tokens=8)
    st = eng.stats()["page_pool"]
    for key in ("pages_total", "pages_free", "pages_shared",
                "internal_fragmentation", "refcount_histogram",
                "capacity_rows_now", "window_bound_rows", "allocs",
                "releases", "shares", "sheds", "retry_after_s"):
        assert key in st, key
    assert st["pages_total"] == pool.capacity_pages
