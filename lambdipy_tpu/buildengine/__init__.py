"""Build engine: recipe -> pruned, smoke-tested bundle tree.

The reference's build path runs recipes inside an Amazon-Linux docker
container (SURVEY.md §3.1 #5). No docker exists here (SURVEY.md §8), so the
engine reproduces the *procedure* of the JAX TPU image build (SURVEY.md
§3.4: venv + pinned installs + post-build manifest) locally:

- ``vendor`` backend: copy installed distributions out of the host env via
  their RECORD file lists (the offline equivalent of ``pip install`` into
  the build tree),
- ``sdist`` backend: build a wheel from a local source tree (``python -m
  build --no-isolation``) and unpack it into the bundle,
- prune pass with the XLA/PJRT whitelist (SURVEY.md §3.3),
- hermetic import-smoke in a fresh interpreter (SURVEY.md §5: "build ->
  install into clean env -> import + smoke" is the integration loop).
"""

from lambdipy_tpu.buildengine.engine import BuildError, BuildResult, build_recipe
from lambdipy_tpu.buildengine.prune import PruneReport, prune_tree, XLA_WHITELIST
from lambdipy_tpu.buildengine.vendor import import_names, vendor_distribution
from lambdipy_tpu.buildengine.smoke import import_smoke

__all__ = [
    "BuildError",
    "BuildResult",
    "build_recipe",
    "PruneReport",
    "prune_tree",
    "XLA_WHITELIST",
    "import_names",
    "vendor_distribution",
    "import_smoke",
]
